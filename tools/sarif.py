"""Minimal SARIF 2.1.0 serialization shared by rxgblint and rxgbverify.

One writer so both static-analysis layers surface as code-review
annotations with the same shape: a single run, the rule catalog under
``tool.driver.rules``, and one result per open finding with a physical
location. Only the subset of SARIF that annotation consumers (GitHub code
scanning et al.) actually read is emitted; the golden-file test pins it.
"""

import json
from typing import Dict, List, Sequence

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def sarif_doc(
    tool_name: str,
    rules: Dict[str, str],
    results: Sequence[Dict[str, object]],
) -> Dict[str, object]:
    """Build the SARIF document dict.

    ``results`` entries carry ``rule`` (id), ``message``, ``path`` (repo-
    relative posix uri), ``line`` (1-based; clamped up from 0), and an
    optional ``level`` (default "error" — both tools gate CI, so an open
    finding is never informational).
    """
    rule_ids = sorted(rules)
    index = {rid: i for i, rid in enumerate(rule_ids)}
    out_results: List[Dict[str, object]] = []
    for r in results:
        rid = str(r["rule"])
        res: Dict[str, object] = {
            "ruleId": rid,
            "level": str(r.get("level", "error")),
            "message": {"text": str(r["message"])},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": str(r["path"])},
                        "region": {"startLine": max(int(r.get("line", 1)), 1)},
                    }
                }
            ],
        }
        if rid in index:
            res["ruleIndex"] = index[rid]
        out_results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": tool_name,
                        # the url setup.py declares for THIS package (the
                        # reference project's repo would send annotation
                        # readers to the wrong codebase)
                        "informationUri": (
                            "https://github.com/example/xgboost_ray_tpu"
                        ),
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {"text": rules[rid]},
                            }
                            for rid in rule_ids
                        ],
                    }
                },
                "results": out_results,
            }
        ],
    }


def to_sarif_json(
    tool_name: str,
    rules: Dict[str, str],
    results: Sequence[Dict[str, object]],
) -> str:
    return json.dumps(
        sarif_doc(tool_name, rules, results), indent=2, sort_keys=True
    )
