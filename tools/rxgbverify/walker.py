"""Jaxpr walker: collective schedules, dtype census, drift fingerprints.

Operates on the ``ClosedJaxpr`` of an abstractly re-traced program (from
``progreg.ProgramRecord.jaxpr()``), recursing into every sub-jaxpr a
primitive carries (``pjit``/``scan``/``while``/``cond``/``shard_map``/
custom-derivative calls) purely by duck typing — anything in an eqn's
params that walks like a jaxpr (has ``eqns``, possibly behind a ``.jaxpr``
attribute) is walked. No jax-internal imports, so the walker survives
module reshuffles across jax versions.
"""

import dataclasses
import hashlib
from typing import Any, FrozenSet, Iterator, List, Tuple

#: primitives that communicate across mesh axes — the ordered sequence of
#: these IS the program's collective schedule (the thing that must match
#: across every rank, and across the world sizes elastic can interleave)
COLLECTIVE_PRIMS = frozenset(
    {"psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
     "reduce_scatter", "pbroadcast"}
)


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective eqn: primitive, axis names, payload aval, context."""

    prim: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    path: str  # nesting chain, e.g. "/shard_map/scan"
    in_cond: bool  # under a lax.cond branch (divergence hazard)

    def identity(self) -> tuple:
        """World-size-invariant identity: a shrink/grow recompile may change
        shard extents but never the primitive, axes, dtype, or rank."""
        return (self.prim, self.axes, self.dtype, len(self.shape))

    def describe(self) -> str:
        dims = "x".join(str(d) for d in self.shape)
        return f"{self.prim}@{','.join(self.axes)}:{self.dtype}[{dims}]{self.path}"


@dataclasses.dataclass
class ProgramAnalysis:
    collectives: List[Collective]
    dtypes: FrozenSet[str]

    def schedule(self) -> Tuple[tuple, ...]:
        return tuple(c.identity() for c in self.collectives)


def _open_jaxpr(obj):
    """The open ``Jaxpr`` behind ``obj`` (ClosedJaxpr or Jaxpr), else None."""
    inner = getattr(obj, "jaxpr", obj)
    return inner if hasattr(inner, "eqns") and hasattr(inner, "invars") else None


def _sub_jaxprs(eqn) -> Iterator[tuple]:
    """Yield ``(open_jaxpr, param_key, index)`` for every sub-jaxpr in the
    eqn's params, in deterministic (sorted-key, positional) order."""
    for key in sorted(eqn.params):
        val = eqn.params[key]
        items = val if isinstance(val, (tuple, list)) else (val,)
        for i, item in enumerate(items):
            sub = _open_jaxpr(item)
            if sub is not None:
                yield sub, key, i


def _axis_names(eqn) -> Tuple[str, ...]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name"))
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    # positional (int) axes are intra-shard reductions, not mesh axes
    return tuple(str(a) for a in ax if isinstance(a, str))


def _payload_aval(eqn):
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            return aval
    return None


def analyze(closed_jaxpr) -> ProgramAnalysis:
    """Walk the whole (nested) program once; return schedule + dtype census."""
    collectives: List[Collective] = []
    dtypes = set()

    def rec(open_j, path: str, in_cond: bool) -> None:
        for eqn in open_j.eqns:
            name = eqn.primitive.name
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "dtype"):
                    dtypes.add(str(aval.dtype))
            if name in COLLECTIVE_PRIMS:
                aval = _payload_aval(eqn)
                collectives.append(Collective(
                    prim=name,
                    axes=_axis_names(eqn),
                    shape=tuple(aval.shape) if aval is not None else (),
                    dtype=str(aval.dtype) if aval is not None else "?",
                    path=path,
                    in_cond=in_cond,
                ))
            for sub, key, _i in _sub_jaxprs(eqn):
                rec(
                    sub,
                    f"{path}/{name}",
                    in_cond or (name == "cond" and key == "branches"),
                )

    rec(closed_jaxpr.jaxpr, "", False)
    return ProgramAnalysis(collectives=collectives, dtypes=frozenset(dtypes))


# ---------------------------------------------------------------------------
# Recompile-drift fingerprints
# ---------------------------------------------------------------------------

def _aval_str(aval) -> str:
    if aval is None or not hasattr(aval, "shape"):
        return "?"
    return f"{aval.dtype}[{'x'.join(str(d) for d in aval.shape)}]"


def _canon_param(val) -> str:
    """Deterministic rendering of a non-jaxpr eqn param. Sets are sorted
    (their repr order is salted), callables reduced to their name, and long
    reprs hashed — the fingerprint must be stable across processes."""
    if isinstance(val, (frozenset, set)):
        return "{" + ",".join(sorted(repr(v) for v in val)) + "}"
    if callable(val) and not isinstance(val, type):
        return f"<fn:{getattr(val, '__name__', type(val).__name__)}>"
    try:
        r = repr(val)
    except Exception:  # pragma: no cover - exotic param types
        r = f"<{type(val).__name__}>"
    if len(r) > 256:
        r = f"sha256:{hashlib.sha256(r.encode()).hexdigest()[:16]}"
    return r


def _canon_lines(open_j, out: List[str], path: str) -> None:
    out.append(
        f"{path} in:" + ",".join(_aval_str(getattr(v, "aval", None))
                                 for v in open_j.invars)
    )
    for eqn in open_j.eqns:
        name = eqn.primitive.name
        subs = list(_sub_jaxprs(eqn))
        sub_keys = {key for _s, key, _i in subs}
        parts = []
        for key in sorted(eqn.params):
            if key in sub_keys:
                n = sum(1 for _s, k, _i in subs if k == key)
                parts.append(f"{key}=<jaxpr*{n}>")
            else:
                parts.append(f"{key}={_canon_param(eqn.params[key])}")
        ins = ",".join(_aval_str(getattr(v, "aval", None)) for v in eqn.invars)
        outs = ",".join(_aval_str(getattr(v, "aval", None)) for v in eqn.outvars)
        out.append(f"{path} {name}[{' '.join(parts)}] ({ins})->({outs})")
        for i, (sub, key, idx) in enumerate(subs):
            _canon_lines(sub, out, f"{path}/{name}.{key}.{idx}")
    out.append(
        f"{path} out:" + ",".join(_aval_str(getattr(v, "aval", None))
                                  for v in open_j.outvars)
    )


def fingerprint(closed_jaxpr, donate_argnums: Tuple[int, ...] = ()) -> str:
    """Stable hash of (jaxpr structure, avals, params, donation): the
    recompile-drift certificate. A PR that changes a compiled program's
    shapes, collective count, or donation shows up as a fingerprint diff."""
    lines: List[str] = []
    _canon_lines(closed_jaxpr.jaxpr, lines, "")
    lines.append(f"donate={tuple(donate_argnums)}")
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Traced program: registry record + its abstract re-trace
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TracedProgram:
    """One registry record re-traced abstractly (or the failure to)."""

    record: Any  # progreg.ProgramRecord
    closed_jaxpr: Any = None
    analysis: ProgramAnalysis = None
    fingerprint: str = ""
    error: str = ""

    @property
    def ok(self) -> bool:
        return not self.error

    def key(self) -> str:
        """Stable artifact key: name + sorted meta coordinates + a short
        input-signature hash (several records can share a name+meta at
        different shapes, e.g. the per-chunk predict programs)."""
        meta = "|".join(f"{k}={v}" for k, v in sorted(self.record.meta.items()))
        sig = hashlib.sha256(
            repr(self.record.signature()).encode()
        ).hexdigest()[:8]
        parts = [self.record.name, meta, f"in={sig}"]
        return "|".join(p for p in parts if p)


def trace_record(record) -> TracedProgram:
    """Abstractly re-trace one registry record (no compile, no execution)."""
    try:
        closed = record.jaxpr()
    except Exception as exc:  # trace failure is itself a finding (TRACE)
        return TracedProgram(record=record, error=f"{type(exc).__name__}: {exc}")
    return TracedProgram(
        record=record,
        closed_jaxpr=closed,
        analysis=analyze(closed),
        fingerprint=fingerprint(closed, record.donate_argnums),
    )
