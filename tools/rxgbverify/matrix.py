"""Config-matrix tracer: build every engine variant, register, re-trace.

Runs on the virtual CPU mesh (``JAX_PLATFORMS=cpu`` + 8 host-platform
devices — the CLI forces this before jax imports). Engines are built over a
tiny synthetic dataset; round programs are REGISTERED but never compiled or
executed (``build_programs`` + ``jax.jit``'s laziness), then each registry
record is re-traced abstractly. The only executed programs are the binning
sketches that run inside engine construction and the 2-round training that
mints the booster the serve predictor traces against.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from tools.rxgbverify import walker

#: shared training defaults: small enough to trace fast, deep enough that
#: every level of the grower (and the quantized allreduce at min_bytes=0)
#: appears in the jaxpr
_BASE_PARAMS = {
    "objective": "binary:logistic",
    "max_depth": 3,
    "eval_metric": ["logloss"],
}

_ROWS = 64
_FEATURES = 5


@dataclasses.dataclass(frozen=True)
class MatrixEntry:
    label: str
    overrides: Dict[str, object]
    worlds: Tuple[int, ...]
    # streamed ingestion: the engine is built from chunked shard streams
    # (multi-chunk, so the real streamed branch runs — single-chunk loads
    # degrade to the materialized path by design). Streamed rows register
    # round programs under meta ingest="streamed"; VER001 treats "ingest"
    # like "world" and certifies the streamed schedule identical to the
    # materialized row's.
    streamed: bool = False
    # vmapped-K HPO lanes: lanes > 0 enables the lane axis on the engine
    # (enable_lanes with an eta-varying pack) so the registered round program
    # is engine.step_vmapped with meta k=lanes. VER001 keys groups on k, so
    # each K certifies its own cross-world schedule identity.
    lanes: int = 0


#: the full CI matrix: grower x hist_quant(none/int8/int16) x sampling x
#: world 2/4/8. Cross-world rows (len(worlds) > 1) feed VER001; the
#: quantized rows feed VER004. hist_quant_min_bytes=0 because the synthetic
#: histograms are tiny — without it the f32 fallback would hide the wire.
FULL_MATRIX: Tuple[MatrixEntry, ...] = (
    MatrixEntry("depthwise-f32", {}, (2, 4, 8)),
    MatrixEntry(
        "depthwise-int8",
        {"hist_quant": "int8", "hist_quant_min_bytes": 0},
        (2, 4, 8),
    ),
    MatrixEntry(
        "depthwise-int16",
        {"hist_quant": "int16", "hist_quant_min_bytes": 0},
        (4, 8),
    ),
    # block-scaled wire (EQuARX schedule): VER004 asserts NO absmax pmax
    # pre-pass, narrow ppermute hops + narrow all_gather, no row-scale
    # all_to_all; VER001 certifies the ring PATTERN across worlds (hop
    # count collapses — it is a function of the axis size, see
    # checks._canonical_schedule)
    MatrixEntry(
        "depthwise-int8block",
        {"hist_quant": "int8_block", "hist_quant_min_bytes": 0},
        (2, 4, 8),
    ),
    MatrixEntry(
        "depthwise-int16block",
        {"hist_quant": "int16_block", "hist_quant_min_bytes": 0},
        (4, 8),
    ),
    MatrixEntry(
        "lossguide",
        {"grow_policy": "lossguide", "max_leaves": 8},
        (2, 4),
    ),
    MatrixEntry("dart", {"booster": "dart"}, (4,)),
    MatrixEntry("subsample", {"subsample": 0.5}, (2, 4)),
    MatrixEntry(
        "goss",
        {"subsample": 0.5, "sampling_method": "gradient_based"},
        (2, 4),
    ),
    MatrixEntry(
        "goss-int8",
        {"subsample": 0.5, "sampling_method": "gradient_based",
         "hist_quant": "int8", "hist_quant_min_bytes": 0},
        (4,),
    ),
    # end-to-end quantized gradients (gh_precision): the on-chip half of
    # the low-precision story. These rows feed the VER004 gh-precision
    # sub-checks (narrow gh aval present, exact int32 histogram wire, no
    # f32 upcast before accumulation) and VER001 across worlds.
    MatrixEntry("depthwise-int8gh", {"gh_precision": "int8"}, (2, 4, 8)),
    MatrixEntry("depthwise-int16gh", {"gh_precision": "int16"}, (4,)),
    MatrixEntry(
        # int8 gh x int8 wire: the composition case — integer accumulation
        # feeding the quantized collective without a f32 round-trip
        "depthwise-int8gh-int8wire",
        {"gh_precision": "int8", "hist_quant": "int8",
         "hist_quant_min_bytes": 0},
        (2, 4),
    ),
    MatrixEntry(
        # int8 gh x int8 BLOCK wire: the int32 quantized-domain histogram
        # must enter the ring via one exact f32 view (never a full-rank f32
        # psum round-trip) — the composition VER004's block half pins
        "depthwise-int8gh-int8block",
        {"gh_precision": "int8", "hist_quant": "int8_block",
         "hist_quant_min_bytes": 0},
        (2, 4),
    ),
    MatrixEntry(
        "lossguide-int8gh",
        {"grow_policy": "lossguide", "max_leaves": 8,
         "gh_precision": "int8"},
        (2,),
    ),
    MatrixEntry(
        # GOSS's amplified compaction dequantizes its small buffer (the
        # documented exception VER004's gh checks carve out)
        "goss-int8gh",
        {"subsample": 0.5, "sampling_method": "gradient_based",
         "gh_precision": "int8"},
        (4,),
    ),
    MatrixEntry(
        "uniform-int8gh",
        {"subsample": 0.5, "gh_precision": "int8"},
        (4,),
    ),
    # 2D row x feature mesh: worlds here are the ROW extent R; each engine
    # takes R x 2 of the 8 virtual devices ((2,2) and (4,2)). The two-world
    # row feeds VER001 with feature_parallel=2 meta, pinning the 2D
    # collective schedule (histogram psums on the actors axis, the tiny
    # election all_gather + bin-column psums on the features axis) across
    # coexisting row worlds the same way the 1D quantized schedule is
    # pinned.
    # world 3 is the SHRUNKEN-WORLD row: an elastic shrink of the (4, 2)
    # mesh rebuilds as (3, 2) with feature tiles fixed, so the odd row
    # extent must trace the identical collective schedule as its siblings
    # (VER001 cross-world identity = the deadlock-freedom certificate for
    # the shrunken 2D meshes the zero-replay continuation compiles).
    MatrixEntry("depthwise-2d", {"feature_parallel": 2}, (2, 3, 4)),
    MatrixEntry(
        "depthwise-2d-int8",
        {"feature_parallel": 2, "hist_quant": "int8",
         "hist_quant_min_bytes": 0},
        (4,),
    ),
    MatrixEntry(
        # 2D mesh x block wire: the ring runs on the actors axis over the
        # F/C local tile; the min_bytes global-payload rescale must keep
        # the block path engaged exactly as on (R, 1)
        "depthwise-2d-int8block",
        {"feature_parallel": 2, "hist_quant": "int8_block",
         "hist_quant_min_bytes": 0},
        (4,),
    ),
    MatrixEntry(
        "lossguide-2d",
        {"feature_parallel": 2, "grow_policy": "lossguide", "max_leaves": 8},
        (2,),
    ),
    MatrixEntry(
        # 2D row x feature mesh under quantized gh: histogram psums stay
        # int32 on the actors axis; the feature axis still carries only the
        # tiny election/broadcast traffic. World 3 pins the shrunken-world
        # composition (int8 gh x 2D after an elastic shrink).
        "depthwise-2d-int8gh",
        {"feature_parallel": 2, "gh_precision": "int8"},
        (2, 3, 4),
    ),
    # streamed ingestion (stream/): the rows-born-binned data plane. The
    # round steps must trace the EXACT materialized schedules (VER001
    # groups them with the rows above via the ingest variant axis), and the
    # streamed cuts merge registers under the same engine.sketch_cuts name
    # — pinning pmin/pmax/psum shape identity with the materialized sketch.
    MatrixEntry("depthwise-streamed", {}, (2, 4), streamed=True),
    MatrixEntry(
        # composition: quantized gh over a streamed (pre-binned) matrix
        "depthwise-streamed-int8gh", {"gh_precision": "int8"}, (4,),
        streamed=True,
    ),
    # vmapped-K HPO lanes (engine.step_vmapped): K boosters in one program.
    # ``k`` registers as a program-meta coordinate, so VER001 groups each K
    # separately and certifies the per-lane-batched collective schedule
    # (every collective's rank is +1, the schedule itself is unchanged)
    # identical across coexisting worlds. Lanes vary eta per slot — the
    # lane-vectorizable axis — while the program statics stay shared.
    MatrixEntry("depthwise-k2", {}, (2, 4), lanes=2),
    MatrixEntry("depthwise-k4", {}, (4,), lanes=4),
    MatrixEntry(
        "lossguide-k2",
        {"grow_policy": "lossguide", "max_leaves": 8},
        (2,), lanes=2,
    ),
    MatrixEntry(
        # composition: quantized gh plane under the lane vmap — VER004's
        # narrow-aval and int32-accumulation checks apply to the batched
        # [K, ...] histogram wire unchanged
        "depthwise-k2-int8gh", {"gh_precision": "int8"}, (4,), lanes=2,
    ),
)

#: tier-1 test subset: the two keystone rows (plain + quantized) at two
#: worlds — enough to exercise VER001 grouping and VER004 end to end while
#: keeping the test under the fast-tier budget
QUICK_MATRIX: Tuple[MatrixEntry, ...] = (
    MatrixEntry("depthwise-f32", {}, (2, 4)),
    MatrixEntry(
        "depthwise-int8",
        {"hist_quant": "int8", "hist_quant_min_bytes": 0},
        (2, 4),
    ),
    # block-scaled wire at one world: the fast tier pins the no-pre-pass
    # ring schedule (VER004 block half) end to end; cross-world pattern
    # identity for the ring rides on the FULL matrix (CLI gate) and the
    # planted-program VER001 ring-collapse unit test
    MatrixEntry(
        "depthwise-int8block",
        {"hist_quant": "int8_block", "hist_quant_min_bytes": 0},
        (2,),
    ),
    # quantized gradients: the gh-plane analog of the quantized wire —
    # exercises the VER004 gh sub-checks in the fast tier
    MatrixEntry("depthwise-int8gh", {"gh_precision": "int8"}, (2, 4)),
    # streamed ingestion at the keystone config: VER001 certifies the
    # streamed world's collective schedule (round steps AND the sketch
    # merge) is identical to the materialized depthwise-f32 rows above
    MatrixEntry("depthwise-streamed", {}, (2, 4), streamed=True),
    # vmapped-K lanes at the keystone config: certifies the lane-batched
    # schedule (engine.step_vmapped, meta k=2) across worlds in the fast tier
    MatrixEntry("depthwise-k2", {}, (2, 4), lanes=2),
)

_GBLINEAR_WORLDS = (2, 4)
_SERVE_WORLD = 4


def _dataset():
    import numpy as np

    rng = np.random.RandomState(7)
    x = rng.rand(_ROWS, _FEATURES).astype(np.float32)
    y = (rng.rand(_ROWS) > 0.5).astype(np.float32)
    return [{"data": x, "label": y}]


def trace_matrix(
    quick: bool = False,
    entries: Optional[Sequence[MatrixEntry]] = None,
) -> List[walker.TracedProgram]:
    """Build the matrix's engines under progreg capture and re-trace every
    registered program. Returns one TracedProgram per registry record."""
    import jax

    from xgboost_ray_tpu import progreg
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.linear import LinearEngine
    from xgboost_ray_tpu.params import parse_params

    if entries is None:
        entries = QUICK_MATRIX if quick else FULL_MATRIX
    shards = _dataset()
    booster = None
    if not quick:
        # mint the serve predictor's booster OUTSIDE capture: its training
        # engine's programs are not part of the matrix and must not pollute
        # the registry (this 2-round depth-2 train is the matrix's only
        # compiled/executed round program)
        params = parse_params({**_BASE_PARAMS, "max_depth": 2})
        train_eng = TpuEngine(shards, params, num_actors=_SERVE_WORLD)
        for i in range(2):
            train_eng.step(i)
        booster = train_eng.get_booster()
    engines = []  # keep alive: records hold the engines' traceable closures
    with progreg.capture():
        progreg.clear()
        for entry in entries:
            for world in entry.worlds:
                params = parse_params({**_BASE_PARAMS, **entry.overrides})
                if entry.streamed:
                    from xgboost_ray_tpu.stream.reader import (
                        array_shard_stream,
                    )

                    entry_shards = [array_shard_stream(
                        shards[0]["data"], label=shards[0]["label"],
                        chunk_rows=_ROWS // 4,
                    )]
                else:
                    entry_shards = shards
                if entry.lanes:
                    from xgboost_ray_tpu.params import vectorize_params

                    etas = (0.3, 0.1, 0.05, 0.025)[:entry.lanes]
                    lp = vectorize_params([
                        {**_BASE_PARAMS, **entry.overrides,
                         "learning_rate": eta}
                        for eta in etas
                    ])
                    eng = TpuEngine(entry_shards, lp.base, num_actors=world)
                    eng.enable_lanes(lp)
                else:
                    eng = TpuEngine(entry_shards, params, num_actors=world)
                eng.build_programs()
                engines.append(eng)
        if not quick:
            for world in _GBLINEAR_WORLDS:
                params = parse_params(
                    {**_BASE_PARAMS, "booster": "gblinear"}
                )
                lin = LinearEngine(shards, params, num_actors=world)
                lin.build_programs()
                engines.append(lin)
            from xgboost_ray_tpu.serve.predictor import CompiledPredictor

            pred = CompiledPredictor(
                booster, devices=jax.devices()[:_SERVE_WORLD]
            )
            pred.register_programs(kinds=("margin", "leaf", "contribs"))
            engines.append(pred)
            # the FIL-style breadth-first layout compiles its own margin and
            # leaf programs (meta layout=node_array → distinct verify
            # groups); contribs routes to the heap program registered above
            pred_na = CompiledPredictor(
                booster, devices=jax.devices()[:_SERVE_WORLD],
                layout="node_array",
            )
            pred_na.register_programs(kinds=("margin", "leaf"))
            engines.append(pred_na)
        traced = [walker.trace_record(r) for r in progreg.records()]
    return traced
