"""rxgbverify: jaxpr-level SPMD schedule / precision-flow / drift verifier.

The second static-analysis layer (after the source-AST ``tools/rxgblint``):
re-traces every compiled program the package registers with
``xgboost_ray_tpu.progreg`` into its ``ClosedJaxpr`` — abstractly, on CPU,
no execution — and checks the properties AST analysis cannot see:

* the ordered collective schedule is identical across every world size the
  elastic engine-cache can interleave (VER001 — deadlock-freedom
  certificate for zero-replay shrink/grow),
* no collective hides inside a ``lax.cond`` branch (VER002),
* collective axis names resolve against the same mesh-axis catalog
  rxgblint's SPMD002 uses (VER003),
* the hist_quant int8/int16 payload reaches the wire un-upcast and the f32
  fallback psum of the full histogram is gone (VER004), no f64 anywhere
  (VER005), and every donated buffer is actually aliasable (VER006),
* a stable per-program fingerprint of (jaxpr structure, avals, donation),
  recorded to a JSON artifact and into BENCH snapshots so silent program
  drift shows up as a reviewable diff.

CLI: ``python -m tools.rxgbverify [--json F] [--sarif F] [--fingerprints F]``
— traces the full config matrix (growers x hist_quant x sampling x world
2/4/8) and exits non-zero on any finding.
"""

from tools.rxgbverify.checks import VERIFY_RULES, Finding, run_checks  # noqa: F401
from tools.rxgbverify.walker import (  # noqa: F401
    Collective,
    TracedProgram,
    analyze,
    fingerprint,
    trace_record,
)


def fingerprint_registry():
    """Fingerprint every program currently in the progreg registry —
    ``{program key: fingerprint}`` (or a ``trace-error:`` marker). This is
    the mapping bench.py embeds in every BENCH snapshot."""
    from xgboost_ray_tpu import progreg

    out = {}
    for rec in progreg.records():
        t = trace_record(rec)
        out[t.key()] = t.fingerprint if t.ok else f"trace-error: {t.error}"
    return out
