"""CLI: ``python -m tools.rxgbverify [--json F] [--sarif F] [--fingerprints F]``.

Traces the config matrix on a hermetic 8-device virtual CPU mesh and runs
every VER* check. Exit status mirrors rxgblint: 0 = clean, 1 = findings,
2 = usage error.
"""

import argparse
import json
import os
import sys


def _force_cpu_mesh() -> None:
    """Hermetic virtual CPU mesh (same trick as tests/conftest.py): must run
    BEFORE the first jax import. If jax is already imported (in-process test
    invocation under conftest) the environment is trusted as-is."""
    if "jax" in sys.modules:
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    for name in list(_xb._backend_factories):
        if name != "cpu":
            _xb._backend_factories.pop(name, None)


def _program_entry(t) -> dict:
    rec = t.record
    entry = {
        "name": rec.name,
        "meta": dict(rec.meta),
        "donate_argnums": list(rec.donate_argnums),
        "registrations": rec.registrations,
    }
    if t.ok:
        entry["fingerprint"] = t.fingerprint
        entry["collectives"] = [c.describe() for c in t.analysis.collectives]
    else:
        entry["error"] = t.error
    return entry


def main(argv=None) -> int:
    from tools.rxgbverify.checks import VERIFY_RULES

    parser = argparse.ArgumentParser(
        prog="rxgbverify",
        description=(
            "jaxpr-level SPMD schedule / precision-flow / recompile-drift "
            "verifier for xgboost_ray_tpu"
        ),
    )
    parser.add_argument(
        "--json", metavar="FILE",
        help="write the machine-readable report (the CI artifact)",
    )
    parser.add_argument(
        "--sarif", metavar="FILE",
        help="write findings as SARIF 2.1.0 for code-review annotations",
    )
    parser.add_argument(
        "--fingerprints", metavar="FILE",
        help="write the {program: fingerprint} drift artifact",
    )
    parser.add_argument(
        "--quick", action="store_true",
        help="trace the reduced matrix (the tier-1 test subset) instead of "
             "the full grower x hist_quant x sampling x world grid",
    )
    parser.add_argument(
        "--list-checks", action="store_true", help="print the check catalog"
    )
    args = parser.parse_args(argv)

    if args.list_checks:
        for code in sorted(VERIFY_RULES):
            print(f"{code}: {VERIFY_RULES[code]}")
        return 0

    _force_cpu_mesh()
    from tools import sarif as sarif_mod
    from tools.rxgblint import catalog
    from tools.rxgbverify import checks as checks_mod
    from tools.rxgbverify.matrix import trace_matrix

    traced = trace_matrix(quick=args.quick)
    if not traced:
        print("rxgbverify: no programs registered — registry wiring broken",
              file=sys.stderr)
        return 2
    findings = checks_mod.run_checks(
        traced, catalog.mesh_axes(), root=catalog.REPO_ROOT
    )
    traced.sort(key=lambda t: t.key())
    programs = {t.key(): _program_entry(t) for t in traced}
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1

    # artifacts + exit status settle BEFORE stdout (a closed pipe must not
    # turn findings into a pass — same hardening as rxgblint)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(
                {
                    "tool": "rxgbverify",
                    "checks": VERIFY_RULES,
                    "quick": bool(args.quick),
                    "programs": programs,
                    "counts": counts,
                    "findings": [f.to_dict() for f in findings],
                },
                fh, indent=2, sort_keys=True,
            )
            fh.write("\n")
    if args.fingerprints:
        with open(args.fingerprints, "w") as fh:
            json.dump(
                {
                    "tool": "rxgbverify",
                    "programs": {
                        k: v.get("fingerprint", v.get("error", ""))
                        for k, v in programs.items()
                    },
                },
                fh, indent=2, sort_keys=True,
            )
            fh.write("\n")
    if args.sarif:
        with open(args.sarif, "w") as fh:
            fh.write(sarif_mod.to_sarif_json(
                "rxgbverify", VERIFY_RULES,
                [
                    # the annotation target is the registration site; the
                    # program key carries the config context
                    {**f.to_dict(), "message": f"{f.program}: {f.message}"}
                    for f in findings
                ],
            ) + "\n")
    status = 1 if findings else 0

    try:
        for f in findings:
            print(f.render())
        n_coll = sum(
            len(t.analysis.collectives) for t in traced if t.ok
        )
        print(
            f"rxgbverify: {len(traced)} programs traced, {n_coll} "
            f"collectives, {len(findings)} finding(s)"
        )
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return status


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(1)
