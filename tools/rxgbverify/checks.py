"""Verification passes over abstractly re-traced programs.

Every check consumes ``walker.TracedProgram`` lists — no execution, no
compilation. Rule codes are VER*, disjoint from rxgblint's AST rules so a
combined SARIF upload stays unambiguous.
"""

import dataclasses
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from tools.rxgbverify.walker import TracedProgram

#: rule code -> one-line description (printed by --list-checks, embedded in
#: the SARIF rule catalog, documented in README "Static analysis")
VERIFY_RULES: Dict[str, str] = {
    "VER001": (
        "collective schedule differs across coexisting world sizes: an "
        "elastic shrink/grow recompile would execute mismatched collective "
        "sequences — the torn-allreduce cluster hang"
    ),
    "VER002": (
        "collective inside a lax.cond branch: shard-divergent predicates "
        "make some ranks skip the collective (hang) — invisible to "
        "source-level SPMD001"
    ),
    "VER003": (
        "collective axis name not in the declared mesh-axis catalog "
        "(shared with rxgblint SPMD002)"
    ),
    "VER004": (
        "quantized precision-flow contract broken: a hist_quant int8/int16 "
        "payload is upcast before the wire collective (or the f32 fallback "
        "psum of the full histogram survives); a *_block program still runs "
        "the global absmax pmax pre-pass, a row-scale all_to_all, or a "
        "non-narrow ppermute ring; or a gh_precision program's gradient "
        "plane is upcast to f32 before histogram accumulation (narrow gh "
        "aval missing / f32 histogram psum instead of the exact int32 wire)"
    ),
    "VER005": (
        "float64 aval in a compiled program: TPU-hostile dtype, doubles "
        "collective bytes, breaks f32 determinism assumptions"
    ),
    "VER006": (
        "donated input buffer matches no output shape/dtype: the donation "
        "frees nothing and silently invalidates the caller's array"
    ),
    "TRACE": "program failed to re-trace abstractly from its registered signature",
}

#: program names subject to the quantized precision-flow pass (the round
#: steps that embed quantized_hist_allreduce)
_HIST_QUANT_PROGRAMS = (
    "engine.step", "engine.step_custom", "engine.step_many", "engine.step_dart",
    "engine.step_vmapped",
)

_NARROW = {"int8": "int8", "int16": "int16"}
#: block-scaled wire modes -> their narrow payload dtype (schedule: ppermute
#: ring + in-band-scale all_gather, NO absmax pre-pass, NO all_to_all)
_NARROW_BLOCK = {"int8_block": "int8", "int16_block": "int16"}
#: a block-mode program may legitimately contain TINY f32 pmaxes (the
#: gh_precision per-tree scale reduce is a [2]-element pmax, [k, 2] under
#: vmapped lanes); the deleted row-scale absmax pre-pass is a
#: [nodes*F]-element pmax — discriminate by payload element count
_BLOCK_PMAX_MAX_ELEMS = 8


@dataclasses.dataclass
class Finding:
    rule: str
    program: str  # TracedProgram.key()
    message: str
    path: str = ""  # registration-site file (repo-relative), for SARIF
    line: int = 1

    def render(self) -> str:
        return f"{self.program}: {self.rule} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "program": self.program,
            "message": self.message,
            "path": self.path,
            "line": self.line,
        }


def _rel(path: str, root: Optional[str]) -> str:
    if not root:
        return path.replace(os.sep, "/")
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:  # different drive
        return path.replace(os.sep, "/")
    return (path if rel.startswith("..") else rel).replace(os.sep, "/")


def _finding(t: TracedProgram, rule: str, message: str,
             root: Optional[str]) -> Finding:
    src_file, src_line = t.record.source
    return Finding(
        rule=rule,
        program=t.key(),
        message=message,
        path=_rel(src_file, root),
        line=src_line,
    )


#: meta coordinates that are schedule-identity VARIANTS, not group splits:
#: programs differing only in these must run the identical collective
#: sequence ("world" — the elastic shrink/grow contract; "ingest" — the
#: streamed data plane must not change any round-step program's schedule)
_VARIANT_KEYS = ("world", "ingest")


def _group_key(t: TracedProgram) -> tuple:
    """Cross-variant grouping: everything but the variant coordinates."""
    return (
        t.record.name,
        tuple(sorted(
            (k, v) for k, v in t.record.meta.items()
            if k not in _VARIANT_KEYS
        )),
    )


def _variant_key(t: TracedProgram) -> tuple:
    return tuple(
        (k, t.record.meta[k]) for k in _VARIANT_KEYS if k in t.record.meta
    )


def check_trace_failures(traced: Sequence[TracedProgram],
                         root: Optional[str] = None) -> List[Finding]:
    return [
        _finding(t, "TRACE", f"abstract re-trace failed: {t.error}", root)
        for t in traced if not t.ok
    ]


def check_schedule_identity(traced: Sequence[TracedProgram],
                            root: Optional[str] = None) -> List[Finding]:
    """VER001: programs that only differ in a VARIANT coordinate (``world``
    and/or ``ingest``) must run the identical (prim, axes, dtype, rank)
    collective sequence — the deadlock-freedom certificate for the elastic
    engine-cache's coexisting worlds, and the streamed data plane's
    round-step-identity certificate against the materialized world."""
    findings: List[Finding] = []
    groups: Dict[tuple, Dict[tuple, List[TracedProgram]]] = {}
    for t in traced:
        if not t.ok or "world" not in t.record.meta:
            continue
        groups.setdefault(_group_key(t), {}).setdefault(
            _variant_key(t), []
        ).append(t)
    for key, by_variant in sorted(groups.items()):
        if len(by_variant) < 2:
            continue
        variants = sorted(by_variant)
        # per variant: the sorted multiset of schedules (a name+meta can
        # have several records at different shapes, all collective-free or
        # alike)
        def sched_set(v):
            return sorted(
                _canonical_schedule(t.analysis.schedule())
                for t in by_variant[v]
            )

        def label(v):
            return ",".join(f"{k}={val}" for k, val in v)
        if all(not s for v in variants for s in sched_set(v)):
            # collective-free in every variant (e.g. the streamed upload
            # assembly concats): record COUNTS may differ per variant (one
            # per shape), but there is no schedule to diverge
            continue
        ref_v = variants[0]
        ref = sched_set(ref_v)
        for v in variants[1:]:
            cur = sched_set(v)
            if cur == ref:
                continue
            t = by_variant[v][0]
            detail = _first_divergence(ref, cur, label(ref_v), label(v))
            findings.append(_finding(
                t, "VER001",
                f"collective schedule at {label(v)} differs from "
                f"{label(ref_v)}: {detail}",
                root,
            ))
    return findings


def _canonical_schedule(sched: Tuple[tuple, ...]) -> Tuple[tuple, ...]:
    """Collapse runs of consecutive identical ``ppermute`` identities into
    one entry. The block-scale ring reduce-scatter traces ``world - 1``
    identical hops, so the hop COUNT is a deterministic function of the
    axis size itself (like a psum's payload extent), not a schedule
    divergence an elastic recompile could deadlock on — every rank of a
    world derives the same count from the same world size. The collapsed
    PATTERN (ring present, payload dtype, axis) is the deadlock-freedom
    certificate VER001 compares."""
    out: List[tuple] = []
    for c in sched:
        if out and out[-1] == c and c[0] == "ppermute":
            continue
        out.append(c)
    return tuple(out)


def _first_divergence(ref, cur, ref_label, cur_label) -> str:
    if len(ref) != len(cur):
        return f"{len(ref)} vs {len(cur)} program variants"
    for rs, cs in zip(ref, cur):
        if rs == cs:
            continue
        n = min(len(rs), len(cs))
        for i in range(n):
            if rs[i] != cs[i]:
                return (f"position {i}: {ref_label} runs {rs[i]}, "
                        f"{cur_label} runs {cs[i]}")
        return (f"length {len(rs)} ({ref_label}) vs {len(cs)} "
                f"({cur_label}) collectives")
    return "schedules differ"


def check_cond_collectives(traced: Sequence[TracedProgram],
                           root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for t in traced:
        if not t.ok:
            continue
        for c in t.analysis.collectives:
            if c.in_cond:
                findings.append(_finding(
                    t, "VER002",
                    f"{c.describe()} executes inside a cond branch",
                    root,
                ))
    return findings


def check_axis_names(traced: Sequence[TracedProgram],
                     mesh_axes: FrozenSet[str],
                     root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for t in traced:
        if not t.ok:
            continue
        for c in t.analysis.collectives:
            bad = [a for a in c.axes if a not in mesh_axes]
            if bad:
                findings.append(_finding(
                    t, "VER003",
                    f"{c.describe()} uses axis {bad} not in the mesh "
                    f"catalog {sorted(mesh_axes)}",
                    root,
                ))
    return findings


def check_precision_flow(traced: Sequence[TracedProgram],
                         root: Optional[str] = None) -> List[Finding]:
    """VER004: the two quantized-precision flows, end to end.

    * ``hist_quant`` (the WIRE): in an int8/int16 round program the
      histogram wire must stay narrow — a single
      ``convert_element_type -> f32`` before the ``all_to_all`` silently
      re-inflates every byte the mode was bought to save, and the f32
      fallback psum of the full [nodes, F, bins, 2] payload must be gone.
    * ``hist_quant`` block modes (``int8_block``/``int16_block``): the
      schedule contract is the EQuARX one — NO global absmax pmax pre-pass
      (the collective the mode was built to delete), a narrow ppermute ring
      present with every hop payload narrow, a narrow all_gather publish,
      no row-scale all_to_all reduce-scatter surviving, and no full-rank
      f32 histogram psum.
    * ``gh_precision`` (the PLANE): the gh buffer entering histogram build
      must BE int8/int16 (the narrow aval must appear in the program) and
      accumulation must stay integer — any histogram-rank psum in f32 means
      the plane was upcast before accumulation; with an unquantized wire
      the histogram psum must be the exact int32 reduction. GOSS programs
      (meta sampling == gradient_based) are exempt from the accumulation
      checks: their amplified compaction dequantizes the small sampled
      buffer by design (the narrow-aval requirement still applies — the
      full-N plane stays quantized).
    """
    findings: List[Finding] = []
    for t in traced:
        if not t.ok or t.record.name not in _HIST_QUANT_PROGRAMS:
            continue
        colls = t.analysis.collectives
        findings.extend(_gh_precision_findings(t, colls, root))
        wire = str(t.record.meta.get("hist_quant", "none"))
        block_narrow = _NARROW_BLOCK.get(wire)
        narrow = block_narrow or _NARROW.get(wire)
        if narrow is None:
            continue
        ag = [c for c in colls if c.prim == "all_gather"]
        a2a = [c for c in colls if c.prim == "all_to_all"]
        if block_narrow is not None:
            pps = [c for c in colls if c.prim == "ppermute"]
            if not pps:
                findings.append(_finding(
                    t, "VER004",
                    "no ppermute in a block-scaled program: the ring "
                    "reduce-scatter traced away (f32 fallback engaged, or "
                    "a row-scale schedule shipped under block meta?)",
                    root,
                ))
            for c in pps:
                if c.dtype != narrow:
                    findings.append(_finding(
                        t, "VER004",
                        f"ppermute hop payload is {c.dtype}, expected "
                        f"{narrow}: upcast before the wire ({c.describe()})",
                        root,
                    ))
            for c in a2a:
                findings.append(_finding(
                    t, "VER004",
                    f"row-scale all_to_all reduce-scatter survives in a "
                    f"block-scaled program ({c.describe()})",
                    root,
                ))
            for c in colls:
                if (
                    c.prim == "pmax"
                    and c.dtype == "float32"
                    and _elems(c.shape) > _BLOCK_PMAX_MAX_ELEMS
                ):
                    findings.append(_finding(
                        t, "VER004",
                        f"global absmax pmax pre-pass survives in a "
                        f"block-scaled program — the full-latency collective "
                        f"the mode deletes ({c.describe()})",
                        root,
                    ))
        else:
            if not a2a:
                findings.append(_finding(
                    t, "VER004",
                    "no all_to_all in a quantized-histogram program: the "
                    "reduce-scatter stage traced away (f32 fallback "
                    "engaged?)",
                    root,
                ))
            for c in a2a:
                if c.dtype != narrow:
                    findings.append(_finding(
                        t, "VER004",
                        f"all_to_all payload is {c.dtype}, expected "
                        f"{narrow}: upcast before the wire ({c.describe()})",
                        root,
                    ))
        if not any(c.dtype == narrow for c in ag):
            findings.append(_finding(
                t, "VER004",
                f"no {narrow} all_gather: the packed requantized gather "
                "stage is missing or upcast",
                root,
            ))
        for c in colls:
            if c.prim == "psum" and c.dtype == "float32" and len(c.shape) >= 4:
                findings.append(_finding(
                    t, "VER004",
                    f"full-rank f32 histogram psum survives in a {narrow} "
                    f"program ({c.describe()})",
                    root,
                ))
    return findings


def _elems(shape: tuple) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _gh_precision_findings(t: TracedProgram, colls,
                           root: Optional[str]) -> List[Finding]:
    """The gh_precision half of VER004 (see check_precision_flow)."""
    narrow = _NARROW.get(str(t.record.meta.get("gh_precision", "float32")))
    if narrow is None:
        return []
    findings: List[Finding] = []
    if narrow not in t.analysis.dtypes:
        findings.append(_finding(
            t, "VER004",
            f"no {narrow} aval anywhere in a gh_precision={narrow} program: "
            "the quantized gh plane traced away (upcast at the source?)",
            root,
        ))
    if str(t.record.meta.get("sampling")) == "gradient_based":
        # GOSS dequantizes its amplified compacted buffer by design; the
        # accumulation-dtype checks below do not apply
        return findings
    hist_psums = [c for c in colls if c.prim == "psum" and len(c.shape) >= 4]
    wire = str(t.record.meta.get("hist_quant", "none"))
    wire_narrow = _NARROW.get(wire) or _NARROW_BLOCK.get(wire)
    if wire_narrow is None:
        # with a narrow hist_quant wire (row- or block-scale) the
        # check_precision_flow loop already flags any surviving f32
        # histogram psum — reporting it here too would count one defect
        # twice
        for c in hist_psums:
            if c.dtype == "float32":
                findings.append(_finding(
                    t, "VER004",
                    f"f32 histogram psum in a gh_precision={narrow} "
                    f"program: the gh plane was upcast before accumulation "
                    f"({c.describe()})",
                    root,
                ))
    if wire == "none" and not any(c.dtype == "int32" for c in hist_psums):
        findings.append(_finding(
            t, "VER004",
            f"no int32 histogram psum in a gh_precision={narrow} program "
            "with an unquantized wire: the exact integer reduction is "
            "missing (accumulation not integer?)",
            root,
        ))
    return findings


def check_no_f64(traced: Sequence[TracedProgram],
                 root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for t in traced:
        if not t.ok:
            continue
        bad = sorted(d for d in t.analysis.dtypes
                     if d in ("float64", "complex128"))
        if bad:
            findings.append(_finding(
                t, "VER005", f"64-bit dtypes in program: {bad}", root,
            ))
    return findings


def check_donation(traced: Sequence[TracedProgram],
                   root: Optional[str] = None) -> List[Finding]:
    """VER006: every donated input aval must be matchable (shape+dtype) by
    some output aval, else XLA cannot alias it and the donation only
    poisons the caller's buffer."""
    import jax

    findings: List[Finding] = []
    for t in traced:
        if not t.ok or not t.record.donate_argnums:
            continue
        args = t.record.abstract_args
        out_pool: List[Tuple[tuple, str]] = [
            (tuple(a.shape), str(a.dtype)) for a in t.closed_jaxpr.out_avals
        ]
        for argnum in t.record.donate_argnums:
            if argnum >= len(args):
                findings.append(_finding(
                    t, "VER006",
                    f"donate_argnums={argnum} out of range for "
                    f"{len(args)} args",
                    root,
                ))
                continue
            flat, _ = jax.tree.flatten(args[argnum])
            for a in flat:
                sig = (tuple(a.shape), str(a.dtype))
                if sig in out_pool:
                    out_pool.remove(sig)  # each output aliases once
                else:
                    findings.append(_finding(
                        t, "VER006",
                        f"donated arg {argnum} aval "
                        f"{sig[1]}[{'x'.join(map(str, sig[0]))}] matches no "
                        f"output buffer: donation is unused",
                        root,
                    ))
    return findings


def run_checks(traced: Sequence[TracedProgram],
               mesh_axes: FrozenSet[str],
               root: Optional[str] = None) -> List[Finding]:
    """All passes, deterministic order."""
    findings: List[Finding] = []
    findings.extend(check_trace_failures(traced, root))
    findings.extend(check_schedule_identity(traced, root))
    findings.extend(check_cond_collectives(traced, root))
    findings.extend(check_axis_names(traced, mesh_axes, root))
    findings.extend(check_precision_flow(traced, root))
    findings.extend(check_no_f64(traced, root))
    findings.extend(check_donation(traced, root))
    findings.sort(key=lambda f: (f.program, f.rule, f.message))
    return findings
