"""Inline suppression pragmas.

Two forms, mirroring the linters this repo's contributors already know:

* same line::

      risky_call()  # rxgblint: disable=SPMD001
      risky_call()  # rxgblint: disable=SPMD001,DET001
      risky_call()  # rxgblint: disable=all

* previous line (for statements that don't fit a trailing comment)::

      # rxgblint: disable-next-line=LOCK001
      self._depth += 1

A pragma suppresses only the named rules (or every rule for ``all``) and
only on its target line. Suppressed findings still appear in ``--json``
output tagged ``"suppressed": "pragma"`` so finding counts stay diffable
across PRs.
"""

import io
import re
import tokenize
from typing import Dict, Set

# codes may be followed by a free-form justification: the recommended style
# is `# rxgblint: disable=DET001 - why this is fine here`
_PRAGMA_RE = re.compile(
    r"#\s*rxgblint:\s*(disable|disable-next-line)\s*=\s*"
    r"(all|[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


def collect(source: str) -> Dict[int, Set[str]]:
    """Map of 1-based line number -> set of disabled rule codes (the token
    ``"all"`` disables every rule on that line).

    Pragmas are recognized only in real COMMENT tokens — pragma-shaped text
    inside a string literal or docstring (e.g. a module documenting the
    pragma syntax) must never silently disable rules on its line."""
    disabled: Dict[int, Set[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            kind, codes_raw = m.group(1), m.group(2)
            codes = {c.strip() for c in codes_raw.split(",") if c.strip()}
            lineno = tok.start[0]
            target = lineno + 1 if kind == "disable-next-line" else lineno
            disabled.setdefault(target, set()).update(codes)
    except (tokenize.TokenError, IndentationError):
        # unparsable tail (callers lint only sources that already passed
        # ast.parse, so this is belt-and-braces); keep what we collected
        pass
    return disabled


def is_disabled(disabled: Dict[int, Set[str]], line: int, rule: str) -> bool:
    codes = disabled.get(line)
    if not codes:
        return False
    return "all" in codes or rule in codes
