"""Justified-baseline suppression file.

The baseline is the escape hatch for findings the team has *reviewed and
accepted* — typically structural false positives the AST rules cannot see
through (e.g. a helper whose dynamic span name is fed only by literal call
sites two lines below). Every entry MUST carry a one-line ``why``; loading
a baseline with a missing/empty justification is an error, so "suppress it
and move on" is never silent.

Entries key on ``(rule, path, scope)`` — not line numbers — so routine
edits to a file don't invalidate its baseline. Stale entries (matching no
current finding) are reported by the runner so the baseline shrinks as the
code improves.

Schema (JSON)::

    {"entries": [
        {"rule": "OBS001",
         "path": "xgboost_ray_tpu/engine.py",
         "scope": "TpuEngine.profile_phases.emit",
         "why": "one-line justification"}
    ]}
"""

import json
import os
from typing import Dict, List, Set, Tuple

from tools.rxgblint.findings import RULES, Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

Key = Tuple[str, str, str]


class BaselineError(ValueError):
    """The baseline file itself is malformed (bad rule, missing why)."""


def load(path: str) -> List[Dict[str, str]]:
    """Load + validate the baseline; returns the entry list ([] when the
    file does not exist)."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: 'entries' must be a list")
    for i, e in enumerate(entries):
        where = f"{path}: entry {i}"
        for req in ("rule", "path", "scope", "why"):
            if not isinstance(e.get(req), str) or not e.get(req, "").strip():
                raise BaselineError(
                    f"{where}: missing/empty {req!r} — every baseline entry "
                    f"needs a rule, a path, a scope, and a one-line "
                    f"justification"
                )
        if e["rule"] not in RULES:
            raise BaselineError(
                f"{where}: unknown rule {e['rule']!r}; one of {sorted(RULES)}"
            )
    return entries


def apply(findings: List[Finding], entries: List[Dict[str, str]]):
    """Mark findings matched by a baseline entry as suppressed.

    Returns ``(stale_entries, used)`` — entries that matched nothing (the
    runner reports them so the baseline shrinks over time), and the count
    of findings suppressed."""
    keys: Set[Key] = {(e["rule"], e["path"], e["scope"]) for e in entries}
    used: Set[Key] = set()
    n_suppressed = 0
    for f in findings:
        if f.suppressed:
            continue
        if f.key() in keys:
            f.suppressed = "baseline"
            used.add(f.key())
            # one scope-keyed entry may match several findings; the count
            # must track findings (what the --json diffing sums), not keys
            n_suppressed += 1
    stale = [
        e for e in entries if (e["rule"], e["path"], e["scope"]) not in used
    ]
    return stale, n_suppressed
