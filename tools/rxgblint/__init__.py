"""rxgblint: SPMD/determinism static analysis for xgboost_ray_tpu.

The runtime bets on invariants that nothing used to check: collectives must
execute uniformly on every rank (a rank-divergent ``psum`` is a silent
cluster hang), training must stay bitwise reproducible (every RNG routed
through ``params.seed`` + the ``SALT_*`` fold domains), shared state in the
threaded serve/obs layers must stay behind its lock, and the fault/trace
catalogs must match their call sites. rxgblint enforces all of it as a
tier-1 CI gate::

    python -m tools.rxgblint xgboost_ray_tpu            # human output
    python -m tools.rxgblint xgboost_ray_tpu --json out.json

Rules: SPMD001 SPMD002 DET001 SYNC001 LOCK001 FAULT001 OBS001 EXP001 — see
``tools/rxgblint/findings.py`` (or README "Static analysis") for the
catalog, pragma syntax (``# rxgblint: disable=RULE``) and the justified
baseline workflow (``tools/rxgblint/baseline.json``).

Stdlib-only, AST-based: never imports the package under analysis, so it
runs before jax is even installed.
"""

from tools.rxgblint.baseline import BaselineError
from tools.rxgblint.findings import RULES, Finding
from tools.rxgblint.runner import (
    lint_source,
    render_report,
    report_to_json,
    run_lint,
)

__all__ = [
    "RULES",
    "Finding",
    "BaselineError",
    "lint_source",
    "run_lint",
    "render_report",
    "report_to_json",
]
