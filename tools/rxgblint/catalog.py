"""Codebase-specific catalogs the rules check against.

Everything here is either declared in one place in the production code and
*extracted* at lint time (fault sites, SALT constants, trace names, mesh
axes) or is a policy list owned by the linter (required exports, collective
wrapper names). Extraction is AST-based — the linter never imports the
package under analysis, so it runs in a bare CPython with no jax installed.
"""

import ast
import functools
import os
import re
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

#: repository root = two levels above this file (tools/rxgblint/)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE = "xgboost_ray_tpu"

# ---------------------------------------------------------------------------
# SPMD: collectives and mesh axes
# ---------------------------------------------------------------------------

#: jax.lax collective primitives (terminal attribute names)
JAX_COLLECTIVES: FrozenSet[str] = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
})

#: repo-local wrapper callables that perform a collective internally; a call
#: to one of these under rank-dependent control flow is the same hang hazard
COLLECTIVE_WRAPPERS: FrozenSet[str] = frozenset({
    "allreduce", "tree_psum", "hist_ar", "counting_psum",
    "quantized_hist_allreduce",
})

#: identifier fragments that mark a value as rank-/shard-dependent when they
#: appear in a branch condition guarding a collective
RANK_TAINT_RE = re.compile(
    r"(^|_)(rank|ranks|process_index|proc_index|shard_id|worker_id|"
    r"host_id|device_id|axis_index|pid)($|_)"
)
RANK_TAINT_CALLS: FrozenSet[str] = frozenset({
    "process_index", "axis_index", "host_id", "process_count",
})


def _parse(path: str) -> ast.Module:
    with open(path, "r", encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


@functools.lru_cache(maxsize=None)
def axis_constants(root: str = REPO_ROOT) -> Tuple[Tuple[str, str], ...]:
    """``AXIS_*`` name -> value pairs declared in the package's constants
    module (``xgboost_ray_tpu/constants.py``) — the one source of truth the
    Mesh constructors, SPMD002, and rxgbverify's schedule checks all share.
    Sorted tuple-of-pairs (hashable for the lru caches downstream)."""
    path = os.path.join(root, PACKAGE, "constants.py")
    pairs = {}
    try:
        tree = _parse(path)
    except (OSError, SyntaxError):
        return ()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id.startswith("AXIS_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    pairs[tgt.id] = node.value.value
    return tuple(sorted(pairs.items()))


@functools.lru_cache(maxsize=None)
def mesh_axes(root: str = REPO_ROOT) -> FrozenSet[str]:
    """Mesh-axis catalog: every string inside a tuple passed to a ``Mesh``
    constructor anywhere in the package, with ``AXIS_*`` constant names
    resolved through :func:`axis_constants`. Falls back to {"actors"} (the
    engine's 1D row mesh) if extraction comes up empty."""
    axes: Set[str] = set()
    consts = dict(axis_constants(root))
    for path in _package_files(root):
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _callee_name(node) == "Mesh"):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for elt in arg.elts:
                        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                            axes.add(elt.value)
                        elif isinstance(elt, ast.Name) and elt.id in consts:
                            axes.add(consts[elt.id])
    return frozenset(axes) if axes else frozenset({"actors"})


# ---------------------------------------------------------------------------
# DET: the SALT_* fold domains
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def salt_values(root: str = REPO_ROOT) -> FrozenSet[int]:
    """Integer values of every module-level ``SALT_*`` assignment in the
    package (declared in ops/grow.py; the scheme every deterministic
    fold_in stream routes through)."""
    vals: Set[int] = set()
    for path in _package_files(root):
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and tgt.id.startswith("SALT_")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, int)
                    ):
                        vals.add(node.value.value)
    return frozenset(vals)


# ---------------------------------------------------------------------------
# FAULT: the fault-site catalog from faults.py
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def fault_sites(root: str = REPO_ROOT) -> Tuple[str, ...]:
    """The ``SITES`` tuple extracted from ``xgboost_ray_tpu/faults.py``."""
    path = os.path.join(root, PACKAGE, "faults.py")
    try:
        tree = _parse(path)
    except (OSError, SyntaxError):
        return ()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SITES":
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        return tuple(
                            elt.value
                            for elt in node.value.elts
                            if isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)
                        )
    return ()


# ---------------------------------------------------------------------------
# OBS: the trace-name catalog from obs/trace.py
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def trace_names(root: str = REPO_ROOT) -> FrozenSet[str]:
    """The ``TRACE_NAMES`` frozenset extracted from obs/trace.py — the one
    declared catalog of every span/event name the runtime may emit."""
    path = os.path.join(root, PACKAGE, "obs", "trace.py")
    try:
        tree = _parse(path)
    except (OSError, SyntaxError):
        return frozenset()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "TRACE_NAMES":
                    names: Set[str] = set()
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                            names.add(sub.value)
                    return frozenset(names)
    return frozenset()


#: valid span/event name shape (lowercase dotted identifiers — greppable,
#: Prometheus-label-safe, and guaranteed to pass validate_trace_records)
TRACE_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")

# ---------------------------------------------------------------------------
# EXP: required public exports of the top-level package
# ---------------------------------------------------------------------------

#: symbols that must appear in xgboost_ray_tpu/__init__.py __all__ —
#: the core API plus the public surfaces added by PRs 3-6
REQUIRED_EXPORTS: FrozenSet[str] = frozenset({
    "train", "predict", "RayParams", "RayDMatrix",
    "faults", "obs",
    "AsyncCheckpointWriter",          # PR 5
    "validate_trace_records",         # PR 6
    "recovery_time_s",                # PR 6 obs helper
})

# ---------------------------------------------------------------------------
# LOCK: the lock-owning-class catalog (shared with tools/rxgbrace)
# ---------------------------------------------------------------------------

#: threading primitive type names whose presence in an attribute's assigned
#: value (or annotation) marks the attribute as a lock
LOCK_TYPES: FrozenSet[str] = frozenset({"Lock", "RLock", "Condition"})


def _is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` -> "<attr>" (None otherwise)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mentions(node: ast.AST, idents: FrozenSet[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            tail = sub.attr if isinstance(sub, ast.Attribute) else sub.id
            if tail in idents:
                return True
    return False


def lock_attr_kinds(cls: ast.ClassDef) -> Dict[str, str]:
    """Lock-typed attributes of one class AST node, mapped to their kind
    (``condition`` | ``rlock`` | ``lock``). This is THE definition of
    "lock-owning class" — rxgblint's LOCK001 and rxgbrace's runtime
    instrumenter both key off it, so the two tools can never disagree on
    which classes own locks."""

    def _kind(node: ast.AST) -> Optional[str]:
        # Condition(threading.Lock()) mentions both; the outermost wins
        if _mentions(node, frozenset({"Condition"})):
            return "condition"
        if _mentions(node, frozenset({"RLock"})):
            return "rlock"
        if _mentions(node, frozenset({"Lock"})):
            return "lock"
        return None

    kinds: Dict[str, str] = {}
    for node in ast.walk(cls):
        target_attr = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            attr = _is_self_attr(tgt)
            if attr:
                target_attr, value = attr, node.value
            elif isinstance(tgt, ast.Name):  # class-body field
                target_attr, value = tgt.id, node.value
        elif isinstance(node, ast.AnnAssign):
            attr = _is_self_attr(node.target)
            if attr:
                target_attr = attr
            elif isinstance(node.target, ast.Name):
                target_attr = node.target.id
            value = node.value if node.value is not None else node.annotation
        if target_attr is None or value is None:
            continue
        kind = _kind(value)
        if kind is None and isinstance(node, ast.AnnAssign):
            # the annotation counts too: `_cond: threading.Condition = field()`
            kind = _kind(node.annotation)
        if kind is not None:
            kinds[target_attr] = kind
    return kinds


def shared_attrs_of_class(cls: ast.ClassDef, locks: FrozenSet[str]) -> FrozenSet[str]:
    """The class's shared-mutable attribute set: every ``self._x`` assigned
    inside a ``with self.<lock>`` block or inside a ``*_locked``
    (caller-holds-the-lock) method — the same definition LOCK001 guards and
    the attribute set rxgbrace's instrumenter records accesses to."""
    shared: Set[str] = set()

    def visit(node: ast.AST, holding: bool, fn_name: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_name = node.name
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if _is_self_attr(item.context_expr) in locks:
                    holding = True
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            attr = _is_self_attr(tgt)
            if (
                attr
                and attr.startswith("_")
                and attr not in locks
                and (holding or fn_name.endswith("_locked"))
            ):
                shared.add(attr)
        for child in ast.iter_child_nodes(node):
            visit(child, holding, fn_name)

    visit(cls, False, "")
    return frozenset(shared)


class LockClassRecord(NamedTuple):
    """One lock-owning class: where it lives, its locks, and the shared
    attribute set its locks guard."""

    path: str  # repo-relative posix path of the defining module
    module: str  # dotted import path (for runtime instrumentation)
    qualname: str  # class qualname within the module ("Outer.Inner" if nested)
    locks: Tuple[Tuple[str, str], ...]  # sorted (attr, kind) pairs
    shared: Tuple[str, ...]  # sorted shared-mutable attr names


@functools.lru_cache(maxsize=None)
def lock_owning_classes(root: str = REPO_ROOT) -> Tuple[LockClassRecord, ...]:
    """Every lock-owning class in the package, extracted by AST (the linter
    never imports the package). Public API consumed by rxgbrace's runtime
    instrumenter — one catalog, two tools."""
    records: List[LockClassRecord] = []

    def collect(body, prefix: str, rel: str, module: str) -> None:
        for node in body:
            if not isinstance(node, ast.ClassDef):
                continue
            qual = f"{prefix}{node.name}"
            kinds = lock_attr_kinds(node)
            if kinds:
                locks = frozenset(kinds)
                records.append(LockClassRecord(
                    path=rel,
                    module=module,
                    qualname=qual,
                    locks=tuple(sorted(kinds.items())),
                    shared=tuple(sorted(shared_attrs_of_class(node, locks))),
                ))
            collect(node.body, f"{qual}.", rel, module)

    for path in _package_files(root):
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        module = rel[:-3].replace("/", ".")
        collect(tree.body, "", rel, module)
    return tuple(sorted(records, key=lambda r: (r.path, r.qualname)))


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def _callee_name(call: ast.Call) -> str:
    """Terminal identifier of a call's callee: ``jax.lax.psum`` -> "psum"."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _package_files(root: str):
    pkg = os.path.join(root, PACKAGE)
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
