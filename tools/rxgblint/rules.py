"""The rxgblint rule implementations: one AST pass per module.

Scope notes (what the rules can and cannot see) — also documented in the
README rule catalog:

* **Traced-code detection** (DET001 time.*, SYNC001) is lexical: a function
  is "traced" when it is passed to a jax tracing entry point
  (``jit``/``shard_map``/``shard_map_compat``/``vmap``/``pmap``/``scan``/
  ``cond``/...) directly, by name within the same module, or via a ``jit``
  decorator — plus everything lexically nested inside such a function.
  Closures returned from one function and traced in another (the engine's
  ``_round_closures`` pattern) are NOT detected; the rules under-approximate
  rather than flood engine host code with false positives.
* **LOCK001** is lexical too: an access is "guarded" when it sits inside
  ``with self.<lock>`` in the same function. The repo's convention for
  caller-holds-the-lock helpers is a ``_locked`` name suffix (e.g.
  ``_percentile_locked``): such methods are exempt from the guard check,
  and in exchange every CALL to a ``*_locked`` method must itself sit
  inside a ``with self.<lock>`` block — the contract is enforced on both
  ends.
"""

import ast
from typing import Dict, List, Optional, Set

from tools.rxgblint import catalog
from tools.rxgblint.findings import Finding

# jax tracing entry points: a function passed into one of these executes
# under trace, where host-side effects are hazards
TRACER_CALLS = frozenset({
    "jit", "shard_map", "shard_map_compat", "vmap", "pmap", "scan",
    "while_loop", "fori_loop", "cond", "switch", "checkpoint", "remat",
    "grad", "value_and_grad", "custom_jvp", "custom_vjp",
})

# SPMD001 cares about communicating collectives only (axis_index is
# rank-divergence-safe); SPMD002 validates the axis arg of everything
SPMD001_CALLS = (catalog.JAX_COLLECTIVES - {"axis_index"}) | catalog.COLLECTIVE_WRAPPERS

_TIME_FNS = frozenset({
    "time", "perf_counter", "monotonic", "process_time", "time_ns",
    "perf_counter_ns", "monotonic_ns",
})
_PY_RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "seed", "getrandbits", "betavariate",
    "expovariate", "normalvariate",
})
_NP_RANDOM_OK = frozenset({
    "RandomState", "default_rng", "Generator", "SeedSequence", "PCG64",
    "Philox",
})
_SET_CONSUMERS = frozenset({
    "list", "tuple", "enumerate", "array", "asarray", "stack",
    "concatenate", "fromiter",
})
_SYNC_BUILTINS = frozenset({"float", "bool"})
_EXEMPT_METHODS = frozenset({"__init__", "__post_init__", "__new__", "__del__"})


def _terminal(node: ast.AST) -> str:
    """Terminal identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _chain(node: ast.AST) -> List[str]:
    """['np', 'random', 'rand'] for ``np.random.rand``; [] when dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


# shared with the lock-owning-class catalog (catalog.lock_owning_classes is
# the single definition rxgbrace's instrumenter reuses)
_is_self_attr = catalog._is_self_attr
_mentions = catalog._mentions


def _rank_tainted(cond: ast.AST) -> bool:
    """Does a branch condition depend on rank-/shard-identity?"""
    for sub in ast.walk(cond):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            ident = _terminal(sub)
            if ident and catalog.RANK_TAINT_RE.search(ident.lower()):
                return True
        if isinstance(sub, ast.Call) and _terminal(sub.func) in catalog.RANK_TAINT_CALLS:
            return True
    return False


class _Module:
    """Parsed module plus the derived maps every rule shares."""

    def __init__(self, source: str, path: str, root: str = catalog.REPO_ROOT):
        self.source = source
        self.path = path
        self.root = root
        self.tree = ast.parse(source, filename=path)
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.scopes = self._scope_map()
        self.traced = self._traced_functions()

    # -- scopes -------------------------------------------------------------

    def _scope_map(self) -> Dict[ast.AST, str]:
        """node -> dotted qualname of its enclosing class/function chain."""
        scopes: Dict[ast.AST, str] = {}

        def visit(node: ast.AST, stack: List[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    scopes[child] = ".".join(stack) if stack else "<module>"
                    visit(child, stack + [child.name])
                elif isinstance(child, ast.Lambda):
                    scopes[child] = ".".join(stack) if stack else "<module>"
                    visit(child, stack + ["<lambda>"])
                else:
                    visit(child, stack)

        visit(self.tree, [])
        return scopes

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the scope containing ``node``."""
        cur = node
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                base = self.scopes.get(cur, "<module>")
                name = getattr(cur, "name", "<lambda>")
                return name if base == "<module>" else f"{base}.{name}"
            cur = self.parent.get(cur)
        return "<module>"

    def nearest_function(self, node: ast.AST):
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = self.parent.get(cur)
        return None

    def nearest_named_function(self, node: ast.AST):
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parent.get(cur)
        return None

    # -- traced-function detection -----------------------------------------

    def _direct_defs(self, owner: ast.AST) -> List[ast.AST]:
        """FunctionDefs declared directly in ``owner``'s scope (descending
        into if/try/with blocks but not into nested functions/classes)."""
        defs: List[ast.AST] = []

        def visit(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.append(child)
                elif not isinstance(child, (ast.ClassDef, ast.Lambda)):
                    visit(child)

        visit(owner)
        return defs

    def _resolve_local_def(self, node: ast.AST, name: str):
        """The FunctionDef bound to ``name`` at ``node``, per lexical scoping
        (climbing enclosing functions up to the module; class bodies don't
        leak method names into nested scopes)."""
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
            ):
                for fn in self._direct_defs(cur):
                    if fn.name == name:
                        return fn
                if isinstance(cur, ast.Module):
                    return None
            elif isinstance(cur, ast.ClassDef):
                # method names are not visible as bare names from inside
                # other methods; skip past the class scope
                pass
            cur = self.parent.get(cur)
        return None

    def _traced_functions(self) -> Set[ast.AST]:
        traced: Set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _terminal(node.func) in TRACER_CALLS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        traced.add(arg)
                    elif isinstance(arg, ast.Name):
                        fn = self._resolve_local_def(node, arg.id)
                        if fn is not None:
                            traced.add(fn)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tail = _terminal(dec.func if isinstance(dec, ast.Call) else dec)
                    if tail == "jit" or (
                        isinstance(dec, ast.Call)
                        and tail == "partial"
                        and _mentions(dec, frozenset({"jit"}))
                    ):
                        traced.add(node)
        # lexical nesting: everything inside a traced function is traced
        out: Set[ast.AST] = set(traced)
        for fn in traced:
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    out.add(sub)
        return out

    def in_traced(self, node: ast.AST) -> bool:
        cur = self.parent.get(node)
        while cur is not None:
            if cur in self.traced:
                return True
            cur = self.parent.get(cur)
        return False

    # -- helpers ------------------------------------------------------------

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            scope=self.scope_of(node),
        )


# ---------------------------------------------------------------------------
# SPMD001 — collectives under rank-dependent Python control flow
# ---------------------------------------------------------------------------


def check_spmd001(mod: _Module) -> List[Finding]:
    findings = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _terminal(node.func) in SPMD001_CALLS):
            continue
        fn_boundary = mod.nearest_function(node)
        cur, prev = mod.parent.get(node), node
        while cur is not None and cur is not fn_boundary:
            cond = None
            if isinstance(cur, (ast.If, ast.While)):
                # only the guarded body/orelse diverges; the test itself runs
                # on every rank
                if prev is not cur.test:
                    cond = cur.test
            elif isinstance(cur, ast.IfExp) and prev is not cur.test:
                cond = cur.test
            if cond is not None and _rank_tainted(cond):
                findings.append(mod.finding(
                    "SPMD001", node,
                    f"collective {_terminal(node.func)!r} under rank-"
                    f"dependent control flow: ranks that skip this branch "
                    f"never join the collective (cluster hang); hoist the "
                    f"collective or use lax.cond/where",
                ))
                break
            prev, cur = cur, mod.parent.get(cur)
    return findings


# ---------------------------------------------------------------------------
# SPMD002 — collective axis names must come from the mesh-axis catalog
# ---------------------------------------------------------------------------


def check_spmd002(mod: _Module) -> List[Finding]:
    findings = []
    axes = catalog.mesh_axes(mod.root)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal(node.func)
        if name not in catalog.JAX_COLLECTIVES:
            continue
        # jax.lax collectives take the axis as the 2nd positional arg
        # (axis_index takes it as the 1st) or as axis_name=
        axis_arg = None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_arg = kw.value
        if axis_arg is None:
            pos = 0 if name == "axis_index" else 1
            if len(node.args) > pos:
                axis_arg = node.args[pos]
        if axis_arg is None:
            continue
        literals = []
        if isinstance(axis_arg, ast.Constant) and isinstance(axis_arg.value, str):
            literals = [axis_arg.value]
        elif isinstance(axis_arg, (ast.Tuple, ast.List)):
            literals = [
                e.value for e in axis_arg.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        elif isinstance(axis_arg, (ast.Name, ast.Attribute)):
            ident = _terminal(axis_arg)
            consts = dict(catalog.axis_constants(mod.root))
            if ident in consts:
                # declared AXIS_* constant: resolve to its value and
                # validate like a literal (one source of truth with the
                # Mesh constructors — see constants.py)
                if consts[ident] not in axes:
                    findings.append(mod.finding(
                        "SPMD002", node,
                        f"collective {name!r} axis constant {ident} "
                        f"resolves to {consts[ident]!r}, not a declared "
                        f"mesh axis: {sorted(axes)}",
                    ))
                continue
            if "axis" not in ident.lower():
                findings.append(mod.finding(
                    "SPMD002", node,
                    f"collective {name!r} axis comes from opaque variable "
                    f"{ident!r}; pass a literal from the mesh-axis catalog "
                    f"{sorted(axes)} or a parameter named axis_name",
                ))
            continue
        else:
            findings.append(mod.finding(
                "SPMD002", node,
                f"collective {name!r} axis is a computed expression; use a "
                f"literal from the mesh-axis catalog {sorted(axes)}",
            ))
            continue
        for lit in literals:
            if lit not in axes:
                findings.append(mod.finding(
                    "SPMD002", node,
                    f"collective {name!r} names unknown mesh axis {lit!r}; "
                    f"declared axes: {sorted(axes)}",
                ))
    return findings


# ---------------------------------------------------------------------------
# DET001 — nondeterminism sources
# ---------------------------------------------------------------------------


def check_det001(mod: _Module) -> List[Finding]:
    findings = []
    salts = catalog.salt_values(mod.root)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _chain(node.func)
        tail = _terminal(node.func)
        # (a) module-level RNGs: random.random() / np.random.rand()
        if chain[:1] == ["random"] and len(chain) == 2 and tail in _PY_RANDOM_FNS:
            findings.append(mod.finding(
                "DET001", node,
                f"module-level random.{tail}() draws from global unseeded "
                f"state; use a seeded random.Random(seed) instance",
            ))
        if (
            len(chain) == 3
            and chain[0] in ("np", "numpy")
            and chain[1] == "random"
            and tail not in _NP_RANDOM_OK
        ):
            findings.append(mod.finding(
                "DET001", node,
                f"np.random.{tail}() draws from global RNG state; use a "
                f"seeded np.random.RandomState/default_rng instance",
            ))
        # (b) wall clock inside traced code
        if chain[:1] == ["time"] and tail in _TIME_FNS and mod.in_traced(node):
            findings.append(mod.finding(
                "DET001", node,
                f"time.{tail}() inside traced code: the value freezes at "
                f"trace time and differs across compiles (nondeterministic "
                f"program text)",
            ))
        # (c) PRNGKey must come from a seed
        if tail in ("PRNGKey", "key") and chain[:2] == ["jax", "random"] or (
            tail == "PRNGKey" and chain[-2:-1] == ["random"]
        ):
            arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "seed":
                    arg = kw.value
            ok = (
                isinstance(arg, ast.Constant) and isinstance(arg.value, int)
            ) or (
                isinstance(arg, (ast.Name, ast.Attribute))
                and "seed" in _terminal(arg).lower()
            ) or (
                isinstance(arg, ast.Call)
                and "seed" in _terminal(arg.func).lower()
            )
            if arg is not None and not ok:
                findings.append(mod.finding(
                    "DET001", node,
                    "PRNGKey seeded from a non-seed expression; route "
                    "through params.seed (plus SALT_* fold domains) so "
                    "runs stay bitwise reproducible",
                ))
        # (d) fold_in with a magic literal outside the SALT_* domains
        if tail == "fold_in" and len(node.args) >= 2:
            data = node.args[1]
            if isinstance(data, ast.Constant) and isinstance(data.value, int):
                if data.value not in salts:
                    findings.append(mod.finding(
                        "DET001", node,
                        f"fold_in literal {data.value:#x} is not a declared "
                        f"SALT_* domain; add a SALT_* constant (ops/grow.py) "
                        f"so fold domains provably never collide",
                    ))
    # (e) unsorted set iteration feeding ordered consumers
    for node in ast.walk(mod.tree):
        is_set = isinstance(node, (ast.Set, ast.SetComp)) or (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("set", "frozenset")
        )
        if not is_set:
            continue
        parent = mod.parent.get(node)
        flagged = False
        if isinstance(parent, (ast.For, ast.AsyncFor)) and parent.iter is node:
            flagged = True
        elif isinstance(parent, ast.comprehension) and parent.iter is node:
            flagged = True
        elif (
            isinstance(parent, ast.Call)
            and node in parent.args
            and _terminal(parent.func) in _SET_CONSUMERS
        ):
            flagged = True
        if flagged:
            findings.append(mod.finding(
                "DET001", node,
                "iterating a set in order-sensitive context: set order "
                "varies across processes (PYTHONHASHSEED); wrap in sorted()",
            ))
    return findings


# ---------------------------------------------------------------------------
# SYNC001 — hidden host<->device syncs in traced code
# ---------------------------------------------------------------------------


def check_sync001(mod: _Module) -> List[Finding]:
    findings = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and mod.in_traced(node)):
            continue
        tail = _terminal(node.func)
        chain = _chain(node.func)
        msg = None
        if (
            isinstance(node.func, ast.Name)
            and tail in _SYNC_BUILTINS
            and node.args
            # float("inf")/bool(0)-style literal args can never be traced
            # values — no sync, don't force a pragma on idiomatic sentinels
            and not all(isinstance(a, ast.Constant) for a in node.args)
        ):
            msg = f"{tail}() on a traced value forces a host sync"
        elif isinstance(node.func, ast.Attribute) and tail == "item":
            msg = ".item() on a traced value forces a host sync"
        elif (
            len(chain) >= 2
            and chain[0] in ("np", "numpy", "onp")
            and tail in ("asarray", "array")
        ):
            msg = (
                f"{'.'.join(chain)}() materializes a traced value on host "
                f"(use jnp.{tail})"
            )
        elif tail in ("device_get", "block_until_ready"):
            msg = f"{tail}() inside traced code forces a host sync"
        if msg:
            findings.append(mod.finding(
                "SYNC001", node,
                msg + "; inside a round closure this serializes the "
                "device pipeline every round",
            ))
    return findings


# ---------------------------------------------------------------------------
# LOCK001 — shared state outside the lock in lock-owning classes
# ---------------------------------------------------------------------------


def _lock_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    """Delegates to the shared catalog extraction — LOCK001's notion of
    "lock-owning" and the rxgbrace instrumenter's are the same function."""
    return set(catalog.lock_attr_kinds(cls))


def _held_locks(cls: ast.ClassDef, locks: Set[str]) -> Dict[ast.AST, frozenset]:
    """Map every node to the frozenset of lock attrs held at that point
    (lexically nested ``with self.<lock>`` blocks accumulate). Tracking
    WHICH locks are held — not just "some lock" — is what lets the check
    catch state guarded by lock A being read under unrelated lock B: the
    wrong-lock torn read is the same bug as no lock at all."""
    held: Dict[ast.AST, frozenset] = {}

    def visit(node: ast.AST, holding: frozenset):
        held[node] = holding
        acquired = set()
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = _is_self_attr(item.context_expr)
                if attr in locks:
                    acquired.add(attr)
        if acquired:
            holding = holding | acquired
        for child in ast.iter_child_nodes(node):
            visit(child, holding)

    visit(cls, frozenset())
    return held


def check_lock001(mod: _Module) -> List[Finding]:
    findings = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs_of_class(cls)
        if not locks:
            continue
        held = _held_locks(cls, locks)

        # shared-mutable set: self._x assigned under a lock anywhere, or
        # assigned inside a *_locked (caller-holds-lock) method. Track the
        # lock sets held at guarded writes: their intersection is the
        # attr's owning lock(s), so a read under an unrelated lock can be
        # flagged as the torn read it is.
        shared: Set[str] = set()
        write_locks: Dict[str, frozenset] = {}
        for node in ast.walk(cls):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                # `self._seen[i] += 1` mutates self._seen just as much as
                # `self._seen = [...]` rebinds it
                if isinstance(tgt, ast.Subscript):
                    tgt = tgt.value
                attr = _is_self_attr(tgt)
                if not attr or not attr.startswith("_") or attr in locks:
                    continue
                fn = mod.nearest_named_function(node)
                in_locked_helper = fn is not None and fn.name.endswith("_locked")
                holding = held.get(node, frozenset())
                if holding or in_locked_helper:
                    shared.add(attr)
                    if holding:
                        write_locks[attr] = (
                            write_locks[attr] & holding
                            if attr in write_locks else holding
                        )

        if not shared:
            continue

        for node in ast.walk(cls):
            # unguarded call of a *_locked helper: contract breach on the
            # caller side
            if (
                isinstance(node, ast.Call)
                and (attr := _is_self_attr(node.func))
                and attr.endswith("_locked")
                and not held.get(node)
            ):
                fn = mod.nearest_named_function(node)
                if fn is not None and (
                    fn.name in _EXEMPT_METHODS or fn.name.endswith("_locked")
                ):
                    continue
                findings.append(mod.finding(
                    "LOCK001", node,
                    f"self.{attr}() requires the caller to hold "
                    f"self.{sorted(locks)[0]} (the _locked suffix contract) "
                    f"but is called outside any `with` on it",
                ))
                continue
            if not isinstance(node, ast.Attribute):
                continue
            attr = _is_self_attr(node)
            if attr not in shared:
                continue
            fn = mod.nearest_named_function(node)
            if fn is None:
                continue
            if fn.name in _EXEMPT_METHODS or fn.name.endswith("_locked"):
                continue
            holding = held.get(node, frozenset())
            owner = write_locks.get(attr, frozenset())
            access = "write to" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read of"
            if not holding:
                findings.append(mod.finding(
                    "LOCK001", node,
                    f"unguarded {access} self.{attr} in {cls.name}."
                    f"{fn.name}: this attribute is mutated under "
                    f"self.{sorted(owner or locks)[0]} elsewhere, so "
                    f"lock-free access can tear; guard it or move it into "
                    f"a *_locked helper",
                ))
            elif owner and not (holding & owner):
                # holding SOME lock of the class, just not the one that
                # guards this attribute's writes — same torn read/lost
                # update as no lock at all, but it reads as safe
                findings.append(mod.finding(
                    "LOCK001", node,
                    f"{access} self.{attr} in {cls.name}.{fn.name} holds "
                    f"self.{sorted(holding)[0]} but the attribute's writes "
                    f"are guarded by self.{sorted(owner)[0]}: the wrong "
                    f"lock does not serialize against them",
                ))
    return findings


# ---------------------------------------------------------------------------
# FAULT001 — fault-site strings must come from faults.SITES
# ---------------------------------------------------------------------------

FAULT_CALLS = frozenset({"fire", "fire_file", "plan_targets"})


def collect_fault_sites_used(mod: _Module) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in FAULT_CALLS
            and _terminal(node.func.value) == "faults"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            used.add(node.args[0].value)
    return used


def check_fault001(mod: _Module) -> List[Finding]:
    findings = []
    sites = set(catalog.fault_sites(mod.root))
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in FAULT_CALLS
            and _terminal(node.func.value) == "faults"
        ):
            continue
        if not node.args or not (
            isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            findings.append(mod.finding(
                "FAULT001", node,
                f"faults.{node.func.attr}() site must be a string literal "
                f"so plans are statically checkable against faults.SITES",
            ))
            continue
        site = node.args[0].value
        if sites and site not in sites:
            findings.append(mod.finding(
                "FAULT001", node,
                f"unknown fault site {site!r}; faults.SITES declares "
                f"{sorted(sites)} — a typo here makes chaos plans silently "
                f"no-op",
            ))
    return findings


# ---------------------------------------------------------------------------
# OBS001 — span/event names: static literals from the trace-name catalog
# ---------------------------------------------------------------------------

OBS_EMITTERS = frozenset({"event", "span", "add_span"})


def collect_trace_literals(mod: _Module) -> Set[str]:
    """Every string literal in the module that is a catalogued trace name
    (loose on purpose: names fed through local emit() helpers still count
    toward reverse coverage)."""
    names = catalog.trace_names(mod.root)
    found: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in names:
                found.add(node.value)
    return found


def _static_name_options(arg: ast.AST):
    """The finite set of literal names an expression can evaluate to, or
    None when dynamic. Accepts bare literals and conditional expressions
    over literals (``"world.shrink" if cond else "world.grow"``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.IfExp):
        body = _static_name_options(arg.body)
        orelse = _static_name_options(arg.orelse)
        if body is not None and orelse is not None:
            return body + orelse
    return None


def check_obs001(mod: _Module) -> List[Finding]:
    if mod.path.replace("\\", "/").endswith("obs/trace.py"):
        return []  # the catalog module itself
    findings = []
    names = catalog.trace_names(mod.root)
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in OBS_EMITTERS
            and node.args
        ):
            continue
        arg = node.args[0]
        options = _static_name_options(arg)
        if options is not None:
            for name in options:
                if not catalog.TRACE_NAME_RE.match(name):
                    findings.append(mod.finding(
                        "OBS001", node,
                        f"span/event name {name!r} violates the lowercase "
                        f"dotted-identifier shape the timeline schema pins",
                    ))
                elif names and name not in names:
                    findings.append(mod.finding(
                        "OBS001", node,
                        f"span/event name {name!r} is not in obs.trace."
                        f"TRACE_NAMES; add it to the catalog (and the README "
                        f"span table) or fix the typo",
                    ))
        elif isinstance(arg, ast.JoinedStr):
            findings.append(mod.finding(
                "OBS001", node,
                "f-string span/event name: emit one catalogued literal per "
                "variant so the timeline stays statically greppable",
            ))
        else:
            findings.append(mod.finding(
                "OBS001", node,
                "dynamic span/event name: the schema validator and the "
                "trace-name catalog cannot pin names it cannot see; pass a "
                "literal (or baseline this helper with a justification)",
            ))
    return findings


# ---------------------------------------------------------------------------
# EXP001 — __all__ must resolve; required public API must be exported
# ---------------------------------------------------------------------------


def _all_strings(tree: ast.Module) -> List[ast.Constant]:
    """Every string constant contributed to __all__ (=, +=, .extend)."""
    out: List[ast.Constant] = []

    def strings_of(node):
        return [
            e for e in getattr(node, "elts", [])
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            ):
                out.extend(strings_of(node.value))
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == "__all__":
                out.extend(strings_of(node.value))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "extend"
            and _terminal(node.func.value) == "__all__"
            and node.args
        ):
            out.extend(strings_of(node.args[0]))
    return out


def _bound_names(tree: ast.Module) -> Set[str]:
    """Names bound at MODULE scope only. A whole-tree walk would count a
    function-local as a module binding and let a broken ``__all__`` entry
    lint clean — the exact AttributeError this rule exists to catch.
    Module-level control flow (``if TYPE_CHECKING``, try/except import
    fallbacks, conditional defs) still binds at module scope, so those
    blocks are descended; function/class bodies are new scopes and are
    not (the def/class *name* itself does bind)."""
    bound: Set[str] = set()

    def names_in(target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                bound.add(sub.id)

    def visit(stmts) -> None:
        for node in stmts:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                bound.add(node.name)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    names_in(tgt)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                bound.add(node.target.id)
            elif isinstance(node, (ast.If, ast.While)):
                visit(node.body)
                visit(node.orelse)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                names_in(node.target)
                visit(node.body)
                visit(node.orelse)
            elif isinstance(node, ast.Try):
                visit(node.body)
                for handler in node.handlers:
                    if handler.name:
                        bound.add(handler.name)
                    visit(handler.body)
                visit(node.orelse)
                visit(node.finalbody)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        names_in(item.optional_vars)
                visit(node.body)

    visit(tree.body)
    return bound


def check_exp001(mod: _Module) -> List[Finding]:
    if not mod.path.replace("\\", "/").endswith("__init__.py"):
        return []
    exported = _all_strings(mod.tree)
    if not exported:
        return []
    findings = []
    bound = _bound_names(mod.tree)
    for const in exported:
        if const.value not in bound:
            findings.append(mod.finding(
                "EXP001", const,
                f"__all__ exports {const.value!r} but the module never "
                f"binds it; `from pkg import *` raises AttributeError",
            ))
    is_top = mod.path.replace("\\", "/").endswith(
        f"{catalog.PACKAGE}/__init__.py"
    )
    if is_top:
        names = {c.value for c in exported}
        missing = sorted(catalog.REQUIRED_EXPORTS - names)
        if missing:
            findings.append(mod.finding(
                "EXP001", mod.tree.body[0] if mod.tree.body else mod.tree,
                f"required public symbols missing from __all__: {missing} "
                f"(API surface added by earlier PRs must stay exported)",
            ))
    return findings


ALL_CHECKS = (
    check_spmd001,
    check_spmd002,
    check_det001,
    check_sync001,
    check_lock001,
    check_fault001,
    check_obs001,
    check_exp001,
)
