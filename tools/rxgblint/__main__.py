"""CLI: ``python -m tools.rxgblint <paths> [--json FILE] [--baseline FILE]``.

Exit status: 0 = no open (non-suppressed) findings, 1 = open findings or a
malformed baseline, 2 = usage error.
"""

import argparse
import os
import sys

from tools.rxgblint.baseline import DEFAULT_BASELINE, BaselineError
from tools.rxgblint.findings import RULES
from tools.rxgblint.runner import (
    TargetError,
    render_report,
    report_to_json,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="rxgblint",
        description="SPMD/determinism static analysis for xgboost_ray_tpu",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument(
        "--json", metavar="FILE",
        help="also write the machine-readable report (the CI artifact "
             "future PRs diff finding counts against)",
    )
    parser.add_argument(
        "--sarif", metavar="FILE",
        help="also write open findings as SARIF 2.1.0 (code-review "
             "annotations; suppressed findings stay out — they are not "
             "actionable on a diff)",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE, metavar="FILE",
        help="justified-suppression baseline (default: the shipped one); "
             "pass an empty string to run baseline-free",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print pragma-/baseline-suppressed findings",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            print(f"{code}: {RULES[code]}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2

    try:
        report = run_lint(args.paths, baseline_path=args.baseline)
    except BaselineError as exc:
        print(f"rxgblint: bad baseline: {exc}", file=sys.stderr)
        return 1
    except TargetError as exc:
        print(f"rxgblint: {exc}", file=sys.stderr)
        return 2
    if report["files"] == 0:
        # an existing-but-empty target is as vacuous as a missing one
        print(
            f"rxgblint: no Python files found under {args.paths!r}",
            file=sys.stderr,
        )
        return 2

    # write the artifact and settle the exit code BEFORE printing: stdout's
    # consumer closing early (`rxgblint ... | head`) must not be able to
    # turn findings into a success exit
    if args.json:
        with open(args.json, "w") as f:
            f.write(report_to_json(report) + "\n")
    if args.sarif:
        from tools.sarif import to_sarif_json

        with open(args.sarif, "w") as f:
            f.write(to_sarif_json(
                "rxgblint", RULES,
                [f_.to_dict() for f_ in report["open"]],
            ) + "\n")
    status = 1 if report["open"] else 0
    try:
        print(render_report(report, show_suppressed=args.show_suppressed))
    except BrokenPipeError:
        # swallow the pipe (not the findings); devnull keeps the
        # interpreter's shutdown flush from tracebacking
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return status


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `rxgblint ... | head` must not traceback...
        sys.exit(1)  # ...but a run we couldn't report is not a pass
