"""Finding record shared by every rxgblint rule.

A finding is one (rule, location, message) triple plus the *scope* — the
dotted qualname of the enclosing class/function chain — which is what the
suppression baseline keys on: line numbers churn on every edit, but a
finding's scope survives refactors that don't move the offending code
between functions.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional

#: rule code -> one-line description (the catalog printed by --list-rules
#: and documented in README "Static analysis")
RULES: Dict[str, str] = {
    "SPMD001": (
        "collective reachable under rank-/shard-dependent Python control "
        "flow (divergent ranks skip the collective: cluster hang)"
    ),
    "SPMD002": (
        "collective axis name not in the engine's declared mesh-axis "
        "catalog (typo'd axis fails at trace time, or worse, resolves "
        "against an unintended mesh)"
    ),
    "DET001": (
        "nondeterminism source in engine/ops code: wall-clock or unseeded "
        "RNG, jax.random fold outside the SALT_* domains, or unsorted set "
        "iteration feeding ordered data (breaks bitwise reproducibility)"
    ),
    "SYNC001": (
        "hidden host<->device sync (float()/bool()/.item()/np.asarray/"
        "device_get) inside traced code (serializes the round pipeline)"
    ),
    "LOCK001": (
        "shared-state attribute accessed outside `with self._lock` in a "
        "lock-owning class (torn snapshot / lost update under threads)"
    ),
    "FAULT001": (
        "fault-injection site string not in faults.SITES, or a catalogued "
        "site with no fire() call site (chaos plans silently no-op)"
    ),
    "OBS001": (
        "span/event name not a static literal from the obs trace-name "
        "catalog (timeline becomes ungreppable; schema validation cannot "
        "pin names)"
    ),
    "EXP001": (
        "__all__ export drift: name does not resolve in the module, or a "
        "required public symbol is missing from the package export list"
    ),
}


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    scope: str = ""  # dotted qualname of enclosing class/function chain
    suppressed: Optional[str] = field(default=None)  # "pragma" | "baseline"

    def key(self):
        """Baseline matching key: stable across line-number churn."""
        return (self.rule, self.path, self.scope)

    def to_dict(self) -> Dict[str, object]:
        out = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "scope": self.scope,
            "message": self.message,
        }
        if self.suppressed:
            out["suppressed"] = self.suppressed
        return out

    def render(self) -> str:
        tag = f" [{self.suppressed}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} {self.message}"
