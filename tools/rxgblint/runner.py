"""File walking, cross-module checks, suppression, and output."""

import json
import os
from typing import Dict, List, Optional, Sequence

from tools.rxgblint import baseline as baseline_mod
from tools.rxgblint import catalog, pragmas, rules
from tools.rxgblint.findings import RULES, Finding


def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    except ValueError:  # different drive (windows)
        return path.replace(os.sep, "/")


class TargetError(Exception):
    """A lint target doesn't exist or isn't Python — a typo'd path must be
    a loud usage error, never a vacuous 0-files/0-findings exit 0 (this is
    the first tier-1 CI gate; passing because it checked nothing is the
    worst possible failure mode)."""


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p):
            if not p.endswith(".py"):
                raise TargetError(f"not a Python file: {p!r}")
            out.append(p)
        else:
            raise TargetError(f"no such file or directory: {p!r}")
    return out


def _lint_module(
    mod: "rules._Module",
    source: str,
    only: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run every rule over one parsed module and apply its pragmas — the
    single per-file pipeline both lint_source and run_lint share, so
    suppression semantics can never diverge between the fixture-test path
    and the CLI."""
    findings: List[Finding] = []
    for check in rules.ALL_CHECKS:
        code = check.__name__.replace("check_", "").upper()
        if only is not None and code not in {c.upper() for c in only}:
            continue
        findings.extend(check(mod))
    disabled = pragmas.collect(source)
    for f in findings:
        if pragmas.is_disabled(disabled, f.line, f.rule):
            f.suppressed = "pragma"
    return findings


def lint_source(
    source: str,
    path: str = "<string>",
    root: str = catalog.REPO_ROOT,
    only: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source blob; the unit the fixture tests drive. ``only``
    restricts to the named rule codes. Pragmas are applied (suppressed
    findings are returned tagged, not dropped)."""
    try:
        mod = rules._Module(source, path, root=root)
    except SyntaxError as exc:
        return [Finding(
            rule="PARSE", path=path, line=exc.lineno or 1, col=0,
            message=f"syntax error: {exc.msg}", scope="<module>",
        )]
    findings = _lint_module(mod, source, only=only)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _covers_package(mods: Dict[str, "rules._Module"], root: str) -> bool:
    """True when the linted set includes every .py file of the package —
    the precondition for whole-package properties (reverse coverage, stale
    baselines). Linting a single file must not claim the rest of the
    package's call sites don't exist."""
    for path in catalog._package_files(root):
        if _rel(path, root) not in mods:
            return False
    return True


def _cross_module_checks(
    mods: Dict[str, "rules._Module"], root: str
) -> List[Finding]:
    """Whole-package reverse checks: every catalogued fault site must have a
    call site; every catalogued trace name must be emitted somewhere."""
    findings: List[Finding] = []

    sites = catalog.fault_sites(root)
    if sites:
        used = set()
        for path, mod in mods.items():
            if path.endswith("faults.py"):
                continue
            used |= rules.collect_fault_sites_used(mod)
        faults_rel = f"{catalog.PACKAGE}/faults.py"
        for site in sites:
            if site not in used:
                findings.append(Finding(
                    rule="FAULT001", path=faults_rel, line=1, col=0,
                    scope="<module>",
                    message=(
                        f"faults.SITES declares {site!r} but no faults.fire"
                        f"()/fire_file()/plan_targets() call site names it: "
                        f"plans targeting it silently never fire"
                    ),
                ))

    names = catalog.trace_names(root)
    if names:
        emitted = set()
        for path, mod in mods.items():
            if path.endswith("obs/trace.py"):
                continue
            emitted |= rules.collect_trace_literals(mod)
        trace_rel = f"{catalog.PACKAGE}/obs/trace.py"
        for name in sorted(names - emitted):
            findings.append(Finding(
                rule="OBS001", path=trace_rel, line=1, col=0,
                scope="<module>",
                message=(
                    f"TRACE_NAMES catalogs {name!r} but nothing in the "
                    f"package emits it: stale catalog entry (or the "
                    f"emission site lost its literal)"
                ),
            ))
    return findings


def run_lint(
    paths: Sequence[str],
    root: str = catalog.REPO_ROOT,
    baseline_path: Optional[str] = None,
) -> Dict[str, object]:
    """Lint ``paths``; returns the full report dict the CLI renders.

    ``baseline_path=None`` uses the shipped baseline file; pass "" to run
    baseline-free (the fixture tests do)."""
    files = iter_python_files(paths)
    findings: List[Finding] = []
    mods: Dict[str, rules._Module] = {}
    for path in files:
        rel = _rel(path, root)
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            mod = rules._Module(source, rel, root=root)
        except SyntaxError as exc:
            findings.append(Finding(
                rule="PARSE", path=rel, line=exc.lineno or 1, col=0,
                message=f"syntax error: {exc.msg}", scope="<module>",
            ))
            continue
        mods[rel] = mod
        findings.extend(_lint_module(mod, source))
    full_package = _covers_package(mods, root)
    if full_package:
        findings.extend(_cross_module_checks(mods, root))

    if baseline_path is None:
        baseline_path = baseline_mod.DEFAULT_BASELINE
    entries = baseline_mod.load(baseline_path) if baseline_path else []
    stale, n_baselined = baseline_mod.apply(findings, entries)
    if not full_package:
        # a partial lint can't distinguish "stale" from "not linted today"
        stale = []

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    open_findings = [f for f in findings if not f.suppressed]
    counts: Dict[str, int] = {}
    for f in open_findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "files": len(files),
        "findings": findings,
        "open": open_findings,
        "counts": counts,
        "baselined": n_baselined,
        "pragma_suppressed": sum(
            1 for f in findings if f.suppressed == "pragma"
        ),
        "stale_baseline": stale,
    }


def render_report(report: Dict[str, object], show_suppressed: bool = False) -> str:
    lines: List[str] = []
    for f in report["findings"]:
        if f.suppressed and not show_suppressed:
            continue
        lines.append(f.render())
    for e in report["stale_baseline"]:
        lines.append(
            f"{e['path']}: stale baseline entry ({e['rule']} @ {e['scope']}): "
            f"no current finding matches — remove it"
        )
    n_open = len(report["open"])
    lines.append(
        f"rxgblint: {report['files']} files, {n_open} finding(s), "
        f"{report['baselined']} baselined, "
        f"{report['pragma_suppressed']} pragma-suppressed"
    )
    return "\n".join(lines)


def report_to_json(report: Dict[str, object]) -> str:
    doc = {
        "tool": "rxgblint",
        "rules": RULES,
        "files": report["files"],
        "counts": report["counts"],
        "baselined": report["baselined"],
        "pragma_suppressed": report["pragma_suppressed"],
        "stale_baseline": report["stale_baseline"],
        "findings": [f.to_dict() for f in report["findings"]],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
