"""Shipped-code scenario units for the schedule explorer.

Each scenario drives REAL shipped classes (the registry's lock dance, the
batcher's deadline flush, the tracer's ring buffer, ...) under the
cooperative scheduler, declares an invariant checked at every terminal
state, and is sized so exhaustive exploration stays in the
hundreds-to-thousands of schedules. Heavy leaves (XLA predictor compiles,
checkpoint serialization) are stubbed via module patches — the
concurrency logic under test lives in the shipped classes, not the
stubs.

Scenario contract (what makes sleep-set pruning and replay sound here):

* scenario threads share state ONLY through instrumented objects (the
  shipped classes + wrapped sync primitives); per-thread results go into
  ctx fields written by a single thread each;
* no real time, randomness, or OS identifiers — the scheduler's logical
  clock and seeded RNGs only;
* every non-daemon thread the body spawns is joined by the body.
"""

import contextlib
import os
import threading
import time
from types import SimpleNamespace
from typing import Callable, Optional, Tuple


class Scenario:
    """One explorable scenario unit."""

    def __init__(
        self,
        name: str,
        description: str,
        body: Callable,
        invariant: Callable,
        setup: Optional[Callable] = None,
        teardown: Optional[Callable] = None,
        classes="catalog",
        max_steps: int = 4000,
        max_schedules: int = 20000,
    ):
        self.name = name
        self.description = description
        self.body = body
        self.invariant = invariant
        self._setup = setup
        self._teardown = teardown
        self.classes = classes
        self.max_steps = max_steps
        self.max_schedules = max_schedules

    def new_ctx(self) -> SimpleNamespace:
        return SimpleNamespace(_patches=[], _env=[])

    def setup(self, ctx) -> None:
        if self._setup is not None:
            self._setup(ctx)

    def teardown(self, ctx) -> None:
        try:
            if self._teardown is not None:
                self._teardown(ctx)
        finally:
            for obj, attr, orig in reversed(ctx._patches):
                setattr(obj, attr, orig)
            for key, orig in reversed(ctx._env):
                if orig is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = orig


def _patch(ctx, obj, attr: str, value) -> None:
    ctx._patches.append((obj, attr, getattr(obj, attr)))
    setattr(obj, attr, value)


def _setenv(ctx, key: str, value: Optional[str]) -> None:
    ctx._env.append((key, os.environ.get(key)))
    if value is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = value


# ---------------------------------------------------------------------------
# 1. registry: load (hot-swap) vs load vs lease
# ---------------------------------------------------------------------------


class _StubBooster:
    num_features = 3

    def __init__(self, tag: int):
        self.tag = tag


class _StubPredictor:
    """Stands in for CompiledPredictor: no XLA, but carries its booster's
    tag so a half-swapped (booster from v2, predictor from v1) entry is
    detectable."""

    def __init__(self, booster, devices=None, min_bucket=8, layout="heap"):
        self.booster = booster
        self.tag = booster.tag

    def warmup(self, kinds=(), max_batch=0):
        pass

    def predict_with_bucket(self, x, kind):
        import numpy as np

        return np.full((x.shape[0],), float(self.tag), np.float32), int(x.shape[0])


def _registry_setup(ctx):
    from xgboost_ray_tpu.serve import registry as regmod

    _patch(ctx, regmod, "CompiledPredictor", _StubPredictor)
    _patch(ctx, regmod, "coerce_model", lambda m: m)


def _registry_body(ctx):
    from xgboost_ray_tpu.serve.registry import ModelRegistry

    reg = ctx.reg = ModelRegistry(warm_kinds=())
    reg.load(_StubBooster(1), warm=False)  # v1 committed before concurrency
    ctx.reads = []

    def loader():
        reg.load(_StubBooster(2), warm=False)

    def reader():
        seen = []
        for _ in range(2):
            with reg.lease() as entry:
                seen.append(
                    (entry.version, entry.booster.tag, entry.predictor.tag)
                )
        ctx.reads = seen

    t1 = threading.Thread(target=loader, name="loader")
    t2 = threading.Thread(target=reader, name="reader")
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def _registry_invariant(ctx):
    reg = ctx.reg
    assert reg._version == 2, f"committed version {reg._version} != 2"
    assert reg._current is not None and reg._current.version == 2
    assert reg._inflight == 0 and not reg._swapping
    last = 0
    for version, booster_tag, predictor_tag in ctx.reads:
        # never half-swapped: the leased entry is wholly one model version
        assert version == booster_tag == predictor_tag, (
            f"half-swapped lease: v{version} booster{booster_tag} "
            f"predictor{predictor_tag}"
        )
        assert version >= last, "reader saw versions go backwards"
        last = version


# ---------------------------------------------------------------------------
# 2. batcher: deadline flush vs shutdown vs shed
# ---------------------------------------------------------------------------


class _StubRegistry:
    """Lock-free registry stand-in: the scenario targets the BATCHER's
    condition dance, so the lease is a plain snapshot."""

    def __init__(self):
        self.entry = SimpleNamespace(
            version=1,
            booster=_StubBooster(1),
            predictor=_StubPredictor(_StubBooster(1)),
        )

    @contextlib.contextmanager
    def lease(self):
        yield self.entry


def _batcher_body(ctx):
    import numpy as np

    from xgboost_ray_tpu.serve.batcher import MicroBatcher

    b = ctx.batcher = MicroBatcher(
        _StubRegistry(), max_batch=4, max_delay_ms=2.0, max_queue_rows=1,
    )

    def client(tag: str):
        x = np.zeros((1, 3), np.float32)
        try:
            out, version = b.submit(x, "value", timeout=None)
            setattr(ctx, tag, ("ok", int(out.shape[0]), version))
        except BaseException as exc:  # noqa: BLE001 - outcome recorded
            setattr(ctx, tag, ("err", type(exc).__name__))

    ts = [
        threading.Thread(target=client, args=("a",), name="client-a"),
        threading.Thread(target=client, args=("b",), name="client-b"),
    ]
    for t in ts:
        t.start()
    # main IS the stopper: shutdown races the in-flight submissions and the
    # flusher's deadline wakeup. timeout=None = unbounded flusher join,
    # which keeps the schedule space exhaustively explorable in CI time
    # (the bounded-join arm only adds an abandoned-daemon tail)
    b.shutdown(timeout=None)
    for t in ts:
        t.join()


def _batcher_invariant(ctx):
    b = ctx.batcher
    allowed_errors = {"OverloadedError", "ShuttingDownError"}
    for tag in ("a", "b"):
        out = getattr(ctx, tag, None)
        assert out is not None, f"client {tag} never completed (lost request)"
        if out[0] == "ok":
            assert out[1] == 1 and out[2] == 1, f"client {tag} torn: {out}"
        else:
            assert out[1] in allowed_errors, (
                f"client {tag} got unexpected error {out[1]}"
            )
    assert b._depth == 0, f"queue depth {b._depth} leaked"
    assert b._queued_rows == 0, f"queued rows {b._queued_rows} leaked"
    # _executing may read 1 when shutdown's bounded join timed out and the
    # daemon flusher was abandoned mid-batch (real interpreter exit does the
    # same); it must never go negative or exceed the single flusher
    assert b._executing in (0, 1), f"executing tore: {b._executing}"
    assert b._closed, "shutdown did not latch closed"


# ---------------------------------------------------------------------------
# 3. AsyncCheckpointWriter: background commit vs driver exit / restart
# ---------------------------------------------------------------------------


class _RestartSim(RuntimeError):
    """Stands in for the elastic-restart exception unwinding the driver."""


def _ckpt_setup(ctx):
    from xgboost_ray_tpu import launcher

    ctx.commits = []

    def stub_save(booster, path, completed_round, keep_last=None, fsync=True):
        # the sleep is a scheduler yield point: the commit genuinely
        # OVERLAPS the driver's continuing round work, which is the design
        # claim under test
        time.sleep(0.001)
        ctx.commits.append(int(completed_round))

    _patch(ctx, launcher, "save_round_checkpoint", stub_save)
    # the scenario pins in-order commit semantics; the bounded exit join is
    # separately covered by tests/test_faults.py under a forced-slow fault
    _setenv(ctx, "RXGB_CKPT_EXIT_JOIN_S", "0")


def _ckpt_body(ctx):
    from xgboost_ray_tpu.launcher import AsyncCheckpointWriter

    ctx.restarted = False
    round_lock = threading.Lock()
    ctx.rounds_done = 0
    try:
        with AsyncCheckpointWriter() as w:
            w.submit(object(), "/tmp/rxgbrace-ckpt.json", 1)
            # the round loop keeps boosting while the commit runs behind it
            for _ in range(2):
                with round_lock:
                    ctx.rounds_done += 1
            w.submit(object(), "/tmp/rxgbrace-ckpt.json", 2)
            raise _RestartSim("simulated elastic restart at a round boundary")
    except _RestartSim:
        ctx.restarted = True


def _ckpt_invariant(ctx):
    assert ctx.restarted, "restart exception was swallowed"
    assert ctx.rounds_done == 2, f"round loop stalled: {ctx.rounds_done}"
    assert ctx.commits == [1, 2], (
        f"commits {ctx.commits} != [1, 2]: out-of-order or dropped write"
    )


# ---------------------------------------------------------------------------
# 4. tracer: emit vs export vs snapshot
# ---------------------------------------------------------------------------


def _tracer_body(ctx):
    from xgboost_ray_tpu.obs.trace import Tracer

    tr = ctx.tracer = Tracer(capacity=2, enabled=True, trace_dir="", rank=0)

    def emitter_spans():
        with tr.span("round", round=0):
            tr.event("fault.injected", site="serve.predict")

    def emitter_events():
        tr.event("checkpoint.commit", round=1)

    def reader():
        ctx.mid_snapshot = tr.snapshot()
        ctx.mid_dropped = tr.dropped

    ts = [
        threading.Thread(target=emitter_spans, name="emit-span"),
        threading.Thread(target=emitter_events, name="emit-event"),
        threading.Thread(target=reader, name="reader"),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def _tracer_invariant(ctx):
    from xgboost_ray_tpu.obs.trace import validate_trace_records

    tr = ctx.tracer
    recs = tr.records()
    snap = tr.snapshot()
    # 3 records were emitted into a 2-slot ring: accounting must be exact
    assert len(recs) == 2, f"ring holds {len(recs)} != capacity 2"
    assert tr.dropped == 1, f"dropped {tr.dropped} != 1"
    assert snap["records"] + snap["dropped_spans"] == 3, f"torn: {snap}"
    seqs = [r["seq"] for r in recs]
    assert len(set(seqs)) == len(seqs), f"duplicate seq in {seqs}"
    assert validate_trace_records(recs) == []
    # the concurrent mid-run snapshot was itself a consistent cut
    mid = ctx.mid_snapshot
    assert 0 <= mid["dropped_spans"] <= 1 and mid["records"] <= 2, mid
    assert 0 <= ctx.mid_dropped <= 1


# ---------------------------------------------------------------------------
# 5. faults: fire vs reset
# ---------------------------------------------------------------------------


def _faults_body(ctx):
    from xgboost_ray_tpu.faults import FaultPlan

    plan = ctx.plan = FaultPlan(
        rules=[{"site": "serve.predict", "action": "raise", "at": 99}],
        seed=3,
    )

    def firer():
        plan.fire("serve.predict", rows=1)
        plan.fire("serve.predict", rows=2)

    def resetter():
        plan.reset()

    t1 = threading.Thread(target=firer, name="firer")
    t2 = threading.Thread(target=resetter, name="resetter")
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def _faults_invariant(ctx):
    plan = ctx.plan
    assert len(plan._seen) == len(plan.rules) == 1
    assert 0 <= plan._seen[0] <= 2, f"torn counter {plan._seen}"
    assert len(plan._rngs) == 1


# ---------------------------------------------------------------------------
# 6. metrics: record vs snapshot / Prometheus render
# ---------------------------------------------------------------------------


def _metrics_body(ctx):
    from xgboost_ray_tpu.serve.metrics import ServeMetrics

    m = ctx.metrics = ServeMetrics()
    ctx.snaps = []

    def worker():
        m.observe_request(0.0015, 1)

    def renderer():
        ctx.snaps.append(m.snapshot())
        ctx.prom = m.prometheus_text()

    t1 = threading.Thread(target=worker, name="worker")
    t2 = threading.Thread(target=renderer, name="renderer")
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def _metrics_invariant(ctx):
    m = ctx.metrics
    assert m.requests == 1 and m.rows == 1, (m.requests, m.rows)
    hist = m._hist.snapshot()
    assert hist["total"] == 1 and sum(hist["counts"]) == 1, hist["total"]
    for snap in ctx.snaps:
        # observe_request incs requests+rows under one lock; any snapshot
        # cut must see them together (n_rows == 1 per request)
        assert snap["rows"] == snap["requests"], f"torn snapshot: {snap}"
    assert "rxgb_serve_requests_total" in ctx.prom
    assert ctx.prom.endswith("\n")


# ---------------------------------------------------------------------------
# 7. elastic: background pending-load vs driver poll (the PR's fixed race)
# ---------------------------------------------------------------------------


def _elastic_setup(ctx):
    _setenv(ctx, "RXGB_ELASTIC_RESTART_GRACE_PERIOD_S", "0")
    _setenv(ctx, "RXGB_TRACE_DIR", None)


def _elastic_teardown(ctx):
    from xgboost_ray_tpu import obs

    obs.set_default_tracer(None)


def _elastic_body(ctx):
    from xgboost_ray_tpu import obs
    from xgboost_ray_tpu.elastic import (
        PendingActor,
        _update_scheduled_actor_states,
    )

    # fresh tracer created INSIDE the scenario so its lock is instrumented
    obs.set_default_tracer(
        obs.Tracer(capacity=64, enabled=True, trace_dir="", rank=0)
    )
    pending = ctx.pending = PendingActor(actor=object(), created_at=time.time())
    state = SimpleNamespace(
        pending_actors={0: pending}, restart_training_at=None,
    )

    def loader():
        # the tail of elastic's background _load closure on the slow path
        pending.mark_ready()

    def driver():
        outs = []
        for _ in range(3):
            outs.append(
                _update_scheduled_actor_states(state, raise_on_ready=False)
            )
        ctx.outs = outs

    t1 = threading.Thread(target=loader, name="elastic-load-rank-0")
    t2 = threading.Thread(target=driver, name="driver")
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def _elastic_invariant(ctx):
    pending = ctx.pending
    assert pending.ready, "load completed but driver-visible ready is False"
    assert pending.error is None
    assert not (pending.ready and pending.error is not None), "torn state"
    grows = [o for o in ctx.outs if o]
    assert len(grows) <= 1, f"double reintegration signal: {ctx.outs}"


# ---------------------------------------------------------------------------
# 8. stream uploader: double-buffered submit/backpressure vs worker drain
# ---------------------------------------------------------------------------


def _uploader_body(ctx):
    from xgboost_ray_tpu.stream.upload import DoubleBufferedUploader

    log = []

    def transfer(array, device):
        # scheduler yield point standing in for the H2D copy: the transfer
        # genuinely overlaps the producer's next submit (the design claim)
        time.sleep(0.001)
        log.append((array, device))
        return ("dev", array, device)

    up = ctx.uploader = DoubleBufferedUploader(depth=2, transfer=transfer)
    ctx.transfer_log = log

    def producer():
        # 3 submits against depth 2: the third MUST hit backpressure until
        # the worker drains one
        for i in range(3):
            up.submit(("blk", i), i, "d0")
        ctx.results = up.drain()

    t = threading.Thread(target=producer, name="bin-producer")
    t.start()
    t.join()
    up.close()


def _uploader_invariant(ctx):
    up = ctx.uploader
    assert ctx.results == {("blk", i): ("dev", i, "d0") for i in range(3)}, (
        f"lost or torn transfer: {ctx.results}"
    )
    # per-device submit order is the row order of the binned matrix:
    # reordering here would interleave blocks corruptly
    assert ctx.transfer_log == [(i, "d0") for i in range(3)], ctx.transfer_log
    assert up._inflight == 0, f"inflight leaked: {up._inflight}"
    assert not up._pending, "pending queue leaked"
    assert up._error is None
    stats = up.stats()
    assert stats["transfers"] == stats["submitted"] == 3, stats


# ---------------------------------------------------------------------------
# 9. router: dispatch vs replica kill — shed requests re-dispatch
# ---------------------------------------------------------------------------


class _StubReplicaBatcher:
    """Flusher-free MicroBatcher stand-in: the batcher's own condition
    dance has its own scenario (batcher_flush_shutdown_shed); here the unit
    under test is the ROUTER's table/kill/re-dispatch logic, so submit
    executes synchronously through the replica view's lease while keeping
    the exact ShuttingDownError surface the router consumes. A kill landing
    between the closed-check and the lease executes anyway — the shipped
    semantics (mid-execution batches complete on the dying replica)."""

    def __init__(self, view, **kwargs):
        self._view = view
        self._lock = threading.Lock()
        self._closed = False

    def submit(self, x, kind="value", timeout=None):
        from xgboost_ray_tpu.serve.batcher import ShuttingDownError

        with self._lock:
            if self._closed:
                raise ShuttingDownError("replica batcher is shut down")
        with self._view.lease() as entry:
            out, _ = entry.predictor.predict_with_bucket(x, kind)
            return out, entry.version

    def queue_depth(self):
        return 0

    def queued_rows(self):
        return 0

    def executing_batches(self):
        return 0

    def consecutive_failures(self):
        return 0

    @property
    def breaker_open(self):
        return False

    def drain(self, timeout=5.0):
        return True

    def shutdown(self, timeout=5.0):
        with self._lock:
            self._closed = True


def _router_setup(ctx):
    from xgboost_ray_tpu.serve import pool as poolmod
    from xgboost_ray_tpu.serve import registry as regmod

    _patch(ctx, regmod, "CompiledPredictor", _StubPredictor)
    _patch(ctx, regmod, "coerce_model", lambda m: m)
    # the replica views build their own predictors through pool's import
    _patch(ctx, poolmod, "CompiledPredictor", _StubPredictor)
    _patch(ctx, poolmod, "MicroBatcher", _StubReplicaBatcher)


def _router_teardown(ctx):
    from xgboost_ray_tpu import obs

    obs.set_default_tracer(None)


def _router_body(ctx):
    import numpy as np

    from xgboost_ray_tpu import obs
    from xgboost_ray_tpu.serve.pool import Router
    from xgboost_ray_tpu.serve.registry import ModelRegistry

    # fresh tracer created INSIDE the scenario so its lock is instrumented
    ctx.tracer = obs.Tracer(capacity=64, enabled=True, trace_dir="", rank=0)
    obs.set_default_tracer(ctx.tracer)
    reg = ModelRegistry(warm_kinds=())
    reg.load(_StubBooster(1), warm=False)
    router = ctx.router = Router(reg, n_replicas=2)

    def client():
        x = np.zeros((1, 3), np.float32)
        try:
            out, version = router.submit(x, "value", timeout=None)
            ctx.client = ("ok", float(out[0]), version)
        except BaseException as exc:  # noqa: BLE001 - outcome recorded
            ctx.client = ("err", type(exc).__name__)

    t = threading.Thread(target=client, name="client")
    t.start()
    # main IS the killer (one thread fewer keeps exploration exhaustive):
    # the hard replica loss races the dispatch — if the request was queued
    # on slot 0 it fails internally and MUST re-dispatch to slot 1
    router.kill(0)
    t.join()
    ctx.live_after = router.live_replicas()
    # timeout=None = unbounded flusher joins, keeping the schedule space
    # exhaustively explorable (same trade as the batcher scenario)
    router.shutdown(timeout=None)


def _router_invariant(ctx):
    router = ctx.router
    out = getattr(ctx, "client", None)
    assert out is not None, "client never completed (lost request)"
    # capacity degrades, availability never: slot 1 outlives the kill, so
    # the request must succeed — wholly on model v1
    assert out == ("ok", 1.0, 1), f"request failed or torn: {out}"
    assert ctx.live_after == 1, f"live {ctx.live_after} != 1 after kill"
    assert router._closed, "shutdown did not latch closed"
    assert not router._replicas, "replica table leaked"
    assert router.queue_depth() == 0 and router.queued_rows() == 0
    names = [r.get("name") for r in ctx.tracer.records()]
    assert "serve.replica_down" in names, f"kill left no timeline event: {names}"


# ---------------------------------------------------------------------------
# 10. fault-domain death coalescing vs the driver's drain + grow polls
# ---------------------------------------------------------------------------


def _domain_body(ctx):
    from xgboost_ray_tpu import obs
    from xgboost_ray_tpu.domains import DeathCoalescer, DomainMap
    from xgboost_ray_tpu.elastic import (
        PendingActor,
        _update_scheduled_actor_states,
    )

    obs.set_default_tracer(
        obs.Tracer(capacity=64, enabled=True, trace_dir="", rank=0)
    )
    co = ctx.co = DeathCoalescer()
    p2 = ctx.p2 = PendingActor(actor=object(), created_at=time.time())
    p3 = ctx.p3 = PendingActor(actor=object(), created_at=time.time())
    # ranks 2+3 form fault domain 1; both died and both replacements are
    # staged but not yet loaded
    state = SimpleNamespace(
        pending_actors={2: p2, 3: p3},
        restart_training_at=None,
        domain_map=DomainMap({0: 0, 1: 0, 2: 1, 3: 1}),
        elastic_dead_ranks={2, 3},
    )
    batches = ctx.batches = []

    def killer(rank, pending):
        # one rank's lifecycle during a correlated host loss: the
        # out-of-band death notification, then the replacement's background
        # load completing
        co.note(rank, domain=1)
        pending.mark_ready()

    def driver():
        outs = []
        for _ in range(3):
            batch = co.drain()
            if batch:
                batches.append(batch)
            ok = _update_scheduled_actor_states(state, raise_on_ready=False)
            outs.append((ok, tuple(getattr(state, "domains_due", ()) or ())))
        ctx.outs = outs

    t1 = threading.Thread(target=killer, args=(2, p2), name="killer-rank-2")
    t2 = threading.Thread(target=killer, args=(3, p3), name="killer-rank-3")
    t3 = threading.Thread(target=driver, name="driver")
    for t in (t1, t2, t3):
        t.start()
    for t in (t1, t2, t3):
        t.join()


def _domain_invariant(ctx):
    leftover = ctx.co.drain()
    if leftover:
        ctx.batches.append(leftover)
    assert not ctx.co.pending, "mailbox not empty after final drain"
    seen = []
    for batch in ctx.batches:
        for rank, dom in batch.items():
            assert dom == 1, f"domain attribution torn: {batch}"
            seen.append(rank)
    # every noted rank lands in exactly one drained batch — never dropped,
    # never double-blamed (double-blame = two shrinks for one host loss)
    assert sorted(seen) == [2, 3], f"ranks drained {seen}, want [2, 3]"
    grows = [o for o in ctx.outs if o[0]]
    assert len(grows) <= 1, f"double grow signal: {ctx.outs}"
    for _ok, due in grows:
        # the grow signal names the WHOLE domain, only once both
        # replacements finished loading — a half-staged domain must wait
        assert due == (1,), f"grow due set {due}, want (1,)"
        assert ctx.p2.ready and ctx.p3.ready, "grew on a half-ready domain"


# ---------------------------------------------------------------------------
# the suite
# ---------------------------------------------------------------------------


SCENARIOS: Tuple[Scenario, ...] = (
    Scenario(
        name="registry_hot_swap",
        description="ModelRegistry.load (drain-then-flip) vs concurrent "
                    "lease: no lease ever observes a half-swapped entry",
        body=_registry_body, invariant=_registry_invariant,
        setup=_registry_setup,
    ),
    Scenario(
        name="batcher_flush_shutdown_shed",
        description="MicroBatcher deadline flush vs shutdown vs queue-cap "
                    "shed: every request resolves exactly once, accounting "
                    "returns to zero",
        body=_batcher_body, invariant=_batcher_invariant,
        max_steps=6000,
    ),
    Scenario(
        name="ckpt_writer_commit_vs_restart",
        description="AsyncCheckpointWriter background commits vs a "
                    "simulated elastic restart unwinding the driver: "
                    "commits stay in round order, none dropped",
        body=_ckpt_body, invariant=_ckpt_invariant, setup=_ckpt_setup,
    ),
    Scenario(
        name="tracer_emit_vs_snapshot",
        description="Tracer ring-buffer emit vs snapshot/records: drop "
                    "accounting exact, seq unique, snapshots are "
                    "consistent cuts",
        body=_tracer_body, invariant=_tracer_invariant,
    ),
    Scenario(
        name="faultplan_fire_vs_reset",
        description="FaultPlan.fire counter advance vs reset rewind: "
                    "counters never tear against the rule list",
        body=_faults_body, invariant=_faults_invariant,
    ),
    Scenario(
        name="metrics_record_vs_render",
        description="ServeMetrics observe vs snapshot + Prometheus render: "
                    "multi-counter cuts are atomic",
        body=_metrics_body, invariant=_metrics_invariant,
    ),
    Scenario(
        name="stream_upload_double_buffer",
        description="DoubleBufferedUploader submit backpressure vs worker "
                    "drain vs drain/close: no transfer lost or reordered, "
                    "accounting returns to zero",
        body=_uploader_body, invariant=_uploader_invariant,
    ),
    Scenario(
        name="router_dispatch_vs_kill",
        description="Router least-queue dispatch vs a hard replica kill: "
                    "the shed request re-dispatches to the survivor, no "
                    "request lost, membership events on the timeline",
        body=_router_body, invariant=_router_invariant,
        setup=_router_setup, teardown=_router_teardown,
        max_steps=8000,
    ),
    Scenario(
        name="elastic_pending_load_vs_poll",
        description="elastic PendingActor background load vs driver "
                    "reintegration poll (the slow-load path): ready/error "
                    "never tear (regression pin for the PendingActor lock)",
        body=_elastic_body, invariant=_elastic_invariant,
        setup=_elastic_setup, teardown=_elastic_teardown,
    ),
    Scenario(
        name="domain_death_coalesce_vs_grow_poll",
        description="DeathCoalescer concurrent domain death notes vs the "
                    "driver's drain + atomic domain grow poll: every rank "
                    "drained exactly once, at most one grow signal, and "
                    "only for the complete domain",
        body=_domain_body, invariant=_domain_invariant,
        setup=_elastic_setup, teardown=_elastic_teardown,
    ),
)


def by_name(name: str) -> Scenario:
    for scn in SCENARIOS:
        if scn.name == name:
            return scn
    raise KeyError(
        f"unknown scenario {name!r}; one of {[s.name for s in SCENARIOS]}"
    )
