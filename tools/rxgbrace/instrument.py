"""Runtime instrumentation: patched threading primitives + attribute hooks.

``Instrumentation`` is a context manager that, for its scope only,
replaces ``threading.Lock/RLock/Condition/Event/Thread`` with recording
wrappers, patches ``time.monotonic/time/perf_counter/sleep`` to a logical
clock (only for scheduler-managed threads), and installs
``__getattribute__``/``__setattr__`` hooks on the lock-owning classes from
rxgblint's LOCK001 catalog (``tools.rxgblint.catalog.lock_owning_classes``
— the instrumenter has NO class list of its own). Everything is restored
on exit; production code that never enters the context manager pays
nothing.

Three execution modes per thread, decided per operation:

* **scheduled** — the thread is managed by a cooperative
  :class:`~tools.rxgbrace.sched.Scheduler`; sync operations route through
  it (virtual lock/condition/event state, deterministic interleaving).
* **record-only** — the thread is tracked (it entered the context or was
  spawned through the patched ``Thread`` while tracking): operations
  delegate to the real primitives and are recorded.
* **passthrough** — unrelated threads (pytest plumbing, jax internals)
  see the real behavior, unrecorded.
"""

import importlib
import threading
import time
import _thread
from typing import List, Optional, Tuple

from tools.rxgbrace.events import Recorder, call_site

# real primitives, saved before any patching can occur
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_EVENT = threading.Event
_REAL_THREAD = threading.Thread
_REAL_MONOTONIC = time.monotonic
_REAL_TIME = time.time
_REAL_PERF = time.perf_counter
_REAL_SLEEP = time.sleep

#: the active Instrumentation (at most one; enforced on __enter__)
_STATE: Optional["Instrumentation"] = None

_tls = threading.local()


class _Killed(BaseException):
    """Raised inside abandoned scenario threads during scheduler cleanup;
    BaseException so ``except Exception`` handlers in scenario code cannot
    swallow the teardown."""


class RawGate:
    """Binary-semaphore turnstile on a raw ``_thread`` lock: ``set()``
    opens it once, ``wait()`` passes and re-closes. Half the cost of an
    ``Event`` round trip, immune to patching — the scheduler's turn
    handoff uses nothing else."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = _thread.allocate_lock()
        self._lock.acquire()

    def set(self) -> None:
        try:
            self._lock.release()
        except RuntimeError:
            pass  # already open (double set is idempotent)

    def wait(self) -> None:
        self._lock.acquire()

    def clear(self) -> None:
        pass  # wait() consumes the open state


def raw_event():
    """A REAL ``threading.Event`` immune to the patched factories.

    ``Event.__init__`` calls ``Condition(Lock())`` through the threading
    module's (patched) globals, so a plain ``_REAL_EVENT()`` created inside
    the patch window would secretly wrap our own wrappers — the scheduler's
    gates and ``Thread``'s internal ``_started`` event must never route
    through the instrumentation they serve. Built piecewise from raw parts
    (``Condition.wait`` itself only uses ``_thread.allocate_lock``, which
    is never patched)."""
    ev = _REAL_EVENT.__new__(_REAL_EVENT)
    cond = _REAL_CONDITION.__new__(_REAL_CONDITION)
    _REAL_CONDITION.__init__(cond, _thread.allocate_lock())
    ev._cond = cond
    ev._flag = False
    return ev


# -- per-thread bookkeeping --------------------------------------------------


def _tracked() -> bool:
    return getattr(_tls, "tracked", False)


def _held() -> List[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _held_add(label: str) -> None:
    _held().append(label)


def _held_remove(label: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == label:
            del held[i]
            return


def _lockset() -> Tuple[str, ...]:
    return tuple(sorted(set(_held())))


def _thread_label() -> str:
    m = getattr(_tls, "managed", None)
    if m is not None:
        return m.label
    label = getattr(_tls, "label", None)
    if label is not None:
        return label
    return threading.current_thread().name


def _ctl():
    """The scheduler controlling the CURRENT thread (None otherwise)."""
    st = _STATE
    if st is None or st.controller is None:
        return None
    if getattr(_tls, "managed", None) is not None:
        return st.controller
    return None


def _rec() -> Optional[Recorder]:
    st = _STATE
    if st is None or not _tracked():
        return None
    return st.recorder


def _record(op: str, obj, kind: str, **kw) -> None:
    rec = _rec()
    if rec is None:
        return
    rec.record(
        _thread_label(), op, obj=rec.label_for(obj, kind),
        locks=_lockset(), site=call_site(), **kw,
    )


# -- wrapper primitives ------------------------------------------------------


class TLock:
    """Wrapper for ``threading.Lock``."""

    _kind = "Lock"

    def __init__(self):
        self._real = _REAL_LOCK()
        # virtual state (scheduled mode only)
        self._v_owner = None

    def _label(self) -> str:
        rec = _STATE.recorder if _STATE else None
        return rec.label_for(self, self._kind) if rec else self._kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ctl = _ctl()
        if ctl is not None:
            return ctl.lock_acquire(self, blocking=blocking)
        if _tracked():
            ok = self._real.acquire(blocking, timeout)
            if ok:
                _record("acquire", self, self._kind)
                _held_add(self._label())
            return ok
        return self._real.acquire(blocking, timeout)

    def release(self):
        ctl = _ctl()
        if ctl is not None:
            return ctl.lock_release(self)
        if _tracked():
            _record("release", self, self._kind)
            _held_remove(self._label())
        return self._real.release()

    def locked(self) -> bool:
        ctl = _ctl()
        if ctl is not None:
            return self._v_owner is not None
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TRLock(TLock):
    """Wrapper for ``threading.RLock`` (reentrant)."""

    _kind = "RLock"

    def __init__(self):
        self._real = _REAL_RLOCK()
        self._v_owner = None
        self._v_count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ctl = _ctl()
        if ctl is not None:
            return ctl.lock_acquire(self, blocking=blocking, reentrant=True)
        if _tracked():
            ok = self._real.acquire(blocking, timeout)
            if ok:
                _record("acquire", self, self._kind)
                _held_add(self._label())
            return ok
        return self._real.acquire(blocking, timeout)

    def release(self):
        ctl = _ctl()
        if ctl is not None:
            return ctl.lock_release(self, reentrant=True)
        if _tracked():
            _record("release", self, self._kind)
            _held_remove(self._label())
        return self._real.release()


class TCondition:
    """Wrapper for ``threading.Condition`` over a (wrapped) lock."""

    _kind = "Condition"

    def __init__(self, lock=None):
        if lock is None:
            # stdlib parity: a bare threading.Condition() defaults to an
            # RLock, and re-entrant acquire patterns must not become
            # spurious scheduler deadlocks
            lock = TRLock()
        self._lock = lock
        # real condition over the real underlying lock (record-only mode)
        self._real = _REAL_CONDITION(getattr(lock, "_real", lock))
        self._v_waiters: List = []  # scheduled mode: waiter queue

    def _label(self) -> str:
        rec = _STATE.recorder if _STATE else None
        return rec.label_for(self, self._kind) if rec else self._kind

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ctl = _ctl()
        if ctl is not None:
            return ctl.cond_wait(self, timeout)
        if _tracked():
            _record("wait", self, self._kind)
            lock_label = (
                self._lock._label() if hasattr(self._lock, "_label") else ""
            )
            _held_remove(lock_label)
            res = self._real.wait(timeout)
            _held_add(lock_label)
            _record(
                "wake", self, self._kind,
                variant="notified" if res else "timeout",
            )
            return res
        return self._real.wait(timeout)

    def notify(self, n: int = 1) -> None:
        ctl = _ctl()
        if ctl is not None:
            return ctl.cond_notify(self, n)
        if _tracked():
            _record("notify", self, self._kind)
        return self._real.notify(n)

    def notify_all(self) -> None:
        return self.notify(1 << 30)


class TEvent:
    """Wrapper for ``threading.Event``."""

    _kind = "Event"

    def __init__(self):
        self._real = _REAL_EVENT()
        self._v_set = False

    def is_set(self) -> bool:
        ctl = _ctl()
        if ctl is not None:
            return self._v_set
        return self._real.is_set()

    def set(self) -> None:
        ctl = _ctl()
        if ctl is not None:
            return ctl.ev_set(self)
        if _tracked():
            _record("ev_set", self, self._kind)
        return self._real.set()

    def clear(self) -> None:
        ctl = _ctl()
        if ctl is not None:
            self._v_set = False
            return None
        if _tracked():
            _record("ev_clear", self, self._kind)
        return self._real.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ctl = _ctl()
        if ctl is not None:
            return ctl.ev_wait(self, timeout)
        if _tracked():
            _record("ev_wait", self, self._kind)
            res = self._real.wait(timeout)
            _record(
                "ev_wake", self, self._kind,
                variant="notified" if res else "timeout",
            )
            return res
        return self._real.wait(timeout)


class TThread(_REAL_THREAD):
    """Patched ``threading.Thread``: threads started while tracking are
    recorded (fork/begin/end/join); threads started from a scheduler-managed
    thread become managed themselves."""

    def __init__(self, *args, **kwargs):
        _REAL_THREAD.__init__(self, *args, **kwargs)
        # Thread.__init__ created its _started event through the patched
        # factories; swap in a raw one so the interpreter's own start/join
        # handshake never routes through the instrumentation
        self._started = raw_event()

    def start(self):
        st = _STATE
        ctl = _ctl()
        if ctl is not None:
            return ctl.thread_spawn(self)
        if st is not None and _tracked():
            rec = st.recorder
            rec.record(
                _thread_label(), "fork",
                target=rec.label_for(self, self.name),
                locks=_lockset(), site=call_site(),
            )
            self._rxgb_track = True
        return _REAL_THREAD.start(self)

    def run(self):
        m = getattr(self, "_rxgb_managed", None)
        if m is not None:
            sched = m.scheduler
            _tls.managed = m
            _tls.tracked = True
            _tls.held = []
            try:
                # begin() can itself raise _Killed (cleanup of a thread that
                # never got a turn) — it must stay inside the handler
                sched.thread_begin(m)
                _REAL_THREAD.run(self)
            except _Killed:
                pass
            except BaseException as exc:  # noqa: BLE001 - surfaced in RunResult
                m.error = exc
            finally:
                sched.thread_end(m)
            return
        if getattr(self, "_rxgb_track", False) and _STATE is not None:
            st = _STATE
            _tls.tracked = True
            _tls.held = []
            # the thread's event label must MATCH the fork record's target
            # label, or the detector loses the fork/join ordering edges
            _tls.label = st.recorder.label_for(self, self.name)
            st.recorder.record(_tls.label, "begin")
            try:
                _REAL_THREAD.run(self)
            finally:
                st.recorder.record(_tls.label, "end")
            return
        return _REAL_THREAD.run(self)

    def join(self, timeout: Optional[float] = None):
        ctl = _ctl()
        if ctl is not None:
            return ctl.thread_join(self, timeout)
        st = _STATE
        res = _REAL_THREAD.join(self, timeout)
        if st is not None and _tracked() and getattr(self, "_rxgb_track", False):
            rec = st.recorder
            op = "join_timeout" if self.is_alive() else "join"
            rec.record(
                _thread_label(), op, target=rec.label_for(self, self.name),
                locks=_lockset(), site=call_site(),
            )
        return res


# -- logical clock (scheduled threads only) ----------------------------------


def _fake_monotonic() -> float:
    ctl = _ctl()
    return ctl.now() if ctl is not None else _REAL_MONOTONIC()


def _fake_time() -> float:
    ctl = _ctl()
    return (1_700_000_000.0 + ctl.now()) if ctl is not None else _REAL_TIME()


def _fake_perf_counter() -> float:
    ctl = _ctl()
    return ctl.now() if ctl is not None else _REAL_PERF()


def _fake_sleep(secs: float) -> None:
    ctl = _ctl()
    if ctl is not None:
        return ctl.sleep(secs)
    return _REAL_SLEEP(secs)


# -- attribute hooks ---------------------------------------------------------


def _note_access(instance, cls, name: str, kind: str) -> None:
    st = _STATE
    if st is None or not _tracked():
        return
    if getattr(_tls, "in_note", False):
        return
    _tls.in_note = True
    try:
        rec = st.recorder
        rec.record(
            _thread_label(), kind,
            obj=rec.label_for(instance, cls.__name__), attr=name,
            locks=_lockset(), site=call_site(),
        )
    finally:
        _tls.in_note = False


def _install_attr_hooks(cls, watched: frozenset):
    """Install read/write hooks for ``watched`` attribute names on ``cls``;
    returns the restore closure."""
    had_get = "__getattribute__" in cls.__dict__
    had_set = "__setattr__" in cls.__dict__
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__
    saved_get = cls.__dict__.get("__getattribute__")
    saved_set = cls.__dict__.get("__setattr__")

    def hooked_get(self, name, _w=watched, _c=cls, _o=orig_get):
        if name in _w:
            _note_access(self, _c, name, "read")
        return _o(self, name)

    def hooked_set(self, name, value, _w=watched, _c=cls, _o=orig_set):
        if name in _w:
            _note_access(self, _c, name, "write")
        return _o(self, name, value)

    cls.__getattribute__ = hooked_get
    cls.__setattr__ = hooked_set

    def restore():
        if had_get:
            cls.__getattribute__ = saved_get
        else:
            del cls.__getattribute__
        if had_set:
            cls.__setattr__ = saved_set
        else:
            del cls.__setattr__

    return restore


def resolve_catalog_classes(root: Optional[str] = None):
    """Resolve rxgblint's lock-owning-class catalog to runtime
    ``(cls, watched_attrs)`` pairs — the instrumenter's class list IS the
    linter's. Returns (pairs, errors)."""
    from tools.rxgblint import catalog

    pairs: List[Tuple[type, frozenset]] = []
    errors: List[str] = []
    records = (
        catalog.lock_owning_classes(root)
        if root is not None else catalog.lock_owning_classes()
    )
    for recd in records:
        try:
            mod = importlib.import_module(recd.module)
            obj = mod
            for part in recd.qualname.split("."):
                obj = getattr(obj, part)
            pairs.append((obj, frozenset(recd.shared)))
        except Exception as exc:  # noqa: BLE001 - surfaced to the CLI
            errors.append(f"{recd.module}.{recd.qualname}: {exc!r}")
    return pairs, errors


# -- the context manager -----------------------------------------------------


class Instrumentation:
    """Install the wrappers + hooks for a scope.

    ``classes`` — "catalog" (default) hooks every lock-owning class from
    rxgblint's catalog; an explicit iterable of ``(cls, attrs)`` pairs
    hooks exactly those; ``None`` hooks nothing. ``controller`` is a
    :class:`~tools.rxgbrace.sched.Scheduler` for deterministic runs (or
    None for record-only mode).
    """

    def __init__(
        self,
        recorder: Optional[Recorder] = None,
        controller=None,
        classes="catalog",
        root: Optional[str] = None,
    ):
        self.recorder = recorder if recorder is not None else Recorder()
        self.controller = controller
        self._classes_arg = classes
        self._root = root
        self._restores: List = []
        self.hooked: List[Tuple[type, frozenset]] = []
        self.hook_errors: List[str] = []

    def __enter__(self) -> "Instrumentation":
        global _STATE
        if _STATE is not None:
            raise RuntimeError("rxgbrace instrumentation is not reentrant")
        # patch the threading factories
        patches = [
            (threading, "Lock", TLock),
            (threading, "RLock", TRLock),
            (threading, "Condition", TCondition),
            (threading, "Event", TEvent),
            (threading, "Thread", TThread),
            (time, "monotonic", _fake_monotonic),
            (time, "time", _fake_time),
            (time, "perf_counter", _fake_perf_counter),
            (time, "sleep", _fake_sleep),
        ]
        for mod, name, repl in patches:
            orig = getattr(mod, name)
            setattr(mod, name, repl)
            self._restores.append(lambda m=mod, n=name, o=orig: setattr(m, n, o))
        # attribute hooks
        if self._classes_arg == "catalog":
            pairs, self.hook_errors = resolve_catalog_classes(self._root)
        elif self._classes_arg is None:
            pairs = []
        else:
            pairs = [(c, frozenset(a)) for c, a in self._classes_arg]
        for cls, watched in pairs:
            if watched:
                self._restores.append(_install_attr_hooks(cls, watched))
            self.hooked.append((cls, watched))
        _STATE = self
        self._prev_tracked = getattr(_tls, "tracked", False)
        _tls.tracked = True
        _tls.held = []
        return self

    def __exit__(self, *exc) -> bool:
        global _STATE
        _tls.tracked = self._prev_tracked
        for restore in reversed(self._restores):
            restore()
        self._restores = []
        _STATE = None
        return False
