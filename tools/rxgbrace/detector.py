"""FastTrack-style vector-clock + lockset race detection over event logs,
plus the RACE003 AST pass.

Ordering model (the Eraser hybrid): happens-before edges are program
order, ``fork -> child begin``, ``child end -> join``, ``Event.set ->
(successful) wait``, and ``Condition.notify -> (notified) wake``. Lock
``release -> acquire`` is deliberately NOT an ordering edge — mutual
exclusion is not ordering, and treating it as ordering hides races that
the observed schedule happened to serialize. Correctly lock-guarded state
is instead recognized through the recorded locksets: two conflicting
accesses sharing a lock can never race.

RACE001 — conflicting (>=1 write) cross-thread accesses to one
``instance.attr`` that are HB-unordered AND hold disjoint locksets.
RACE002 — a cycle in the global lock-acquisition graph (edge A->B when a
thread acquired B while holding A), reported with witness sites: the
deadlock certificate, independent of whether any run deadlocked.
RACE003 — static: a ``self.<condition>.wait()`` call with no enclosing
``while``/``for`` loop inside its function (stale-predicate wakeups),
checked over the condition-kind attributes of the shared lock-owning-class
catalog.
"""

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.rxgbrace.events import Event

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass
class RaceFinding:
    rule: str
    message: str
    path: str = "tools/rxgbrace/detector.py"
    line: int = 1
    scenario: str = ""
    fingerprint: str = ""

    def key(self) -> Tuple:
        return (self.rule, self.message)

    def to_dict(self) -> Dict[str, object]:
        out = {
            "rule": self.rule, "message": self.message,
            "path": self.path, "line": self.line,
        }
        if self.scenario:
            out["scenario"] = self.scenario
        if self.fingerprint:
            out["fingerprint"] = self.fingerprint
        return out

    def render(self) -> str:
        where = f" [{self.scenario}]" if self.scenario else ""
        return f"{self.path}:{self.line}: {self.rule}{where} {self.message}"


def _site_loc(site: str) -> Tuple[str, int]:
    if ":" in site:
        path, _, line = site.rpartition(":")
        try:
            return path, int(line)
        except ValueError:
            pass
    return (site or "tools/rxgbrace/detector.py"), 1


# ---------------------------------------------------------------------------
# vector clocks
# ---------------------------------------------------------------------------


class _VC(dict):
    """Sparse vector clock: thread label -> logical time."""

    def join(self, other: Dict[str, int]) -> None:
        for k, v in other.items():
            if v > self.get(k, 0):
                self[k] = v


@dataclass
class _Access:
    thread: str
    epoch: int  # vc[thread] at access time
    write: bool
    locks: frozenset
    site: str


@dataclass
class _VarState:
    # last access per (thread, is_write): enough for pairwise race checks
    accesses: Dict[Tuple[str, bool], _Access] = field(default_factory=dict)


def detect(
    events: Sequence[Event],
    scenario: str = "",
    fingerprint: str = "",
) -> List[RaceFinding]:
    """Run the vector-clock + lockset pass over one totally-ordered log."""
    vc: Dict[str, _VC] = {}
    obj_vc: Dict[str, _VC] = {}
    child_init: Dict[str, _VC] = {}
    final_vc: Dict[str, _VC] = {}
    variables: Dict[Tuple[str, str], _VarState] = {}
    # lock-order graph: (held, acquired) -> witness "siteA -> siteB"
    edges: Dict[Tuple[str, str], str] = {}
    findings: List[RaceFinding] = []
    seen: Set[Tuple] = set()

    def clock(t: str) -> _VC:
        c = vc.get(t)
        if c is None:
            c = vc[t] = _VC({t: 1})
        return c

    def inc(t: str) -> None:
        c = clock(t)
        c[t] = c.get(t, 0) + 1

    for ev in events:
        t = ev.thread
        c = clock(t)
        if ev.op == "fork":
            snap = _VC(c)
            child_init[ev.target] = snap
            inc(t)
        elif ev.op == "begin":
            init = child_init.pop(t, None)
            if init is not None:
                c.join(init)
        elif ev.op == "end":
            final_vc[t] = _VC(c)
        elif ev.op == "join":
            fin = final_vc.get(ev.target)
            if fin is not None:
                c.join(fin)
        elif ev.op in ("ev_set", "notify"):
            o = obj_vc.setdefault(ev.obj, _VC())
            o.join(c)
            inc(t)
        elif ev.op in ("ev_wake", "wake"):
            if ev.variant == "notified":
                c.join(obj_vc.setdefault(ev.obj, _VC()))
        elif ev.op == "acquire":
            # lock-order edges: every lock already held -> this one
            for held in ev.locks:
                if held != ev.obj:
                    edges.setdefault((held, ev.obj), f"{ev.site}")
        elif ev.op in ("read", "write"):
            is_write = ev.op == "write"
            var = (ev.obj, ev.attr)
            st = variables.setdefault(var, _VarState())
            locks = frozenset(ev.locks)
            cur_epoch = c.get(t, 0)
            for (other_t, other_w), prev in list(st.accesses.items()):
                if other_t == t or not (is_write or other_w):
                    continue
                # HB: prev happens-before current iff prev's epoch is
                # covered by the current thread's clock entry for it
                if prev.epoch <= c.get(other_t, 0):
                    continue
                if prev.locks & locks:
                    continue  # a common lock serializes them
                pair = tuple(sorted((prev.site, ev.site)))
                key = ("RACE001", var, pair)
                if key in seen:
                    continue
                seen.add(key)
                w_site = ev.site if is_write else prev.site
                path, line = _site_loc(w_site)
                a, b = (
                    (prev, "write" if other_w else "read"),
                    (_Access(t, cur_epoch, is_write, locks, ev.site),
                     "write" if is_write else "read"),
                )
                findings.append(RaceFinding(
                    rule="RACE001",
                    path=path, line=line,
                    scenario=scenario, fingerprint=fingerprint,
                    message=(
                        f"unordered {a[1]}/{b[1]} of {ev.obj}.{ev.attr}: "
                        f"{a[0].thread} @ {a[0].site or '?'} (locks "
                        f"{sorted(a[0].locks) or '[]'}) vs {b[0].thread} @ "
                        f"{b[0].site or '?'} (locks {sorted(b[0].locks) or '[]'})"
                        f" — no fork/join/event/notify edge orders them and "
                        f"no common lock serializes them"
                    ),
                ))
            st.accesses[(t, is_write)] = _Access(
                t, cur_epoch, is_write, locks, ev.site
            )

    findings.extend(_lock_order_cycles(edges, scenario, fingerprint, seen))
    return findings


def _lock_order_cycles(
    edges: Dict[Tuple[str, str], str],
    scenario: str,
    fingerprint: str,
    seen: Set[Tuple],
) -> List[RaceFinding]:
    """Cycle detection over the acquisition graph -> RACE002."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    for vs in graph.values():
        vs.sort()
    findings: List[RaceFinding] = []
    visiting: List[str] = []
    visited: Set[str] = set()

    def dfs(node: str) -> Optional[List[str]]:
        if node in visiting:
            return visiting[visiting.index(node):] + [node]
        if node in visited:
            return None
        visiting.append(node)
        for nxt in graph.get(node, ()):
            cyc = dfs(nxt)
            if cyc is not None:
                return cyc
        visiting.pop()
        visited.add(node)
        return None

    for start in sorted(graph):
        cyc = dfs(start)
        if cyc is None:
            continue
        # canonical rotation for dedup
        body = cyc[:-1]
        k = body.index(min(body))
        canon = tuple(body[k:] + body[:k])
        key = ("RACE002", canon)
        if key in seen:
            visiting.clear()
            continue
        seen.add(key)
        witness = [
            f"{a}->{b} @ {edges.get((a, b), '?')}"
            for a, b in zip(cyc, cyc[1:])
        ]
        path, line = _site_loc(edges.get((cyc[0], cyc[1]), ""))
        findings.append(RaceFinding(
            rule="RACE002",
            path=path, line=line,
            scenario=scenario, fingerprint=fingerprint,
            message=(
                f"lock-order inversion cycle {' -> '.join(canon + (canon[0],))}"
                f"; witness acquisitions: {'; '.join(witness)} — two threads "
                f"taking these locks in opposing order can deadlock"
            ),
        ))
        visiting.clear()
    return findings


# ---------------------------------------------------------------------------
# RACE003: condition wait outside a predicate loop (AST, package-wide)
# ---------------------------------------------------------------------------


def race003_findings(root: Optional[str] = None) -> List[RaceFinding]:
    """Every ``self.<cond>.wait(...)`` in a catalogued lock-owning class
    must sit inside a ``while``/``for`` of its enclosing function."""
    from tools.rxgblint import catalog

    records = (
        catalog.lock_owning_classes(root)
        if root is not None else catalog.lock_owning_classes()
    )
    repo_root = root or catalog.REPO_ROOT
    findings: List[RaceFinding] = []
    for recd in records:
        conds = {attr for attr, kind in recd.locks if kind == "condition"}
        if not conds:
            continue
        path = os.path.join(repo_root, recd.path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        cls = _find_class(tree, recd.qualname)
        if cls is None:
            continue
        findings.extend(_check_waits(cls, conds, recd))
    return findings


def _find_class(tree: ast.Module, qualname: str) -> Optional[ast.ClassDef]:
    parts = qualname.split(".")
    body = tree.body
    node: Optional[ast.ClassDef] = None
    for part in parts:
        node = next(
            (n for n in body if isinstance(n, ast.ClassDef) and n.name == part),
            None,
        )
        if node is None:
            return None
        body = node.body
    return node


def _check_waits(
    cls: ast.ClassDef, conds: Set[str], recd
) -> List[RaceFinding]:
    findings: List[RaceFinding] = []

    def walk(node: ast.AST, loop_depth: int, fn: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node.name
            loop_depth = 0  # a loop outside the function does not re-check
        if isinstance(node, (ast.While, ast.For)):
            loop_depth += 1
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            callee = node.func
            if callee.attr == "wait":
                owner = callee.value
                if (
                    isinstance(owner, ast.Attribute)
                    and isinstance(owner.value, ast.Name)
                    and owner.value.id == "self"
                    and owner.attr in conds
                    and loop_depth == 0
                ):
                    findings.append(RaceFinding(
                        rule="RACE003",
                        path=recd.path, line=node.lineno,
                        message=(
                            f"{recd.qualname}.{fn}: self.{owner.attr}.wait() "
                            f"outside any while/for loop — a spurious or "
                            f"stolen wakeup proceeds on a stale predicate; "
                            f"re-check the predicate in a loop around the wait"
                        ),
                    ))
        for child in ast.iter_child_nodes(node):
            walk(child, loop_depth, fn)

    walk(cls, 0, cls.name)
    return findings
