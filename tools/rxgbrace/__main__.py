"""CLI: ``python -m tools.rxgbrace [--json F] [--sarif F] ...``.

Runs the RACE003 AST pass over the package, then exhaustively explores
every shipped scenario (deterministic interleavings + vector-clock/lockset
detection on each explored schedule). Exit status mirrors the other two
analysis gates: 0 = clean, 1 = findings, 2 = usage error.

``--replay scenario@i.j.k`` re-executes one recorded schedule fingerprint
and prints its event log — the bit-identical reproduction recipe for a
SCHED001/RACE001 finding.
"""

import argparse
import inspect
import json
import os
import sys


def _force_cpu() -> None:
    """The scenarios import the serve layer (and therefore jax); keep it on
    CPU and quiet, same treatment as rxgbverify."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    from tools.rxgbrace import RACE_RULES

    parser = argparse.ArgumentParser(
        prog="rxgbrace",
        description=(
            "deterministic interleaving explorer + vector-clock race "
            "detector for the threaded host plane of xgboost_ray_tpu"
        ),
    )
    parser.add_argument(
        "--json", metavar="FILE",
        help="write the machine-readable report (the CI artifact: per-"
             "scenario schedule counts + findings)",
    )
    parser.add_argument(
        "--sarif", metavar="FILE",
        help="write findings as SARIF 2.1.0 for code-review annotations",
    )
    parser.add_argument(
        "--scenario", action="append", metavar="NAME",
        help="explore only the named scenario(s) (repeatable)",
    )
    parser.add_argument(
        "--replay", metavar="FINGERPRINT",
        help="replay one schedule fingerprint (scenario@i.j.k) and print "
             "its event log",
    )
    parser.add_argument(
        "--max-schedules", type=int, default=30000,
        help="per-scenario exhaustiveness cap; hitting it is itself a "
             "finding (default 30000)",
    )
    parser.add_argument(
        "--no-prune", action="store_true",
        help="disable sleep-set pruning (slower, same findings — pinned by "
             "tests)",
    )
    parser.add_argument(
        "--list-scenarios", action="store_true",
        help="print the scenario catalog",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RACE_RULES):
            print(f"{code}: {RACE_RULES[code]}")
        return 0

    _force_cpu()
    from tools.rxgbrace import detector as det
    from tools.rxgbrace import explore as exp
    from tools.rxgbrace import scenarios as scn_mod

    if args.list_scenarios:
        for scn in scn_mod.SCENARIOS:
            print(f"{scn.name}: {scn.description}")
        return 0

    if args.replay:
        name, _ = exp.parse_fingerprint(args.replay)
        try:
            scn = scn_mod.by_name(name)
        except KeyError as e:
            print(f"rxgbrace: {e}", file=sys.stderr)
            return 2
        run = exp.replay(scn, args.replay)
        for ev in run.events:
            print(ev.key())
        print(
            f"rxgbrace replay: status={run.status} "
            f"invariant={'FAILED: ' + run.invariant_error if run.invariant_error else 'ok'} "
            f"digest={exp.events_digest(run.events)}"
        )
        return 0

    if args.scenario:
        try:
            scenarios = [scn_mod.by_name(n) for n in args.scenario]
        except KeyError as e:
            print(f"rxgbrace: {e}", file=sys.stderr)
            return 2
    else:
        scenarios = list(scn_mod.SCENARIOS)

    findings = []
    # static pass first: RACE003 over the package's condition catalog
    findings.extend(det.race003_findings())

    scenario_reports = {}
    for scn in scenarios:
        res = exp.explore(
            scn, prune=not args.no_prune, max_schedules=args.max_schedules,
        )
        scn_findings = []
        scn_line = inspect.getsourcelines(scn.body)[1]
        for fail in res.failures:
            scn_findings.append(det.RaceFinding(
                rule="SCHED001",
                path="tools/rxgbrace/scenarios.py", line=scn_line,
                scenario=scn.name, fingerprint=fail.fingerprint,
                message=(
                    f"{scn.name}: {fail.kind} — {fail.detail} "
                    f"(replay: python -m tools.rxgbrace --replay "
                    f"{fail.fingerprint or scn.name + '@'})"
                ),
            ))
        scn_findings.extend(res.races)
        findings.extend(scn_findings)
        scenario_reports[scn.name] = {
            "description": scn.description,
            "schedules": res.schedules,
            "runs": res.runs,
            "pruned": res.pruned,
            "max_choice_depth": res.max_choice_depth,
            "events": res.events_total,
            "truncated": res.truncated,
            "findings": [f.to_dict() for f in scn_findings],
            "status": "clean" if not scn_findings else "findings",
        }

    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1

    # artifacts + exit status settle BEFORE stdout (a closed pipe must not
    # turn findings into a pass — same hardening as rxgblint/rxgbverify)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(
                {
                    "tool": "rxgbrace",
                    "rules": RACE_RULES,
                    "scenarios": scenario_reports,
                    "counts": counts,
                    "findings": [f.to_dict() for f in findings],
                },
                fh, indent=2, sort_keys=True,
            )
            fh.write("\n")
    if args.sarif:
        from tools.sarif import to_sarif_json

        with open(args.sarif, "w") as fh:
            fh.write(to_sarif_json(
                "rxgbrace", RACE_RULES,
                [f.to_dict() for f in findings],
            ) + "\n")
    status = 1 if findings else 0

    try:
        for f in findings:
            print(f.render())
        n_sched = sum(r["schedules"] for r in scenario_reports.values())
        print(
            f"rxgbrace: {len(scenario_reports)} scenarios, {n_sched} "
            f"schedules explored, {len(findings)} finding(s)"
        )
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return status


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(1)
