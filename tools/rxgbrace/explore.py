"""Replay-based exhaustive schedule exploration with sleep-set pruning.

The explorer enumerates the choice tree of a scenario: a *choice point*
is any scheduler state with >=2 enabled transitions, and a schedule is the
list of indices taken at the choice points. Exploration is replay-based
stateless DFS — every tree node costs one deterministic re-execution —
with Godefroid-style sleep sets for partial-order reduction: after
exploring transition ``a`` at a state, sibling subtrees inherit ``a`` in
their sleep set for as long as ``a`` stays independent of the transitions
taken, and a sleeping transition is not re-explored.

Independence is measured, not declared: the scheduler records each macro
step's *footprint* (every sync object and ``instance.attr`` touched while
the thread held the turn — exact, because exactly one thread runs at a
time). Two transitions are independent iff they belong to different
threads and their footprints are disjoint; an unmeasured footprint is
conservatively dependent. This relies on the scenario contract (see
``scenarios.py``): scenario threads share state only through instrumented
objects, so disjoint footprints really do commute. ``prune=False``
switches to plain exhaustive DFS — the equivalence of the two on planted
bugs is pinned by tests.

A failing terminal state is captured as ``scenario@i.j.k`` — the choice
indices — which replays bit-identically (the determinism contract of the
scheduler; also pinned by tests).
"""

import hashlib
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from tools.rxgbrace.detector import RaceFinding, detect
from tools.rxgbrace.events import RunResult
from tools.rxgbrace.instrument import Instrumentation
from tools.rxgbrace.sched import Scheduler


@dataclass
class Failure:
    kind: str  # "invariant" | "deadlock" | "exception" | "overflow" | "explosion"
    fingerprint: str
    detail: str


@dataclass
class ExploreResult:
    scenario: str
    schedules: int = 0  # complete terminal schedules explored
    runs: int = 0  # total executions (tree nodes)
    pruned: int = 0  # sleep-set-pruned branches
    max_choice_depth: int = 0
    events_total: int = 0
    truncated: bool = False
    failures: List[Failure] = field(default_factory=list)
    races: List[RaceFinding] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.failures and not self.races and not self.truncated


def fingerprint_of(scenario_name: str, chosen: Sequence[int]) -> str:
    return f"{scenario_name}@{'.'.join(map(str, chosen))}"


def parse_fingerprint(fp: str) -> Tuple[str, List[int]]:
    name, _, rest = fp.partition("@")
    if not rest:
        return name, []
    return name, [int(x) for x in rest.split(".")]


def events_digest(events) -> str:
    """Stable digest of a run's full event log (replay bit-identity)."""
    h = hashlib.sha256()
    for ev in events:
        h.update(repr(ev.key()).encode())
    return h.hexdigest()[:16]


def run_scenario(scenario, forced: Sequence[int] = ()) -> RunResult:
    """One deterministic execution of ``scenario`` under ``forced``.

    The ambient fault plan (programmatic or ``RXGB_FAULT_PLAN``) is
    suspended for the run: scenario code hits real ``faults.fire()`` sites
    (registry.swap, serve.predict), so an inherited plan would both inject
    faults into the scenario and perturb the schedule count — the reported
    counts must depend on the shipped locking alone."""
    import os

    from tools.rxgbrace.events import Recorder

    ctx = scenario.new_ctx()
    recorder = Recorder()
    sched = Scheduler(recorder, forced=forced, max_steps=scenario.max_steps)
    prev_env_plan = os.environ.pop("RXGB_FAULT_PLAN", None)
    prev_plan = None
    try:
        from xgboost_ray_tpu import faults as _faults

        prev_plan = _faults._PLAN  # the programmatic slot, not the env view
        _faults.install_plan(None)
    except Exception:  # noqa: BLE001 - package import is the scenario's job
        _faults = None
    try:
        # setup INSIDE the try: a raising setup must still unwind the
        # patches it already applied (teardown restores ctx._patches) and
        # put the suspended fault plan back
        scenario.setup(ctx)
        with Instrumentation(
            recorder=recorder, controller=sched, classes=scenario.classes
        ):
            result = sched.run(lambda: scenario.body(ctx), main_name="main")
    finally:
        scenario.teardown(ctx)
        if _faults is not None:
            _faults.install_plan(prev_plan)
        if prev_env_plan is not None:
            os.environ["RXGB_FAULT_PLAN"] = prev_env_plan
    if result.status == "complete" and not result.errors:
        try:
            scenario.invariant(ctx)
        except AssertionError as exc:
            result.invariant_error = str(exc) or "invariant failed"
        except Exception as exc:  # noqa: BLE001 - an invariant crash is a failure
            result.invariant_error = f"invariant raised {exc!r}"
    return result


def _independent(
    a: Tuple, b: Tuple, footprints: Dict[Tuple, FrozenSet[str]]
) -> bool:
    if a[0] == b[0]:  # same thread: program order, never independent
        return False
    fa = footprints.get(a)
    fb = footprints.get(b)
    if fa is None or fb is None:
        return False  # unmeasured: conservatively dependent
    return not (fa & fb)


def explore(
    scenario,
    prune: bool = True,
    max_schedules: Optional[int] = None,
    collect_races: bool = True,
) -> ExploreResult:
    """Exhaustively explore ``scenario``'s schedules."""
    limit = max_schedules or scenario.max_schedules
    res = ExploreResult(scenario=scenario.name)
    footprints: Dict[Tuple, FrozenSet[str]] = {}
    race_keys = set()
    failure_keys = set()

    def evaluate(run: RunResult) -> None:
        res.schedules += 1
        res.events_total += len(run.events)
        fp = fingerprint_of(scenario.name, run.chosen)
        if run.status == "deadlock":
            key = ("deadlock", tuple(sorted(run.deadlocked)))
            if key not in failure_keys:
                failure_keys.add(key)
                res.failures.append(Failure(
                    "deadlock", fp,
                    f"threads stuck: {run.deadlocked}",
                ))
        elif run.status == "overflow":
            key = ("overflow",)
            if key not in failure_keys:
                failure_keys.add(key)
                res.failures.append(Failure(
                    "overflow", fp,
                    f"run exceeded {scenario.max_steps} transitions "
                    f"(livelock or scenario too large)",
                ))
        if run.errors:
            key = ("exception", tuple(run.errors))
            if key not in failure_keys:
                failure_keys.add(key)
                res.failures.append(Failure(
                    "exception", fp, f"uncaught in threads: {run.errors}",
                ))
        if run.invariant_error:
            key = ("invariant", run.invariant_error)
            if key not in failure_keys:
                failure_keys.add(key)
                res.failures.append(Failure("invariant", fp, run.invariant_error))
        if collect_races:
            for f in detect(run.events, scenario=scenario.name, fingerprint=fp):
                if f.key() not in race_keys:
                    race_keys.add(f.key())
                    res.races.append(f)

    def dfs(prefix: List[int], sleep: FrozenSet[Tuple]) -> None:
        if res.schedules >= limit:
            res.truncated = True
            return
        run = run_scenario(scenario, prefix)
        res.runs += 1
        for sig, foot in run.footprints.items():
            # union across runs, same reasoning as within a run: dependence
            # must be monotone or pruning loses soundness
            footprints[sig] = footprints.get(sig, frozenset()) | foot
        if len(run.choices) > res.max_choice_depth:
            res.max_choice_depth = len(run.choices)
        if len(run.choices) <= len(prefix):
            evaluate(run)
            return
        cp = run.choices[len(prefix)]
        done: List[Tuple] = []
        for i, sig in enumerate(cp.sigs):
            if prune and sig in sleep:
                res.pruned += 1
                continue
            child_sleep = frozenset(
                u for u in (set(sleep) | set(done))
                if _independent(u, sig, footprints)
            )
            dfs(prefix + [i], child_sleep)
            if res.truncated:
                return
            done.append(sig)

    dfs([], frozenset())
    if res.truncated and not any(f.kind == "explosion" for f in res.failures):
        res.failures.append(Failure(
            "explosion", "",
            f"schedule count exceeded the {limit} cap before exhaustion — "
            f"shrink the scenario or raise max_schedules",
        ))
    return res


def replay(scenario, fingerprint: str) -> RunResult:
    """Re-run the exact schedule a fingerprint names."""
    name, forced = parse_fingerprint(fingerprint)
    if name != scenario.name:
        raise ValueError(
            f"fingerprint {fingerprint!r} names scenario {name!r}, "
            f"not {scenario.name!r}"
        )
    return run_scenario(scenario, forced)
