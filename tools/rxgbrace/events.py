"""Event records and the per-run recorder.

Every instrumented operation appends one :class:`Event` to the run's
:class:`Recorder` under a RAW (never-instrumented) lock, so the log is a
total order (``seq``) consistent with real execution: ``acquire`` is
recorded while the lock is already held, ``release`` while it is still
held — two critical sections on one lock can never interleave their
events. Under the cooperative scheduler only one scenario thread runs at
a time, so the order is additionally deterministic.

Object labels (``Lock#1``, ``ModelRegistry#1``) are assigned in
first-sight order per recorder; with a deterministic schedule the same
schedule always yields the same labels, which is what makes schedule
fingerprints replay to bit-identical logs.
"""

import os
import sys
import _thread
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

#: repository root (tools/rxgbrace/ is two levels down)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: frames from these basenames are skipped when attributing a call site
_INTERNAL_FILES = frozenset({
    "events.py", "instrument.py", "sched.py", "explore.py", "detector.py",
    "threading.py", "contextlib.py",
})


@dataclass(frozen=True)
class Event:
    """One instrumented operation.

    ``op`` is one of: ``begin end fork join join_timeout acquire release
    wait notify wake ev_set ev_clear ev_wait ev_wake sleep read write``.
    ``obj`` is the sync-object or instance label; ``attr`` is set for
    read/write; ``locks`` is the thread's held lockset at the operation;
    ``target`` names the other thread for fork/join; ``variant`` is
    ``"notified"`` / ``"timeout"`` on wake-style events.
    """

    seq: int
    thread: str
    op: str
    obj: str = ""
    attr: str = ""
    locks: Tuple[str, ...] = ()
    site: str = ""
    target: str = ""
    variant: str = ""

    def key(self) -> Tuple:
        """Canonical tuple for log hashing / bit-identical replay checks."""
        return (
            self.seq, self.thread, self.op, self.obj, self.attr,
            self.locks, self.site, self.target, self.variant,
        )


def call_site() -> str:
    """Attribute the current operation to the nearest non-internal frame,
    as a repo-relative ``path:line`` string ('' when none is found)."""
    frame = sys._getframe(1)
    while frame is not None:
        fn = frame.f_code.co_filename
        if os.path.basename(fn) not in _INTERNAL_FILES:
            try:
                rel = os.path.relpath(fn, REPO_ROOT)
            except ValueError:  # different drive
                rel = fn
            if not rel.startswith(".."):
                return f"{rel.replace(os.sep, '/')}:{frame.f_lineno}"
            return f"{os.path.basename(fn)}:{frame.f_lineno}"
        frame = frame.f_back
    return ""


class Recorder:
    """Thread-safe, totally-ordered event log for one run."""

    def __init__(self):
        # raw OS lock: the recorder must never route through the
        # instrumented wrappers it serves
        self._lock = _thread.allocate_lock()
        self.events: List[Event] = []
        self._labels: Dict[int, str] = {}
        self._counts: Dict[str, int] = {}

    def label_for(self, obj: Any, kind: Optional[str] = None) -> str:
        """Stable per-run label for ``obj`` (``Kind#n`` in first-sight
        order)."""
        with self._lock:
            got = self._labels.get(id(obj))
            if got is not None:
                return got
            k = kind or type(obj).__name__
            n = self._counts.get(k, 0) + 1
            self._counts[k] = n
            label = f"{k}#{n}"
            self._labels[id(obj)] = label
            return label

    def record(
        self,
        thread: str,
        op: str,
        obj: str = "",
        attr: str = "",
        locks: Tuple[str, ...] = (),
        site: str = "",
        target: str = "",
        variant: str = "",
    ) -> Event:
        with self._lock:
            ev = Event(
                seq=len(self.events), thread=thread, op=op, obj=obj,
                attr=attr, locks=locks, site=site, target=target,
                variant=variant,
            )
            self.events.append(ev)
            return ev

    def __len__(self) -> int:
        with self._lock:
            return len(self.events)

    def snapshot(self) -> List[Event]:
        with self._lock:
            return list(self.events)


@dataclass
class ChoicePoint:
    """One branch point of a scheduled run: the enabled transition
    signatures (sorted, deterministic) and the index that was taken."""

    sigs: Tuple[Tuple, ...]
    chosen: int
    event_index: int = 0  # len(recorder) when the choice was made


@dataclass
class RunResult:
    """Outcome of one scheduled execution of a scenario."""

    status: str  # "complete" | "deadlock" | "overflow"
    events: List[Event] = field(default_factory=list)
    choices: List[ChoicePoint] = field(default_factory=list)
    errors: List[Tuple[str, str]] = field(default_factory=list)  # (thread, repr)
    deadlocked: List[Tuple[str, str]] = field(default_factory=list)  # (thread, op desc)
    footprints: Dict[Tuple, Tuple[str, ...]] = field(default_factory=dict)
    invariant_error: Optional[str] = None
    steps: int = 0

    @property
    def chosen(self) -> List[int]:
        return [c.chosen for c in self.choices]
