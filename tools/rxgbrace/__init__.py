"""rxgbrace: deterministic interleaving explorer + vector-clock race
detector for the threaded host plane.

The third static-analysis layer (and third tier-1 CI gate), covering what
rxgblint's lexical LOCK001 and rxgbverify's jaxpr checks structurally
cannot see: *interleavings*. Three parts:

1. **Instrumentation** (`instrument.py`): a context manager that
   monkeypatches ``threading.Lock/RLock/Condition/Event/Thread`` and hooks
   attribute access on the lock-owning classes from rxgblint's LOCK001
   catalog (``tools.rxgblint.catalog.lock_owning_classes`` — one catalog,
   two tools), recording per-thread event logs (acquire / release / wait /
   notify / set / read / write / fork / join). Outside the context manager
   nothing is patched and production code pays nothing.

2. **Detector** (`detector.py`): a FastTrack-style vector-clock +
   lockset pass over those logs. Ordering edges are fork/join,
   ``Event.set -> wait`` and ``Condition.notify -> wake`` (lock
   release→acquire is mutual exclusion, not ordering — the Eraser
   insight, so a race is reported even when one schedule happened to
   serialize it); properly lock-guarded state is recognized through the
   recorded locksets. RACE001 = conflicting unordered access, RACE002 =
   lock-order-inversion cycle in the global acquisition graph (the
   deadlock certificate LOCK001 cannot give), RACE003 = a condition wait
   outside a predicate re-check loop (AST pass over the same catalog).

3. **Explorer** (`sched.py` + `explore.py` + `scenarios.py`): a
   cooperative scheduler that serializes scenario threads at instrumented
   sync points and exhaustively enumerates interleavings of small
   shipped-code scenario units (registry hot-swap vs lease, batcher
   deadline-flush vs shutdown vs shed, AsyncCheckpointWriter commit vs
   driver exit, tracer emit vs snapshot, FaultPlan fire vs reset, metrics
   record vs Prometheus render, elastic pending-load vs driver poll) with
   DPOR-style sleep-set pruning. Every terminal state checks the
   scenario's invariant; a failing schedule is captured as a seedable
   fingerprint (``scenario@choice.choice. ...``) that replays
   bit-identically.

Findings flow through the shared ``tools/sarif.py`` writer; the CLI
(``python -m tools.rxgbrace``) exits 1 on any finding.
"""

from typing import Dict

#: rule code -> one-line description (the catalog printed by --list-rules,
#: embedded in the SARIF driver, and documented in README "Static analysis")
RACE_RULES: Dict[str, str] = {
    "RACE001": (
        "conflicting cross-thread access to shared state with no ordering "
        "edge (fork/join/event/notify) and disjoint locksets — a torn read "
        "or lost update some interleaving can realize"
    ),
    "RACE002": (
        "lock-order inversion: a cycle in the global lock-acquisition "
        "graph (thread holds A while taking B elsewhere B is held while "
        "taking A) — a deadlock certificate, independent of whether this "
        "run deadlocked"
    ),
    "RACE003": (
        "condition wait outside a predicate re-check loop — a spurious or "
        "stolen wakeup proceeds on a stale predicate"
    ),
    "SCHED001": (
        "a scenario invariant failed (or the scenario deadlocked) at an "
        "explored terminal state; the attached schedule fingerprint "
        "replays the failing interleaving bit-identically"
    ),
}

__all__ = ["RACE_RULES"]
