"""Cooperative deterministic scheduler for scenario exploration.

Exactly one scenario thread runs at any moment. Scenario threads park on a
per-thread gate at every instrumented *blocking-capable* sync point
(lock/condition acquire, waits, joins, sleeps); fast operations (release,
notify, event set, attribute access) execute inline while the thread holds
the turn, so a context switch can occur exactly at the instrumented sync
points — the classic schedule-at-synchronization granularity.

Time is logical: ``time.monotonic()/time()/perf_counter()`` (patched by
the instrumentation layer) read the scheduler clock, which advances a
microtick per transition and by the full timeout when the scheduler
*chooses* to fire a timed wait. A timed wait is therefore a scheduling
CHOICE with two transitions — "woken by its signal" and "timed out" —
which is what lets the explorer drive deadline-flush-vs-shutdown style
interleavings deterministically.

Determinism contract: a scenario run under the same forced choice list
produces the identical event log (labels, seqs, sites) — scenario code
must not consult real time, real randomness, or OS identifiers; the
instrumented clock and seeded RNGs keep the shipped scenarios inside
that contract.
"""

from typing import List, Optional, Tuple

from tools.rxgbrace import instrument as ins
from tools.rxgbrace.events import ChoicePoint, Recorder, RunResult, call_site

_EPS = 1e-6  # clock microtick per transition


class Managed:
    """Scheduler-side state of one scenario thread."""

    __slots__ = (
        "label", "thread", "gate", "state", "pending", "op_result",
        "killed", "error", "scheduler", "idx",
    )

    def __init__(self, scheduler, thread, label: str, idx: int):
        self.scheduler = scheduler
        self.thread = thread
        self.label = label
        self.idx = idx
        self.gate = ins.RawGate()
        self.state = "new"  # new | waiting | running | done
        self.pending = None  # dict describing the parked operation
        self.op_result = None
        self.killed = False
        self.error: Optional[BaseException] = None


class Scheduler:
    """Controller driving managed threads one transition at a time."""

    def __init__(self, recorder: Recorder, forced=(), max_steps: int = 4000):
        self.recorder = recorder
        self.forced: List[int] = list(forced)
        self.max_steps = max_steps
        self.threads: List[Managed] = []
        self.clock = 0.0
        self.steps = 0
        self.choices: List[ChoicePoint] = []
        self.footprints = {}
        self.status = "complete"
        self.deadlocked: List[Tuple[str, str]] = []
        self.aborting = False
        self._returned = ins.RawGate()
        self._running: Optional[Managed] = None
        self._labels = set()

    # -- registration / lifecycle -------------------------------------------

    def _register(self, thread) -> Managed:
        base = thread.name or "thread"
        label = base
        n = 1
        while label in self._labels:
            n += 1
            label = f"{base}#{n}"
        self._labels.add(label)
        m = Managed(self, thread, label, len(self.threads))
        # park-state is set HERE, before the OS thread exists: the scheduler
        # loop may inspect it before the child ever runs
        m.pending = {"op": "begin"}
        m.state = "waiting"
        thread._rxgb_managed = m
        self.threads.append(m)
        return m

    def thread_spawn(self, thread) -> None:
        """Called from a RUNNING managed thread creating a child."""
        parent = ins._tls.managed
        m = self._register(thread)
        self.recorder.record(
            parent.label, "fork", target=m.label,
            locks=ins._lockset(), site=call_site(),
        )
        ins._REAL_THREAD.start(thread)
        # child's OS thread parks in thread_begin; no turn handoff happens

    def thread_begin(self, m: Managed) -> None:
        """First action of a managed OS thread: park until granted. The
        park state was already published by ``_register`` (before the OS
        thread started), so this only waits — and does NOT signal
        ``_returned``: the spawning parent still holds the turn."""
        m.gate.wait()
        m.gate.clear()
        if m.killed:
            raise ins._Killed()

    def thread_end(self, m: Managed) -> None:
        m.state = "done"
        if not self.aborting:
            self.recorder.record(m.label, "end")
        if self._running is m:
            self._returned.set()

    def thread_join(self, thread, timeout: Optional[float]):
        target = getattr(thread, "_rxgb_managed", None)
        if target is None:
            return None  # joining an unmanaged thread: nothing to wait for
        res = self._call({"op": "join", "target": target, "timeout": timeout})
        rec = self.recorder
        me = ins._tls.managed
        if res:
            rec.record(
                me.label, "join", target=target.label,
                locks=ins._lockset(), site=call_site(),
            )
        else:
            rec.record(
                me.label, "join_timeout", target=target.label,
                locks=ins._lockset(), site=call_site(),
            )
        return None

    # -- thread-side yield protocol -----------------------------------------

    def _call(self, op):
        m = ins._tls.managed
        if m.killed or self.aborting:
            raise ins._Killed()
        m.pending = op
        m.state = "waiting"
        self._returned.set()
        m.gate.wait()
        m.gate.clear()
        if m.killed:
            raise ins._Killed()
        return m.op_result

    # -- controller API used by the wrappers --------------------------------

    def now(self) -> float:
        return self.clock

    def sleep(self, secs: float) -> None:
        self._call({"op": "sleep", "dur": max(0.0, float(secs or 0.0))})

    def lock_acquire(self, lock, blocking=True, reentrant=False) -> bool:
        res = self._call({
            "op": "acquire", "lock": lock, "blocking": blocking,
            "reentrant": reentrant,
        })
        if res:
            me = ins._tls.managed
            self.recorder.record(
                me.label, "acquire", obj=self.recorder.label_for(lock, lock._kind),
                locks=ins._lockset(), site=call_site(),
            )
            ins._held_add(self.recorder.label_for(lock, lock._kind))
        return res

    def lock_release(self, lock, reentrant=False) -> None:
        me = ins._tls.managed
        label = self.recorder.label_for(lock, lock._kind)
        self.recorder.record(
            me.label, "release", obj=label,
            locks=ins._lockset(), site=call_site(),
        )
        ins._held_remove(label)
        if reentrant and lock._v_count > 1:
            lock._v_count -= 1
        else:
            lock._v_owner = None
            if reentrant:
                lock._v_count = 0

    def cond_wait(self, cond, timeout: Optional[float]) -> bool:
        me = ins._tls.managed
        lock = cond._lock
        cond_label = self.recorder.label_for(cond, cond._kind)
        lock_label = self.recorder.label_for(lock, lock._kind)
        self.recorder.record(
            me.label, "wait", obj=cond_label,
            locks=ins._lockset(), site=call_site(),
        )
        # release the lock and enqueue as a waiter (fast, still our turn).
        # Like threading's _release_save, an RLock is released FULLY and
        # its recursion count restored on reacquire.
        saved_count = getattr(lock, "_v_count", 0)
        lock._v_owner = None
        if hasattr(lock, "_v_count"):
            lock._v_count = 0
        ins._held_remove(lock_label)
        cond._v_waiters.append(me)
        res = self._call({
            "op": "cond_wait", "cond": cond, "lock": lock,
            "timeout": timeout, "phase": "waiting", "result": None,
            "saved_count": saved_count,
        })
        ins._held_add(lock_label)
        self.recorder.record(
            me.label, "wake", obj=cond_label,
            variant="notified" if res else "timeout",
            locks=ins._lockset(), site=call_site(),
        )
        self.recorder.record(
            me.label, "acquire", obj=lock_label,
            locks=ins._lockset(), site=call_site(),
        )
        return res

    def cond_notify(self, cond, n: int) -> None:
        me = ins._tls.managed
        self.recorder.record(
            me.label, "notify", obj=self.recorder.label_for(cond, cond._kind),
            locks=ins._lockset(), site=call_site(),
        )
        woken = 0
        remaining = []
        for w in cond._v_waiters:
            if woken < n and w.pending and w.pending.get("phase") == "waiting":
                w.pending["phase"] = "reacquire"
                w.pending["result"] = True
                woken += 1
            else:
                remaining.append(w)
        cond._v_waiters[:] = remaining

    def ev_set(self, event) -> None:
        me = ins._tls.managed
        event._v_set = True
        self.recorder.record(
            me.label, "ev_set", obj=self.recorder.label_for(event, event._kind),
            locks=ins._lockset(), site=call_site(),
        )
        for m in self.threads:
            if (
                m.pending
                and m.pending.get("op") == "ev_wait"
                and m.pending.get("event") is event
            ):
                m.pending["ready"] = True

    def ev_wait(self, event, timeout: Optional[float]) -> bool:
        me = ins._tls.managed
        label = self.recorder.label_for(event, event._kind)
        self.recorder.record(
            me.label, "ev_wait", obj=label,
            locks=ins._lockset(), site=call_site(),
        )
        res = self._call({
            "op": "ev_wait", "event": event, "timeout": timeout,
            "ready": event._v_set,
        })
        self.recorder.record(
            me.label, "ev_wake", obj=label,
            variant="notified" if res else "timeout",
            locks=ins._lockset(), site=call_site(),
        )
        return bool(res)

    # -- the exploration loop -----------------------------------------------

    def _enabled(self):
        """Enabled transitions, deterministically ordered by registration.
        Each is ``(managed, variant, sig)``; sig = (thread label, op,
        object label, variant)."""
        out = []
        for m in self.threads:
            if m.state != "waiting" or m.pending is None:
                continue
            op = m.pending
            kind = op["op"]
            if kind == "begin":
                out.append((m, "run", (m.label, "begin", "", "")))
            elif kind == "sleep":
                out.append((m, "go", (m.label, "sleep", "", "")))
            elif kind == "acquire":
                lock = op["lock"]
                label = self.recorder.label_for(lock, lock._kind)
                free = lock._v_owner is None
                mine = op["reentrant"] and lock._v_owner is m
                if free or mine:
                    out.append((m, "take", (m.label, "acquire", label, "")))
                elif not op["blocking"]:
                    out.append((m, "fail", (m.label, "acquire", label, "fail")))
            elif kind == "cond_wait":
                cond = op["cond"]
                clabel = self.recorder.label_for(cond, cond._kind)
                if op["phase"] == "waiting":
                    if op["timeout"] is not None:
                        out.append(
                            (m, "timeout", (m.label, "cond_wait", clabel, "timeout"))
                        )
                else:  # reacquire
                    lock = op["lock"]
                    if lock._v_owner is None:
                        out.append(
                            (m, "take", (m.label, "cond_wait", clabel, "reacquire"))
                        )
            elif kind == "ev_wait":
                ev = op["event"]
                elabel = self.recorder.label_for(ev, ev._kind)
                if op.get("ready"):
                    out.append((m, "go", (m.label, "ev_wait", elabel, "")))
                elif op["timeout"] is not None:
                    out.append(
                        (m, "timeout", (m.label, "ev_wait", elabel, "timeout"))
                    )
            elif kind == "join":
                target = op["target"]
                if target.state == "done":
                    out.append((m, "go", (m.label, "join", target.label, "")))
                elif op["timeout"] is not None:
                    out.append(
                        (m, "timeout", (m.label, "join", target.label, "timeout"))
                    )
        return out

    def _grant(self, m: Managed, result) -> None:
        m.op_result = result
        m.pending = None
        m.state = "running"
        self._running = m
        self._returned.clear()
        m.gate.set()
        self._returned.wait()
        self._running = None

    def _apply(self, m: Managed, variant: str, sig) -> None:
        op = m.pending
        kind = op["op"]
        self.clock += _EPS
        start_idx = len(self.recorder)
        granted = True
        if kind == "begin":
            self.recorder.record(m.label, "begin")
            self._grant(m, None)
        elif kind == "sleep":
            self.clock += op["dur"]
            self._grant(m, None)
        elif kind == "acquire":
            if variant == "fail":
                self._grant(m, False)
            else:
                lock = op["lock"]
                if op["reentrant"] and lock._v_owner is m:
                    lock._v_count += 1
                else:
                    lock._v_owner = m
                    if op["reentrant"]:
                        lock._v_count = 1
                self._grant(m, True)
        elif kind == "cond_wait":
            if variant == "timeout":
                # fire the timeout: thread moves to the reacquire phase
                # without running user code (threading semantics: a timed
                # wait reacquires the lock before returning False)
                self.clock += op["timeout"] or 0.0
                op["phase"] = "reacquire"
                op["result"] = False
                cond = op["cond"]
                cond._v_waiters[:] = [w for w in cond._v_waiters if w is not m]
                granted = False
            else:  # take (reacquire the lock, return result)
                lock = op["lock"]
                lock._v_owner = m
                if hasattr(lock, "_v_count"):
                    # _acquire_restore: the recursion count from before wait
                    lock._v_count = op.get("saved_count") or 1
                self._grant(m, op["result"])
        elif kind == "ev_wait":
            if variant == "timeout":
                self.clock += op["timeout"] or 0.0
                self._grant(m, False)
            else:
                self._grant(m, True)
        elif kind == "join":
            if variant == "timeout":
                self.clock += op["timeout"] or 0.0
                self._grant(m, False)
            else:
                self._grant(m, True)
        # footprint of the macro step: everything recorded while the thread
        # held the turn (single-threaded execution makes this exact). The
        # transition's own object is ALWAYS included (a failed try-acquire
        # or fired timeout still conflicts on its lock), and a signature
        # seen with several different footprints accumulates their UNION —
        # last-wins would let a recurring acquire with a different critical
        # section body masquerade as independent and unsoundly prune.
        foot = set()
        for ev in self.recorder.events[start_idx:]:
            if ev.obj:
                foot.add(f"{ev.obj}.{ev.attr}" if ev.attr else ev.obj)
            if ev.target:
                foot.add(f"thread:{ev.target}")
        if sig[2]:
            foot.add(sig[2])
        self.footprints[sig] = self.footprints.get(sig, frozenset()) | foot

    def run(self, main_fn, main_name: str = "main") -> RunResult:
        """Drive ``main_fn`` (and every thread it spawns) to a terminal
        state; returns the RunResult with choices + events."""
        thread = ins.TThread(target=main_fn, name=main_name, daemon=False)
        m = self._register(thread)
        ins._REAL_THREAD.start(thread)
        while True:
            if self.steps >= self.max_steps:
                self.status = "overflow"
                break
            if all(
                t.state == "done" for t in self.threads if not t.thread.daemon
            ):
                # every non-daemon thread finished: scheduling leftover
                # daemons (a parked batcher flusher, an abandoned writer) is
                # exactly what real interpreter exit skips
                self.status = "complete"
                break
            trans = self._enabled()
            if not trans:
                blocked = [
                    t for t in self.threads
                    if t.state not in ("done",) and not t.thread.daemon
                ]
                if blocked:
                    self.status = "deadlock"
                    self.deadlocked = [
                        (t.label, str((t.pending or {}).get("op")))
                        for t in blocked
                    ]
                else:
                    self.status = "complete"
                break
            idx = 0
            if len(trans) > 1:
                if self.forced:
                    idx = self.forced.pop(0)
                    if idx >= len(trans):
                        idx = 0  # schedule no longer matches; degrade gracefully
                self.choices.append(ChoicePoint(
                    sigs=tuple(t[2] for t in trans), chosen=idx,
                    event_index=len(self.recorder),
                ))
            chosen = trans[idx]
            self.steps += 1
            self._apply(chosen[0], chosen[1], chosen[2])
        events = self.recorder.snapshot()
        errors = [
            (t.label, repr(t.error)) for t in self.threads if t.error is not None
        ]
        self._cleanup()
        return RunResult(
            status=self.status, events=events, choices=self.choices,
            errors=errors, deadlocked=self.deadlocked,
            footprints=dict(self.footprints), steps=self.steps,
        )

    def _cleanup(self) -> None:
        """Abandon every unfinished thread: the next instrumented operation
        each performs raises ``_Killed``, unwinding it."""
        self.aborting = True
        for m in self.threads:
            if m.state != "done":
                m.killed = True
                m.gate.set()
        for m in self.threads:
            ins._REAL_THREAD.join(m.thread, 2.0)

    def is_managed_current(self) -> bool:
        return getattr(ins._tls, "managed", None) is not None
