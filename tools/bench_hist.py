"""Microbenchmark the histogram implementations (the tpu_hist hot op).

Run on real hardware to pin ``resolve_hist_impl``'s accelerator default:

    python tools/bench_hist.py                    # ambient backend
    JAX_PLATFORMS=cpu python tools/bench_hist.py  # CPU sanity

Prints per-(impl, n_nodes) timings plus a full build_tree comparison; the
winning impl per fan-out regime is what `mixed` should select.
"""

import argparse
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--features", type=int, default=28)
    parser.add_argument("--max-bin", type=int, default=256)
    parser.add_argument("--depth", type=int, default=6)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--impls", nargs="+",
                        default=["scatter", "onehot", "partition"])
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from xgboost_ray_tpu.ops import binning
    from xgboost_ray_tpu.ops.grow import GrowConfig, build_tree
    from xgboost_ray_tpu.ops.histogram import build_histogram
    from xgboost_ray_tpu.ops.split import SplitParams

    print(f"backend={jax.default_backend()} rows={args.rows} "
          f"features={args.features} bins={args.max_bin}")

    rng = np.random.RandomState(0)
    nbt = args.max_bin + 1
    bins_np = rng.randint(0, nbt, size=(args.rows, args.features))
    bins = jnp.asarray(bins_np.astype(
        np.uint8 if nbt <= 256 else np.int16))
    gh = jnp.asarray(rng.randn(args.rows, 2).astype(np.float32))

    for n_nodes in (1, 8, 64):
        pos = jnp.asarray(
            rng.randint(0, n_nodes, size=args.rows).astype(np.int32))
        for impl in args.impls:
            try:
                fn = jax.jit(
                    lambda b, g, p, impl=impl, nn=n_nodes: build_histogram(
                        b, g, p, nn, nbt, impl=impl))
                fn(bins, gh, pos).block_until_ready()  # compile
                t0 = time.time()
                for _ in range(args.repeats):
                    fn(bins, gh, pos).block_until_ready()
                dt = (time.time() - t0) / args.repeats
                print(f"  hist n_nodes={n_nodes:3d} {impl:10s} {dt * 1e3:9.2f} ms")
            except Exception as exc:  # noqa: BLE001
                print(f"  hist n_nodes={n_nodes:3d} {impl:10s} FAILED: "
                      f"{str(exc)[:80]}")

    # full tree builds (includes partition-order maintenance, split search)
    x = rng.randn(args.rows, args.features).astype(np.float32)
    cuts = jnp.asarray(binning.sketch_cuts_np(x[:100_000], args.max_bin))
    for impl in args.impls + ["mixed"]:
        try:
            cfg = GrowConfig(max_depth=args.depth, max_bin=args.max_bin,
                             split=SplitParams(), hist_impl=impl)
            fn = jax.jit(lambda b, g: build_tree(b, g, cuts, cfg)[1])
            fn(bins, gh).block_until_ready()
            t0 = time.time()
            for _ in range(args.repeats):
                fn(bins, gh).block_until_ready()
            dt = (time.time() - t0) / args.repeats
            print(f"  tree depth={args.depth} {impl:10s} {dt * 1e3:9.2f} ms")
        except Exception as exc:  # noqa: BLE001
            print(f"  tree depth={args.depth} {impl:10s} FAILED: {str(exc)[:80]}")


if __name__ == "__main__":
    main()
