"""Microbenchmark the histogram implementations (the tpu_hist hot op).

Run on real hardware to pin ``resolve_hist_impl``'s accelerator default:

    python tools/bench_hist.py                    # ambient backend
    JAX_PLATFORMS=cpu python tools/bench_hist.py  # CPU sanity

Prints per-(impl, n_nodes) timings plus a full build_tree comparison; the
winning impl per fan-out regime is what `mixed` should select.

Timing methodology (matters on the axon TPU tunnel): ``block_until_ready``
does not reliably block there, and every host read costs a ~90 ms relay
round trip. Each kernel is therefore repeated R times inside one jitted
``lax.scan`` (inputs perturbed per iteration so XLA cannot CSE the body)
and synced with a single scalar host read; the relay overhead is measured
separately and subtracted.
"""

import argparse
import time

import numpy as np


def _measure_overhead(jax, jnp):
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))
    t0 = time.time()
    for _ in range(3):
        v = float(f(x))
    return (time.time() - t0) / 3


def _time_scanned(jax, jnp, make_body, operands, repeats, overhead,
                  slow_cutoff=2.0):
    """Time make_body(i, *operands): single-call probe first, scan-repeat
    refinement when the single call is fast enough to be overhead-dominated.
    Operands are jit arguments (not closed-over constants) so the traced
    program matches production shapes."""
    single = jax.jit(lambda i, *ops: make_body(i, *ops))
    float(single(jnp.int32(0), *operands))  # compile
    t0 = time.time()
    v = float(single(jnp.int32(1), *operands))
    t1 = max(0.0, time.time() - t0 - overhead)
    assert np.isfinite(v)
    if t1 > slow_cutoff:
        return t1  # slow enough that the relay overhead is noise

    def prog(seed, *ops):
        def body(carry, i):
            out = make_body(i, *ops)
            return carry + out, None

        total, _ = jax.lax.scan(
            body, jnp.float32(0.0), jnp.arange(repeats, dtype=jnp.int32)
        )
        return total + seed

    fn = jax.jit(prog)
    float(fn(jnp.float32(0.0), *operands))  # compile
    t0 = time.time()
    v = float(fn(jnp.float32(1.0), *operands))
    dt = time.time() - t0
    assert np.isfinite(v)
    return max(0.0, dt - overhead) / repeats


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--features", type=int, default=28)
    parser.add_argument("--max-bin", type=int, default=256)
    parser.add_argument("--depth", type=int, default=6)
    parser.add_argument("--repeats", type=int, default=8)
    parser.add_argument("--impls", nargs="+",
                        default=["scatter", "onehot", "partition"])
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from xgboost_ray_tpu.ops import binning
    from xgboost_ray_tpu.ops.grow import GrowConfig, build_tree
    from xgboost_ray_tpu.ops.histogram import build_histogram
    from xgboost_ray_tpu.ops.split import SplitParams

    print(f"backend={jax.default_backend()} rows={args.rows} "
          f"features={args.features} bins={args.max_bin}", flush=True)

    overhead = _measure_overhead(jax, jnp)
    print(f"  host-read overhead {overhead * 1e3:.1f} ms (subtracted)",
          flush=True)

    rng = np.random.RandomState(0)
    nbt = args.max_bin + 1
    bins_np = rng.randint(0, nbt, size=(args.rows, args.features))
    bins = jnp.asarray(bins_np.astype(
        np.uint8 if nbt <= 256 else np.int16))
    gh = jnp.asarray(rng.randn(args.rows, 2).astype(np.float32))

    for n_nodes in (1, 8, 64):
        pos = jnp.asarray(
            rng.randint(0, n_nodes, size=args.rows).astype(np.int32))
        for impl in args.impls:
            try:
                def body(i, b, g0, p, impl=impl, nn=n_nodes):
                    # perturb gh by the iteration index so XLA cannot CSE
                    g = g0 + (i.astype(jnp.float32) * 1e-12)
                    h = build_histogram(b, g, p, nn, nbt, impl=impl)
                    return h.sum()

                dt = _time_scanned(jax, jnp, body, (bins, gh, pos),
                                   args.repeats, overhead)
                print(f"  hist n_nodes={n_nodes:3d} {impl:10s} "
                      f"{dt * 1e3:9.2f} ms", flush=True)
            except Exception as exc:  # noqa: BLE001
                print(f"  hist n_nodes={n_nodes:3d} {impl:10s} FAILED: "
                      f"{str(exc)[:120]}", flush=True)

    # full tree builds (includes partition-order maintenance, split search)
    x = rng.randn(args.rows, args.features).astype(np.float32)
    cuts = jnp.asarray(binning.sketch_cuts_np(x[:100_000], args.max_bin))
    for impl, prec in [(i, p) for i in args.impls + ["mixed"]
                       for p in ("fast", "highest")]:
        try:
            cfg = GrowConfig(max_depth=args.depth, max_bin=args.max_bin,
                             split=SplitParams(), hist_impl=impl,
                             hist_precision=prec)

            def body(i, b, g0, c, cfg=cfg):
                g = g0 + (i.astype(jnp.float32) * 1e-12)
                tree = build_tree(b, g, c, cfg)[0]
                return tree.value.sum()

            dt = _time_scanned(jax, jnp, body, (bins, gh, cuts),
                               max(2, args.repeats // 2), overhead)
            print(f"  tree depth={args.depth} {impl:10s} {prec:8s} "
                  f"{dt * 1e3:9.2f} ms", flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"  tree depth={args.depth} {impl:10s} {prec:8s} FAILED: "
                  f"{str(exc)[:120]}", flush=True)


if __name__ == "__main__":
    main()
