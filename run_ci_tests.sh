#!/bin/bash
# Ordered fail-fast test runner (parity with the reference's run_ci_tests.sh).
set -e
cd "$(dirname "$0")"
echo "================= rxgblint static analysis (tier-1 gate) ================="
# fails on any non-baselined finding; the JSON artifact lets future PRs
# diff finding counts (tools/rxgblint/baseline.json holds justified ones)
python -m tools.rxgblint xgboost_ray_tpu --json /tmp/rxgblint.json --sarif /tmp/rxgblint.sarif
echo "================= rxgbverify jaxpr verification (tier-1 gate) ================="
# second static-analysis layer: re-traces every compiled program the full
# config matrix (grower x hist_quant x sampling x world 2/4/8) can produce
# and checks collective-schedule identity / precision flow / drift
# fingerprints on the jaxprs; exits non-zero on any finding. The JSON
# artifact (incl. per-program fingerprints) is what future PRs diff.
python -m tools.rxgbverify --json /tmp/rxgbverify.json --sarif /tmp/rxgbverify.sarif --fingerprints /tmp/rxgbverify_fingerprints.json
echo "================= rxgbrace interleaving exploration (tier-1 gate) ================="
# third static-analysis layer, schedule-level: exhaustively explores the
# threaded host plane's scenario units under a deterministic cooperative
# scheduler (DPOR sleep-set pruning) and runs the vector-clock + lockset
# race detector over every explored schedule; exits non-zero on any
# RACE*/SCHED* finding. Failing schedules replay bit-identically via
# `python -m tools.rxgbrace --replay <fingerprint>`.
python -m tools.rxgbrace --json /tmp/rxgbrace.json --sarif /tmp/rxgbrace.sarif
python -m pytest tests/test_lint.py -v -x
python -m pytest tests/test_verify.py -v -x
python -m pytest tests/test_race.py -v -x
python -m pytest tests/test_matrix.py -v -x
python -m pytest tests/test_data_source.py -v -x
python -m pytest tests/test_ops.py -v -x
python -m pytest tests/test_engine.py -v -x
python -m pytest tests/test_sampling.py -v -x
python -m pytest tests/test_gh_precision.py -v -x
python -m pytest tests/test_streaming.py -v -x
python -m pytest tests/test_bench_tripwire.py -v -x
python -m pytest tests/test_obs.py -v -x
python -m pytest tests/test_serve_pool.py -v -x
python -m pytest tests/test_end_to_end.py -v -x
python -m pytest tests/test_fault_tolerance.py -v -x
python -m pytest tests/test_faults.py -v -x
python -m pytest tests/test_elastic_continuation.py -v -x -m 'not slow'
python -m pytest tests/test_xgboost_api.py -v -x
python -m pytest tests/test_tune.py -v -x
python -m pytest tests/test_sklearn.py -v -x
echo "================= Running smoke benchmark ================="
# explicit PYTHONPATH: the script lives in tests/release/, so sys.path[0]
# is NOT the repo root (same treatment as the elastic smoke below)
PYTHONPATH=".:$PYTHONPATH" python tests/release/benchmark_tpu.py 2 10 8 --smoke-test
echo "================= Running chaos smoke (bench --chaos) ================="
BENCH_CHAOS_ROWS=2000 BENCH_CHAOS_ROUNDS=6 python bench.py --chaos
echo "========= Running low-precision wire smoke (bench --lowprec) ========="
# gh int8/int16 arms plus the composed row/block wire arms: gh byte cut,
# block wire byte cut, and the scale-aware logloss gates must all hold at
# smoke shape (the strict 5e-4 block-vs-row parity engages at >=100k rows)
BENCH_LOW_PRECISION_ROWS=4000 BENCH_LOW_PRECISION_ROUNDS=4 \
    python bench.py --lowprec
echo "========= Running large-measurement smoke (bench --large) ========="
# the composed headline run at smoke rows: streamed synthetic ingest x
# int8 gh x int8_block wire vs the f32 reference — memory budget, wire
# byte cut, and the relative logloss envelope are real gates even small
BENCH_LARGE_ROWS=20000 BENCH_LARGE_ROUNDS=4 python bench.py --large
echo "========= Running elastic-continuation chaos smoke (kill + reintegrate) ========="
PYTHONPATH=".:$PYTHONPATH" \
RXGB_FAULT_PLAN='{"rules": [{"site": "actor.train_round", "action": "raise", "ranks": [1], "match": {"round": 3}}]}' \
    python examples/elastic_continuation.py
echo "========= Running 2D-mesh elastic-continuation chaos smoke ========="
# the same kill on the 2D (R, C) row x feature mesh: the shrink/grow path
# must absorb it in-flight (feature tiles fixed, zero rounds replayed)
PYTHONPATH=".:$PYTHONPATH" \
RXGB_SMOKE_FEATURE_PARALLEL=2 \
RXGB_FAULT_PLAN='{"rules": [{"site": "actor.train_round", "action": "raise", "ranks": [1], "match": {"round": 3}}]}' \
    python examples/elastic_continuation.py
echo "========= Running streamed elastic-continuation chaos smoke ========="
# the same kill on a streamed (out-of-core) matrix: continuation reuses the
# survivors' binned blocks + frozen cuts (zero re-stream, zero re-sketch)
PYTHONPATH=".:$PYTHONPATH" \
RXGB_SMOKE_STREAM=1 \
RXGB_FAULT_PLAN='{"rules": [{"site": "actor.train_round", "action": "raise", "ranks": [1], "match": {"round": 3}}]}' \
    python examples/elastic_continuation.py
echo "========= Running domain-kill elastic-continuation chaos smoke ========="
# correlated host loss: RXGB_FAULT_DOMAINS=2 partitions the 4 ranks into 2
# fault domains and the plan kills ALL of domain 1 (ranks 2+3) at once —
# the deaths must coalesce into ONE recovery (zero replay, no restart,
# domains_lost/deaths_coalesced reported) and the world must be restored
PYTHONPATH=".:$PYTHONPATH" \
RXGB_SMOKE_ACTORS=4 \
RXGB_FAULT_DOMAINS=2 \
RXGB_FAULT_PLAN='{"rules": [{"site": "actor.train_round", "action": "domain_kill", "domain": 1, "ranks": [2], "match": {"round": 3}}]}' \
    python examples/elastic_continuation.py
