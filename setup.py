"""Package metadata (parity with the reference's ``setup.py:1-25``)."""

from setuptools import find_packages, setup

setup(
    name="xgboost_ray_tpu",
    packages=find_packages(include=["xgboost_ray_tpu", "xgboost_ray_tpu.*"]),
    version="0.1.0",
    author="xgboost_ray_tpu authors",
    description="TPU-native distributed gradient-boosted-tree training with "
    "the xgboost_ray API: JAX/XLA/Pallas tpu_hist learner over a device mesh.",
    long_description="A standalone re-design of ray-project/xgboost_ray for "
    "TPU: mesh workers instead of Ray actors, psum histogram allreduce "
    "instead of Rabit, and an HBM-resident quantile-binned matrix instead "
    "of the xgboost C++ DMatrix.",
    url="https://github.com/example/xgboost_ray_tpu",
    install_requires=[
        "jax",
        "numpy",
        "pandas",
        "packaging",
    ],
    extras_require={
        "sklearn": ["scikit-learn"],
        "parquet": ["pyarrow"],
    },
    python_requires=">=3.9",
)
