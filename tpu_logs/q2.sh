#!/bin/bash
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
echo "=== time_rounds start $(date +%T) ===" >> tpu_logs/bench.log
timeout 2400 python tpu_logs/time_rounds.py >> tpu_logs/bench.log 2>&1
echo "=== exit=$? $(date +%T) ===" >> tpu_logs/bench.log
