#!/bin/bash
# Round-3 tunnel watchdog: probe the axon TPU backend until it comes up.
# Appends one line per attempt to r3_probe.log; writes TUNNEL_UP marker file
# on success and exits. Single-client tunnel: this only probes, never holds
# the device (the probe process exits immediately after listing devices).
L=/root/repo/tpu_logs
while true; do
  ts=$(date +%T)
  out=$(timeout 240 python -c "import jax; print('DEVS', jax.devices())" 2>&1 | tail -2)
  if echo "$out" | grep -q "DEVS"; then
    echo "$ts UP: $out" >> $L/r3_probe.log
    touch $L/TUNNEL_UP
    exit 0
  fi
  echo "$ts down: $(echo "$out" | tr '\n' ' ' | cut -c1-160)" >> $L/r3_probe.log
  sleep 180
done
