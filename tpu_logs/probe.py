import json, time, sys
t0 = time.time()
try:
    import jax
    devs = jax.devices()
    out = {"ok": True, "devices": [str(d) for d in devs], "platform": devs[0].platform, "t_init_s": round(time.time()-t0, 1)}
except Exception as e:
    out = {"ok": False, "error": repr(e)[:500], "t_init_s": round(time.time()-t0, 1)}
print(json.dumps(out), flush=True)
