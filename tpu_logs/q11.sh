#!/bin/bash
# contingency: if steady2/higgs_full2 failed or timed out, retry with the
# pallas kernel disabled (einsum deep path) to isolate infra hangs
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/root/repo/tpu_logs
while ! grep -q "Q10 ALL DONE" $L/r2.log; do sleep 30; done
run() { echo "=== $1 start $(date +%T) ===" >> $L/r2.log; timeout "$2" "${@:3}" >> $L/r2.log 2>&1; echo "=== $1 exit=$? $(date +%T) ===" >> $L/r2.log; }
if ! grep -q "higgs11m_100r_train_wall_clock" $L/r2.log; then
  export RXGB_DISABLE_PALLAS=1
  run higgs_nopallas 4500 python bench.py
fi
echo "Q11 ALL DONE $(date +%T)" >> $L/r2.log
