"""Time each component of a level build at 1M rows on TPU."""
import time
import numpy as np
import jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu.ops.histogram import (
    hist_onehot, hist_partition_presorted, presorted_block_layout,
    select_small_child_rows, update_partition_order, _blocked_hist)
from xgboost_ray_tpu.ops import hist_pallas as hp

def overhead():
    f = jax.jit(lambda x: x + 1.0); x = jnp.float32(0.0); float(f(x))
    t0 = time.time()
    for _ in range(3): float(f(x))
    return (time.time() - t0) / 3

def timeit(name, fn, *ops, repeats=8):
    jfn = jax.jit(lambda i, *a: fn(i, *a))
    float(jfn(jnp.int32(0), *ops))
    t0 = time.time(); v = float(jfn(jnp.int32(1), *ops)); t1 = max(0.0, time.time()-t0-OH)
    if t1 > 2.0:
        print(f"{name:28s} {t1*1e3:9.2f} ms", flush=True); return
    def prog(seed, *a):
        def body(c, i): return c + fn(i, *a), None
        tot, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(repeats, dtype=jnp.int32))
        return tot + seed
    pfn = jax.jit(prog); float(pfn(jnp.float32(0.0), *ops))
    t0 = time.time(); float(pfn(jnp.float32(1.0), *ops))
    print(f"{name:28s} {max(0.0,(time.time()-t0-OH))/repeats*1e3:9.2f} ms", flush=True)

OH = overhead()
print(f"overhead {OH*1e3:.1f} ms", flush=True)
rng = np.random.RandomState(0)
N, F, NBT = 1_000_000, 28, 257
bins = jnp.asarray(rng.randint(0, NBT, size=(N, F)).astype(np.int32))
gh0 = jnp.asarray(rng.randn(N, 2).astype(np.float32))
n_nodes = 16
pos = jnp.asarray(rng.randint(0, n_nodes, size=N).astype(np.int32))
order = jnp.asarray(np.argsort(np.asarray(pos), kind="stable").astype(np.int32))
counts = jnp.asarray(np.bincount(np.asarray(pos), minlength=n_nodes).astype(np.int32))
go_right = jnp.asarray((rng.rand(N) > 0.5))
sir = jnp.asarray((rng.rand(n_nodes // 2) > 0.5))

def p(i): return (i.astype(jnp.float32) * 1e-12)

timeit("update_partition_order", lambda i, o, c, g: update_partition_order(o, c, g)[0].sum().astype(jnp.float32), order, counts, go_right)
timeit("select_small_child", lambda i, o, c, s: select_small_child_rows(o, c, s)[0].sum().astype(jnp.float32), order, counts, sir)
timeit("gather_bins_half", lambda i, b, r: b[r].sum().astype(jnp.float32), bins, jnp.arange(N // 2, dtype=jnp.int32))
timeit("block_layout", lambda i, b, g, o, c: presorted_block_layout(b, g + p(i), o, c, n_nodes, 256)[1].sum(), bins, gh0, order, counts)
timeit("hist_presorted_highest", lambda i, b, g, o, c: hist_partition_presorted(b, g + p(i), o, c, n_nodes, NBT, precision="highest").sum(), bins, gh0, order, counts)
timeit("hist_presorted_fast", lambda i, b, g, o, c: hist_partition_presorted(b, g + p(i), o, c, n_nodes, NBT, precision="fast").sum(), bins, gh0, order, counts)
timeit("pallas_presorted_highest", lambda i, b, g, o, c: hp.hist_pallas_presorted(b, g + p(i), o, c, n_nodes, NBT, precision="highest").sum(), bins, gh0, order, counts)
timeit("pallas_presorted_fast", lambda i, b, g, o, c: hp.hist_pallas_presorted(b, g + p(i), o, c, n_nodes, NBT, precision="fast").sum(), bins, gh0, order, counts)
timeit("onehot_1node_highest", lambda i, b, g: hist_onehot(b, g + p(i), jnp.zeros((N,), jnp.int32), 1, NBT, precision="highest").sum(), bins, gh0)
timeit("onehot_1node_fast", lambda i, b, g: hist_onehot(b, g + p(i), jnp.zeros((N,), jnp.int32), 1, NBT, precision="fast").sum(), bins, gh0)
timeit("pallas_1node_fast", lambda i, b, g: hp.hist_pallas(b, g + p(i), jnp.zeros((N,), jnp.int32), 1, NBT, precision="fast").sum(), bins, gh0)
