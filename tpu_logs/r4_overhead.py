"""Round-4: per-round overhead attribution by ablation (VERDICT r3 #2).

r2 measured tree build ~0.5 s/round on v5e at 1M x 28 while full-protocol
rounds cost 0.8-1.4 s more. This script attributes the gap by ablating the
driver-protocol features one at a time and measuring the MARGINAL per-round
cost of each config via two run lengths (identical compiles thanks to the
SCAN_MAX_CHUNK divisor), plus an engine-only loop that excludes the driver
entirely:

  engine_only   TpuEngine.step_many, no driver at all
  bare          train() with no evals, no checkpointing
  evals         + evals=[(dtrain,"train")] (device logloss per round)
  evals_ckpt    + checkpoint_frequency=5 (booster serialization + queue)

deltas: (bare - engine_only) = driver dispatch; (evals - bare) = eval-margin
updates + metric transfer; (evals_ckpt - evals) = checkpoint serialization.

Run serialized on the tunnel; also meaningful on the CPU mesh for RANKING
the host-side suspects (python dispatch, serialization, metric transfers are
hardware-independent; device compute is not).
"""

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import numpy as np


def main():
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # hermeticity guard (same as tests/conftest.py): the axon plugin
        # self-registers and would be initialized even under
        # JAX_PLATFORMS=cpu, hanging/failing when the tunnel is down
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        for _name in list(_xb._backend_factories):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)
    import jax

    backend = jax.default_backend()
    print(f"backend={backend} devices={len(jax.devices())}", flush=True)
    sys.path.insert(0, "/root/repo")
    from xgboost_ray_tpu import RayDMatrix, RayParams, train
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params

    n_rows = int(float(os.environ.get(
        "OVERHEAD_ROWS", "1e6" if backend != "cpu" else "2e5")))
    r_lo, r_hi = 10, 50
    rng = np.random.RandomState(0)
    x = rng.standard_normal((n_rows, 28)).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    base_params = {"objective": "binary:logistic", "max_depth": 6,
                   "max_bin": 256, "tree_method": "tpu_hist"}

    def timed_train(rounds, evals, ckpt, eval_metric):
        params = dict(base_params)
        if eval_metric:
            params["eval_metric"] = eval_metric
        dtrain = RayDMatrix(x, y)
        t0 = time.time()
        train(params, dtrain, num_boost_round=rounds,
              evals=[(dtrain, "train")] if evals else (),
              ray_params=RayParams(num_actors=int(
                  os.environ.get("OVERHEAD_ACTORS", "1" if backend != "cpu" else "8")),
                  checkpoint_frequency=ckpt))
        return time.time() - t0

    def timed_engine(rounds):
        # SAME actor count as the train() configs — the delta to "bare" must
        # isolate driver dispatch, not mesh size
        n_act = int(os.environ.get("OVERHEAD_ACTORS",
                                   "1" if backend != "cpu" else "8"))
        from xgboost_ray_tpu.matrix import RayShardingMode, _get_sharding_indices

        params = parse_params(dict(base_params))
        shards = []
        for rank in range(n_act):
            idx = _get_sharding_indices(
                RayShardingMode.INTERLEAVED, rank, n_act, x.shape[0])
            shards.append({"data": x[idx], "label": y[idx], "weight": None,
                           "base_margin": None, "label_lower_bound": None,
                           "label_upper_bound": None, "qid": None})
        eng = TpuEngine(shards, params, num_actors=n_act)
        t0 = time.time()
        done = 0
        while done < rounds:
            n = min(10, rounds - done)
            eng.step_many(done, n)
            done += n
        eng.get_booster()  # flush deferred forests — train() configs pay this too
        return time.time() - t0

    rows = {}
    for name, fn in (
        ("engine_only", lambda r: timed_engine(r)),
        ("bare", lambda r: timed_train(r, evals=False, ckpt=0, eval_metric=None)),
        ("evals", lambda r: timed_train(r, evals=True, ckpt=0,
                                        eval_metric=["logloss"])),
        ("evals_ckpt", lambda r: timed_train(r, evals=True, ckpt=5,
                                             eval_metric=["logloss"])),
    ):
        w_lo = fn(r_lo)
        w_hi = fn(r_hi)
        marginal = (w_hi - w_lo) / (r_hi - r_lo)
        rows[name] = marginal
        print(f"{name:12s} wall{r_lo}={w_lo:7.1f}s wall{r_hi}={w_hi:7.1f}s "
              f"marginal={marginal:6.3f} s/round", flush=True)

    print("--- attribution (s/round) ---", flush=True)
    print(f"tree build + engine   : {rows['engine_only']:.3f}", flush=True)
    print(f"driver dispatch       : {rows['bare'] - rows['engine_only']:+.3f}",
          flush=True)
    print(f"eval margins + metric : {rows['evals'] - rows['bare']:+.3f}",
          flush=True)
    print(f"checkpoint every 5    : {rows['evals_ckpt'] - rows['evals']:+.3f}",
          flush=True)


if __name__ == "__main__":
    main()
