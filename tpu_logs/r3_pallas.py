"""Round-3: decide the Pallas kernel's fate (VERDICT r2 #3).

Parity + timing for the bins-on-rows presorted kernel vs the XLA einsum at
production shapes: 1M x 28 x 256, nodes in {1, 8, 64}, both precisions.
Keep (and promote) only if it is exact and faster; otherwise it gets
deleted.
"""

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ["RXGB_ENABLE_PALLAS"] = "1"

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    print(f"backend={jax.default_backend()}", flush=True)
    sys.path.insert(0, "/root/repo")
    from xgboost_ray_tpu.ops import hist_pallas as hp
    from xgboost_ray_tpu.ops.histogram import hist_partition_presorted

    assert hp.PALLAS_AVAILABLE
    n, f, max_bin = 1_000_000, 28, 256
    nbt = max_bin + 1
    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, nbt, size=(n, f)).astype(np.uint8)
    gh_np = rng.randn(n, 2).astype(np.float32)

    for nodes in (1, 8, 64):
        # contiguous node segments (the presorted invariant)
        counts_np = np.full(nodes, n // nodes, np.int32)
        counts_np[-1] += n - counts_np.sum()
        order_np = np.arange(n, dtype=np.int32)
        bins = jnp.asarray(bins_np)
        gh = jnp.asarray(gh_np)
        order = jnp.asarray(order_np)
        counts = jnp.asarray(counts_np)
        for precision in ("highest", "fast"):
            ref_fn = jax.jit(lambda b, g, o, c: hist_partition_presorted(
                b, g, o, c, nodes, nbt, precision=precision))
            pal_fn = jax.jit(lambda b, g, o, c: hp.hist_pallas_presorted(
                b, g, o, c, nodes, nbt, precision=precision))
            try:
                ref = ref_fn(bins, gh, order, counts)
                ref_np = np.asarray(ref)
                pal = pal_fn(bins, gh, order, counts)
                pal_np = np.asarray(pal)
            except Exception as exc:
                print(f"nodes={nodes} prec={precision} COMPILE/RUN FAIL "
                      f"{type(exc).__name__}: {str(exc)[:200]}", flush=True)
                continue
            scale = max(np.abs(ref_np).max(), 1e-6)
            err = np.abs(pal_np - ref_np).max() / scale
            # timing: scan-repeat inside one program, one scalar sync
            def timed(fn, reps=20):
                def body(c, _):
                    h = fn(bins, gh, order, counts)
                    return c + h[0, 0, 0, 0], None
                prog = jax.jit(lambda: jax.lax.scan(
                    body, jnp.float32(0.0), None, length=reps)[0])
                prog()  # compile+warm
                t0 = time.time(); float(prog()); dt = time.time() - t0
                return dt / reps
            t_ref = timed(lambda b=bins, g=gh, o=order, c=counts:
                          hist_partition_presorted(b, g, o, c, nodes, nbt,
                                                   precision=precision))
            t_pal = timed(lambda b=bins, g=gh, o=order, c=counts:
                          hp.hist_pallas_presorted(b, g, o, c, nodes, nbt,
                                                   precision=precision))
            verdict = "PARITY_OK" if err < 1e-5 else f"PARITY_FAIL rel={err:.3e}"
            print(f"nodes={nodes} prec={precision} {verdict} "
                  f"einsum={t_ref*1e3:.1f}ms pallas={t_pal*1e3:.1f}ms "
                  f"speedup={t_ref/max(t_pal,1e-9):.2f}x", flush=True)


if __name__ == "__main__":
    main()
