#!/bin/bash
# Round-4 tunnel watchdog: probe until the axon TPU backend answers, then
# FIRE the measurement queue exactly once (VERDICT r3 #1: r3_watch only
# logged; a recovery window would have been missed).
L=/root/repo/tpu_logs
while true; do
  ts=$(date +%F_%T)
  out=$(timeout 240 python -c "import jax; print('DEVS', jax.devices())" 2>&1 | tail -2)
  if echo "$out" | grep -q "DEVS"; then
    echo "$ts UP: $out" >> $L/r4_probe.log
    touch $L/TUNNEL_UP_R4
    bash $L/r4_queue.sh
    echo "$ts queue finished" >> $L/r4_probe.log
    exit 0
  fi
  echo "$ts down: $(echo "$out" | tr '\n' ' ' | cut -c1-160)" >> $L/r4_probe.log
  sleep 180
done
