#!/bin/bash
# Round-5 serialized TPU queue (single-client tunnel — never overlap).
# Fired automatically by r5_watch.sh the moment the tunnel answers.
# Order: crash bisection first (validates the 11M SCAN_MAX_CHUNK fix), then
# the headline bench while the tunnel is known-good, then overhead
# attribution, distributed predict, MSLR ranking, precision quality. Commits results unattended.
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache
L=/root/repo/tpu_logs
run() {  # run <name> <timeout_s> <cmd...>
  echo "=== $1 start $(date +%T) ===" >> $L/r5.log
  timeout "$2" "${@:3}" >> $L/r5.log 2>&1
  echo "=== $1 exit=$? $(date +%T) ===" >> $L/r5.log
}
run bisect 3600 python tpu_logs/r3_bisect.py
run bench_full 4000 python bench.py
# preserve the real-TPU bench line separately so it can't be lost
grep -a '"metric"' $L/r5.log | tail -1 > $L/r5_bench_line.json
run steady 2400 python tpu_logs/r3_steady.py
run overhead 3600 python tpu_logs/r4_overhead.py
run predict_bench 2400 python tests/release/benchmark_predict.py 1 1000000
run mslr 3600 python tests/release/benchmark_ranking.py 1 100
run int8_probe 1200 python tpu_logs/r4_int8_probe.py
run quality 1800 python tpu_logs/quality_fast.py
run newfeat 2400 python tpu_logs/r5_newfeat_probe.py
echo "R5 QUEUE ALL DONE $(date +%T)" >> $L/r5.log
git add tpu_logs/r5.log tpu_logs/r5_bench_line.json tpu_logs/r5_probe.log 2>/dev/null
git commit -m "Record round-5 on-TPU measurement queue results" >> $L/r5.log 2>&1
