"""On-TPU bit-parity check: hist_pallas vs hist_scatter (VERDICT #2)."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp

assert jax.default_backend() == "tpu", jax.default_backend()
from xgboost_ray_tpu.ops.histogram import hist_scatter
from xgboost_ray_tpu.ops import hist_pallas

rng = np.random.RandomState(0)
rows, feats, nbt = 200_000, 28, 257
bins = jnp.asarray(rng.randint(0, nbt, size=(rows, feats)).astype(np.uint8))
gh = jnp.asarray(rng.randn(rows, 2).astype(np.float32))
for n_nodes in (1, 8):
    pos = jnp.asarray(rng.randint(0, n_nodes, size=rows).astype(np.int32))
    hp = np.asarray(hist_pallas.hist_pallas(bins, gh, pos, n_nodes, nbt))
    hs = np.asarray(hist_scatter(bins, gh, pos, n_nodes, nbt))
    md = float(np.max(np.abs(hp - hs)))
    rel = md / max(1e-9, float(np.max(np.abs(hs))))
    print(f"n_nodes={n_nodes} max_abs_diff={md:.3e} rel={rel:.3e} "
          f"{'PARITY_OK' if rel < 1e-5 else 'PARITY_FAIL'}", flush=True)
