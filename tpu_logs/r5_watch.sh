#!/bin/bash
# Round-5 tunnel watchdog: probe until the axon TPU backend answers, then
# FIRE the measurement queue exactly once (fire-once pattern from r4;
# VERDICT r4 #1 requires a real-TPU BENCH_r05 or a committed probe log).
L=/root/repo/tpu_logs
while true; do
  ts=$(date +%F_%T)
  out=$(timeout 240 python -c "import jax; print('DEVS', jax.devices())" 2>&1 | tail -2)
  # require a REAL accelerator answer: a CPU fallback must not fire the
  # queue and unattended-commit CPU numbers as the round-5 TPU record
  if echo "$out" | grep -q "DEVS" && ! echo "$out" | grep -qi "CpuDevice"; then
    echo "$ts UP: $out" >> $L/r5_probe.log
    touch $L/TUNNEL_UP_R5
    bash $L/r5_queue.sh
    echo "$ts queue finished" >> $L/r5_probe.log
    exit 0
  fi
  echo "$ts down: $(echo "$out" | tr '\n' ' ' | cut -c1-160)" >> $L/r5_probe.log
  sleep 180
done
