"""Separate compile cost from steady-state per-round cost at 1M rows."""
import time
import numpy as np, jax
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu import RayDMatrix, RayParams, train

rng = np.random.RandomState(0)
n = 1_000_000
x = rng.standard_normal((n, 28)).astype(np.float32)
y = (0.8*x[:,0] - 0.6*x[:,1] + 0.4*x[:,2]*x[:,3] > 0).astype(np.float32)
for rounds in (8, 40):
    add = {}
    t0 = time.time()
    train({"objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
           "tree_method": "tpu_hist"}, RayDMatrix(x, y), rounds,
          additional_results=add,
          ray_params=RayParams(num_actors=1, checkpoint_frequency=0))
    print(f"rounds={rounds} wall={time.time()-t0:.1f}s "
          f"train={add['training_time_s']:.1f}s", flush=True)
