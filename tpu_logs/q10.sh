#!/bin/bash
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/root/repo/tpu_logs
while ! grep -q "Q9 ALL DONE" $L/r2.log; do sleep 20; done
run() { echo "=== $1 start $(date +%T) ===" >> $L/r2.log; timeout "$2" "${@:3}" >> $L/r2.log 2>&1; echo "=== $1 exit=$? $(date +%T) ===" >> $L/r2.log; }
run mslr 3600 python tests/release/benchmark_ranking.py 1 100 --groups 31000 --group-size 120
echo "Q10 ALL DONE $(date +%T)" >> $L/r2.log
