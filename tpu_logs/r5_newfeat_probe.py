"""On-chip timing probe for the round-5 features (runs LAST in r5_queue.sh).

Times 10 steady rounds of (a) depthwise baseline, (b) lossguide at two leaf
budgets, (c) gblinear, at 1M x 28 on whatever backend answers — small
enough to not endanger the headline bench's tunnel time, enough to anchor
the lossguide O(N * leaves) cost model and the gblinear round cost with
real numbers.
"""

import json
import time

import numpy as np


def main():
    import jax

    backend = jax.default_backend()
    rng = np.random.RandomState(0)
    n, f = 1_000_000, 28
    x = rng.standard_normal(size=(n, f)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    configs = {
        "depthwise_d8": {"max_depth": 8},
        "lossguide_l64": {"grow_policy": "lossguide", "max_leaves": 64,
                          "max_depth": 10},
        "lossguide_l256": {"grow_policy": "lossguide", "max_leaves": 256,
                           "max_depth": 12},
        "gblinear": {"booster": "gblinear"},
    }
    for name, extra in configs.items():
        params = {"objective": "binary:logistic", "eta": 0.3, "seed": 0,
                  **extra}
        t0 = time.time()
        train(params, RayDMatrix(x, y), 2, ray_params=RayParams(num_actors=1))
        warm = time.time() - t0
        t1 = time.time()
        train(params, RayDMatrix(x, y), 10,
              ray_params=RayParams(num_actors=1))
        total = time.time() - t1
        print(json.dumps({
            "probe": "r5_newfeat", "config": name, "backend": backend,
            "rows": n, "warmup_2r_s": round(warm, 2),
            "run_10r_s": round(total, 2),
            "per_round_s": round(total / 10, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
