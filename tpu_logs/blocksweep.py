"""Pallas block-size sweep at 1M rows, 16 nodes (presorted, bins_rows)."""
import time
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu.ops import hist_pallas as hp

def overhead():
    f = jax.jit(lambda x: x + 1.0); x = jnp.float32(0.0); float(f(x))
    t0 = time.time()
    for _ in range(3): float(f(x))
    return (time.time() - t0) / 3

OH = overhead()
rng = np.random.RandomState(0)
N, F, NBT, NODES = 1_000_000, 28, 257, 16
bins = jnp.asarray(rng.randint(0, NBT, size=(N, F)).astype(np.int16))
gh0 = jnp.asarray(rng.randn(N, 2).astype(np.float32))
pos = rng.randint(0, NODES, size=N).astype(np.int32)
order = jnp.asarray(np.argsort(pos, kind="stable").astype(np.int32))
counts = jnp.asarray(np.bincount(pos, minlength=NODES).astype(np.int32))

for block in (256, 512, 1024, 2048):
    for prec in ("fast", "highest"):
        def body(i, b, g, o, c, block=block, prec=prec):
            g = g + i.astype(jnp.float32) * 1e-12
            return hp.hist_pallas_presorted(b, g, o, c, NODES, NBT,
                                            block=block, precision=prec).sum()
        try:
            fn = jax.jit(body)
            float(fn(jnp.int32(0), bins, gh0, order, counts))
            def prog(seed, b, g, o, c):
                def sbody(carry, i): return carry + body(i, b, g, o, c), None
                tot, _ = jax.lax.scan(sbody, jnp.float32(0.0), jnp.arange(8, dtype=jnp.int32))
                return tot + seed
            pfn = jax.jit(prog); float(pfn(jnp.float32(0.0), bins, gh0, order, counts))
            t0 = time.time(); float(pfn(jnp.float32(1.0), bins, gh0, order, counts))
            dt = max(0.0, time.time() - t0 - OH) / 8
            print(f"block={block:5d} prec={prec:8s} {dt*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"block={block:5d} prec={prec:8s} FAILED {str(e)[:100]}", flush=True)
# onehot after feature tiling
from xgboost_ray_tpu.ops.histogram import hist_onehot
pos1 = jnp.zeros((N,), jnp.int32)
for prec in ("fast", "highest"):
    def body(i, b, g, prec=prec):
        g = g + i.astype(jnp.float32) * 1e-12
        return hist_onehot(b, g, pos1, 1, NBT, precision=prec).sum()
    fn = jax.jit(body); float(fn(jnp.int32(0), bins, gh0))
    def prog(seed, b, g):
        def sbody(carry, i): return carry + body(i, b, g), None
        tot, _ = jax.lax.scan(sbody, jnp.float32(0.0), jnp.arange(8, dtype=jnp.int32))
        return tot + seed
    pfn = jax.jit(prog); float(pfn(jnp.float32(0.0), bins, gh0))
    t0 = time.time(); float(pfn(jnp.float32(1.0), bins, gh0))
    print(f"onehot_ftile 1node {prec:8s} {max(0.0,(time.time()-t0-OH))/8*1e3:8.2f} ms", flush=True)
