#!/bin/bash
# Round-3 serialized TPU queue (single-client tunnel — never overlap).
# Order: crash bisection first (validates the 11M fix), then the headline
# bench while the tunnel is known-good, then overhead attribution, MSLR
# ranking, pallas fate, precision quality.
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache
L=/root/repo/tpu_logs
run() {  # run <name> <timeout_s> <cmd...>
  echo "=== $1 start $(date +%T) ===" >> $L/r3.log
  timeout "$2" "${@:3}" >> $L/r3.log 2>&1
  echo "=== $1 exit=$? $(date +%T) ===" >> $L/r3.log
}
run bisect 3600 python tpu_logs/r3_bisect.py
run bench_full 4000 python bench.py
run steady 2400 python tpu_logs/r3_steady.py
run mslr 3600 python tests/release/benchmark_ranking.py 1 100
run pallas 2400 python tpu_logs/r3_pallas.py
run quality 1800 python tpu_logs/quality_fast.py
echo "R3 QUEUE ALL DONE $(date +%T)" >> $L/r3.log
