"""Probe: would int8 quantized histogram matmuls beat the bf16 one-hot path?

Motivated by 'Quantized Training of GBDT' (arxiv 2207.09682, PAPERS.md):
low-bit gradient histograms. STATUS r2 measured the v5e hist build as
DMA/step-bound rather than MXU-pass-bound, so the expected win (if any) is
from halving the one-hot operand's HBM traffic (bf16 -> int8), not FLOPs.
This times the EXACT contraction shape hist_onehot issues — [chunk, nb] x
[chunk, 2] — in bf16 vs int8 (int32 accumulate), at the 1M x 28 x 256
depth-6 worst level. Decision rule: int8 must win by >15% per build before
a product quantized path (with stochastic rounding + accuracy validation)
is worth building; otherwise record the negative result and close the idea.

Run serialized on the tunnel (r4_queue.sh).
"""

import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import numpy as np
import jax
import jax.numpy as jnp


def time_build(dtype_name: str, chunk=8192, n=1_000_000, nodes=32, nb_reg=256,
               reps=3):
    nb = nodes * nb_reg
    n_chunks = n // chunk
    rng = np.random.RandomState(0)
    idx = rng.randint(0, nb, size=(n_chunks, chunk)).astype(np.int32)
    gh = rng.randn(n_chunks, chunk, 2).astype(np.float32)

    if dtype_name == "bf16":
        oh_dtype, gh_dtype, acc_dtype = jnp.bfloat16, jnp.bfloat16, jnp.float32
    else:  # int8: one-hot is exactly representable; gh quantized per chunk
        oh_dtype, gh_dtype, acc_dtype = jnp.int8, jnp.int8, jnp.int32

    def build(idx_a, gh_a):
        def step(acc, args):
            ix, ghk = args
            oh = jax.nn.one_hot(ix, nb, dtype=oh_dtype)
            if dtype_name == "int8":
                scale = jnp.max(jnp.abs(ghk)) / 127.0 + 1e-12
                ghq = jnp.clip(jnp.round(ghk / scale), -127, 127).astype(jnp.int8)
                contrib = jax.lax.dot_general(
                    oh, ghq, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )
                acc = acc + contrib.astype(jnp.float32) * scale
            else:
                ghk = ghk.astype(gh_dtype)
                contrib = jax.lax.dot_general(
                    oh, ghk, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                acc = acc + contrib
            return acc, None
        acc0 = jnp.zeros((nb, 2), jnp.float32)
        acc, _ = jax.lax.scan(step, acc0, (idx_a, gh_a))
        return acc

    fn = jax.jit(build)
    idx_d, gh_d = jnp.asarray(idx), jnp.asarray(gh)
    out = fn(idx_d, gh_d)
    _ = np.asarray(out[:1, :1])  # force compile + run
    times = []
    for _r in range(reps):
        t0 = time.time()
        out = fn(idx_d, gh_d)
        _ = np.asarray(out[:1, :1])  # host read = sync (relay-safe)
        times.append(time.time() - t0)
    return min(times), np.asarray(out)


def main():
    print(f"backend={jax.default_backend()}", flush=True)
    t_bf16, h_bf16 = time_build("bf16")
    print(f"bf16 one-hot build: {t_bf16*1e3:.1f} ms / 1M rows", flush=True)
    t_int8, h_int8 = time_build("int8")
    print(f"int8 one-hot build: {t_int8*1e3:.1f} ms / 1M rows", flush=True)
    rel = np.abs(h_int8 - h_bf16).max() / (np.abs(h_bf16).max() + 1e-9)
    print(f"speedup: {t_bf16 / t_int8:.2f}x  max-rel-diff: {rel:.2e}", flush=True)
    print("DECISION: build quantized product path" if t_bf16 / t_int8 > 1.15
          else "DECISION: keep bf16 (int8 not worth it here)", flush=True)


if __name__ == "__main__":
    main()
