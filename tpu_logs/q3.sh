#!/bin/bash
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/root/repo/tpu_logs
run() { echo "=== $1 start $(date +%T) ===" >> $L/r2.log; timeout "$2" "${@:3}" >> $L/r2.log 2>&1; echo "=== $1 exit=$? $(date +%T) ===" >> $L/r2.log; }
run parity2 1800 python tpu_logs/parity2.py
for impl in pallas partition onehot; do
  run hist2_$impl 2400 python tools/bench_hist.py --impls $impl
done
run quality 2400 python tpu_logs/quality_fast.py
echo "Q3 ALL DONE $(date +%T)" >> $L/r2.log
