#!/bin/bash
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/root/repo/tpu_logs
while ! grep -q "Q7 ALL DONE" $L/r2.log; do sleep 20; done
run() { echo "=== $1 start $(date +%T) ===" >> $L/r2.log; timeout "$2" "${@:3}" >> $L/r2.log 2>&1; echo "=== $1 exit=$? $(date +%T) ===" >> $L/r2.log; }
run parity3 1800 python tpu_logs/parity2.py
run steady2 2400 python tpu_logs/steady.py
run higgs_full2 4500 python bench.py
echo "Q8 ALL DONE $(date +%T)" >> $L/r2.log
