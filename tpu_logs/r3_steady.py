"""Round-3: steady-state per-round cost + overhead attribution at 1M x 28.

Times two training lengths with the bounded-chunk scan path (difference
isolates the marginal per-round cost from compile+data setup), then one
profiled chunk when RXGB_PROFILE_DIR is set. VERDICT r2 #2: tree build was
~0.5 s while rounds cost 0.8-1.4 s more than that — attribute the rest.
"""

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import numpy as np


def main():
    import jax

    print(f"backend={jax.default_backend()}", flush=True)
    sys.path.insert(0, "/root/repo")
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    n_rows = int(float(os.environ.get("STEADY_ROWS", "1e6")))
    rng = np.random.RandomState(0)
    x = rng.standard_normal((n_rows, 28)).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "eval_metric": ["logloss"],
              "max_depth": 6, "max_bin": 256, "tree_method": "tpu_hist"}

    for rounds in (10, 50):
        t0 = time.time()
        train(params, RayDMatrix(x, y), num_boost_round=rounds,
              ray_params=RayParams(num_actors=1, checkpoint_frequency=0))
        wall = time.time() - t0
        print(f"rounds={rounds} wall={wall:.1f}s", flush=True)
    # marginal/round = (wall50 - wall10) / 40 with identical compiles
    # (same chunk program sizes thanks to SCAN_MAX_CHUNK=10 divisor).


if __name__ == "__main__":
    main()
