"""Per-phase timing of train() on TPU: where do the seconds go?"""
import time, os
import numpy as np
import jax
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu import RayDMatrix, RayParams, train

rng = np.random.RandomState(0)
n = 1_000_000
x = rng.standard_normal((n, 28)).astype(np.float32)
y = (0.8*x[:,0] - 0.6*x[:,1] + 0.4*x[:,2]*x[:,3] > 0).astype(np.float32)

t0 = time.time()
dtrain = RayDMatrix(x, y)
add = {}
bst = train({"objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
             "max_bin": 256, "tree_method": "tpu_hist"},
            dtrain, num_boost_round=16,
            additional_results=add,
            ray_params=RayParams(num_actors=1, checkpoint_frequency=0))
total = time.time() - t0
rt = add.get("round_times_s", [])
print(f"total={total:.1f}s training_time={add.get('training_time_s'):.1f}s")
print("round_times_s:", " ".join(f"{t:.2f}" for t in rt))
