#!/bin/bash
# wait for q3 to finish (single-client tunnel), then run parity2 + pieces
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
L=/root/repo/tpu_logs
while ! grep -q "Q3 ALL DONE" $L/r2.log; do sleep 20; done
run() { echo "=== $1 start $(date +%T) ===" >> $L/r2.log; timeout "$2" "${@:3}" >> $L/r2.log 2>&1; echo "=== $1 exit=$? $(date +%T) ===" >> $L/r2.log; }
run parity2b 1800 python tpu_logs/parity2.py
run pieces 2400 python tpu_logs/pieces.py
echo "Q4 ALL DONE $(date +%T)" >> $L/r2.log
