"""On-TPU end-to-end smoke: small training run, accuracy sanity."""
import time
import numpy as np
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
from xgboost_ray_tpu import RayDMatrix, RayParams, train

rng = np.random.RandomState(0)
n = 200_000
x = rng.standard_normal((n, 28)).astype(np.float32)
logits = 0.8*x[:,0] - 0.6*x[:,1] + 0.4*x[:,2]*x[:,3] + 0.3*x[:,4]
y = (logits + rng.standard_normal(n).astype(np.float32) > 0).astype(np.float32)
dtrain = RayDMatrix(x, y)
res = {}
t0 = time.time()
bst = train({"objective": "binary:logistic", "eval_metric": ["logloss", "error"],
             "max_depth": 6, "eta": 0.3, "max_bin": 256, "tree_method": "tpu_hist"},
            dtrain, num_boost_round=20,
            evals=[(dtrain, "train")], evals_result=res,
            ray_params=RayParams(num_actors=1, checkpoint_frequency=0))
dt = time.time() - t0
err = res["train"]["error"][-1]
print(f"SMOKE rounds=20 wall={dt:.1f}s final_train_error={err:.4f} "
      f"{'SMOKE_OK' if err < 0.25 else 'SMOKE_BAD'}", flush=True)
