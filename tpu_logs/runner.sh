#!/bin/bash
# Serialized TPU run queue — the tunnel is single-client; never overlap.
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
L=/root/repo/tpu_logs
run() {  # run <name> <timeout_s> <cmd...>
  echo "=== $1 start $(date +%T) ===" >> $L/runner.log
  timeout "$2" "${@:3}" >> $L/runner.log 2>&1
  echo "=== $1 exit=$? $(date +%T) ===" >> $L/runner.log
}
run smoke 1200 python tpu_logs/smoke.py
for impl in scatter onehot partition pallas; do
  run hist_$impl 2400 python tools/bench_hist.py --impls $impl
done
run pallas_parity 1200 python tpu_logs/pallas_parity.py
echo "ALL DONE $(date +%T)" >> $L/runner.log
