"""fast vs highest hist precision: final train logloss at 1M rows."""
import numpy as np, jax, time
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu import RayDMatrix, RayParams, train

rng = np.random.RandomState(0)
n = 1_000_000
x = rng.standard_normal((n, 28)).astype(np.float32)
logits = 0.8*x[:,0] - 0.6*x[:,1] + 0.4*x[:,2]*x[:,3] + 0.3*x[:,4]
y = (logits + rng.standard_normal(n).astype(np.float32) > 0).astype(np.float32)
for prec in ("fast", "highest"):
    res, add = {}, {}
    dtrain = RayDMatrix(x, y)
    t0 = time.time()
    train({"objective": "binary:logistic", "eval_metric": ["logloss"],
           "max_depth": 6, "eta": 0.1, "tree_method": "tpu_hist",
           "hist_precision": prec},
          dtrain, 16, evals=[(dtrain, "train")],
          evals_result=res, additional_results=add,
          ray_params=RayParams(num_actors=1, checkpoint_frequency=0))
    ll = res["train"]["logloss"]
    print(f"prec={prec:8s} wall={time.time()-t0:.1f}s train_time={add['training_time_s']:.1f}s "
          f"logloss[0]={ll[0]:.6f} logloss[-1]={ll[-1]:.6f}", flush=True)
