#!/bin/bash
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH}
L=/root/repo/tpu_logs
echo "=== bench 1M/10r start $(date +%T) ===" >> $L/bench.log
BENCH_ROWS=1000000 BENCH_ROUNDS=10 timeout 2400 python bench.py >> $L/bench.log 2>&1
echo "=== exit=$? $(date +%T) ===" >> $L/bench.log
