"""Round-3: bisect the 11M-row worker crash (r2.log:180).

Trains a few rounds at increasing row counts with the bounded-chunk scan
path, logging HBM-relevant sizes, so the crash (if it persists) is localized
to a row count and phase. Run serialized on the tunnel.
"""

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import numpy as np


def main():
    import jax

    print(f"backend={jax.default_backend()} devices={jax.devices()}", flush=True)
    sys.path.insert(0, "/root/repo")
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    rows_list = [int(float(r)) for r in os.environ.get(
        "BISECT_ROWS", "2e6,4e6,8e6,11e6").split(",")]
    rounds = int(os.environ.get("BISECT_ROUNDS", "3"))
    for n_rows in rows_list:
        rng = np.random.RandomState(0)
        x = rng.standard_normal((n_rows, 28)).astype(np.float32)
        y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
        print(f"--- rows={n_rows} gen done {x.nbytes/1e9:.2f}GB host ---", flush=True)
        t0 = time.time()
        try:
            bst = train(
                {"objective": "binary:logistic", "eval_metric": ["logloss"],
                 "max_depth": 6, "max_bin": 256, "tree_method": "tpu_hist"},
                RayDMatrix(x, y), num_boost_round=rounds,
                ray_params=RayParams(num_actors=1, checkpoint_frequency=0),
            )
            print(f"rows={n_rows} OK wall={time.time()-t0:.1f}s "
                  f"rounds={bst.num_boosted_rounds()}", flush=True)
        except Exception as exc:
            print(f"rows={n_rows} FAIL {type(exc).__name__}: {str(exc)[:300]}",
                  flush=True)
            raise
        del x, y


if __name__ == "__main__":
    main()
