"""Localize the pallas kernel bug: layout x precision matrix on-chip."""
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu.ops import hist_pallas as hp
from xgboost_ray_tpu.ops.histogram import hist_scatter

def case(n, f, nbt, n_nodes, seed, block=256):
    rng = np.random.RandomState(seed)
    bins = jnp.asarray(rng.randint(0, nbt, size=(n, f)).astype(np.int32))
    gh = jnp.asarray(np.round(rng.randn(n, 2) * 4).astype(np.float32))  # small ints: bf16-exact
    pos = jnp.asarray(rng.randint(0, n_nodes, size=n).astype(np.int32))
    want = np.asarray(hist_scatter(bins, gh, pos, n_nodes, nbt))
    for lay in ("bins_lanes", "bins_rows"):
        for prec in ("highest", "fast"):
            try:
                got = np.asarray(hp.hist_pallas(bins, gh, pos, n_nodes, nbt,
                                                block=block, precision=prec, layout=lay))
                d = np.abs(got - want)
                tag = f"n={n} f={f} nbt={nbt} nodes={n_nodes} {lay:10s} {prec:8s}"
                print(f"{tag} maxdiff={d.max():.3e}", flush=True)
                if d.max() > 1e-3 and n <= 2048:
                    idx = np.unravel_index(np.argmax(d), d.shape)
                    node, feat = idx[0], idx[1]
                    print("   worst idx:", idx, flush=True)
                    print("   want:", want[node, feat, :10, 0], flush=True)
                    print("   got :", got[node, feat, :10, 0], flush=True)
                    wrong = np.where(d[node, feat, :, 0] > 1e-3)[0]
                    print("   wrong bins:", wrong[:25], flush=True)
            except Exception as e:
                print(f"{lay} {prec} EXC: {str(e)[:140]}", flush=True)

case(512, 1, 9, 1, 0)
case(2048, 3, 9, 4, 3)
case(1024, 2, 257, 1, 4)
case(200_000, 28, 257, 1, 5)
