"""On-TPU parity of the re-aligned pallas kernel, both precisions."""
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from xgboost_ray_tpu.ops.histogram import hist_scatter
from xgboost_ray_tpu.ops import hist_pallas as hp

rng = np.random.RandomState(0)
rows, feats, nbt = 200_000, 28, 257
bins = jnp.asarray(rng.randint(0, nbt, size=(rows, feats)).astype(np.int32))
gh = jnp.asarray(rng.randn(rows, 2).astype(np.float32))
for n_nodes in (1, 8, 16):
    pos = jnp.asarray(rng.randint(0, n_nodes, size=rows).astype(np.int32))
    hs = np.asarray(hist_scatter(bins, gh, pos, n_nodes, nbt))
    scale = max(1e-9, float(np.abs(hs).max()))
    for prec, tol in (("highest", 2e-5), ("fast", 5e-3)):
        hp_out = np.asarray(hp.hist_pallas(bins, gh, pos, n_nodes, nbt, precision=prec))
        rel = float(np.abs(hp_out - hs).max()) / scale
        print(f"n_nodes={n_nodes} prec={prec:8s} rel={rel:.2e} "
              f"{'PARITY_OK' if rel < tol else 'PARITY_FAIL'}", flush=True)
