#!/bin/bash
# Smoke-run the examples (parity with the reference's run_ci_examples.sh).
set -e
# CI examples always run on the CPU mesh (set RXGB_EXAMPLES_ON_TPU=1 to use
# the ambient accelerator instead) — the ambient env may pin JAX_PLATFORMS to
# a TPU plugin, which would serialize CI on accelerator availability.
if [ "${RXGB_EXAMPLES_ON_TPU:-0}" != "1" ]; then
  export JAX_PLATFORMS=cpu
fi
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
ROOT="$(cd "$(dirname "$0")" && pwd)"
export PYTHONPATH="$ROOT:$PYTHONPATH"
cd "$ROOT"

pushd examples/ || exit 1
ran=0
for ex in readme.py readme_sklearn_api.py simple.py simple_predict.py \
          simple_objectstore.py simple_partitioned.py simple_tune.py \
          simple_dask.py simple_modin.py simple_ray_dataset.py \
          simple_categorical.py simple_remote.py \
          simple_gblinear.py simple_constraints.py \
          simple_serve.py elastic_continuation.py \
          trace_run.py vectorized_hpo.py \
          custom_objective_metric.py replicated_serve.py; do
  echo "================= Running $ex ================="
  python "$ex"
  ran=$((ran+1))
done
popd
echo "================= Running train_on_test_data.py ================="
python -m examples.train_on_test_data --num-rows 20000 --num-partitions 4 --num-actors 2
echo "Ran $ran examples + train_on_test_data OK"
