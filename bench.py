"""Headline benchmark: HIGGS-protocol training wall-clock (BASELINE.md).

Reproduces the reference's benchmark protocol
(``xgboost_ray/tests/release/benchmark_cpu_gpu.py:22-106``: N workers, 100
boosting rounds, ``TRAIN TIME TAKEN``) on TPU. The real HIGGS csv (11M x 28)
is not downloadable in this zero-egress image, so the dataset is a
synthetic HIGGS-shaped binary-classification problem of the same size and
dtype; wall-clock is shape-bound (histograms over 11M x 28 x 256 bins), not
data-content-bound, so timings are protocol-comparable.

vs_baseline: BASELINE.json publishes no reference number (the reference
writes res.csv at runtime only), so we normalize against the BASELINE.md
north-star target of 120 s for `gpu_hist` on HIGGS-11M/100 rounds.
vs_baseline > 1.0 means faster than that target.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_GPU_HIST_S = 120.0


def make_higgs_like(n_rows: int, n_features: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.standard_normal(size=(n_rows, n_features)).astype(np.float32)
    # learnable structure: a few informative features + mild nonlinearity
    logits = 0.8 * x[:, 0] - 0.6 * x[:, 1] + 0.4 * x[:, 2] * x[:, 3] + 0.3 * x[:, 4]
    y = (logits + rng.standard_normal(n_rows).astype(np.float32) > 0).astype(np.float32)
    return x, y


def _probe_accelerator(timeout_s: float = 120.0) -> bool:
    """Check in a subprocess that the accelerator backend actually comes up.

    The TPU plugin initializes at backend-init time and can hang indefinitely
    if its tunnel/lease is wedged; probing in a killable child keeps the
    benchmark from hanging — on probe failure we fall back to the CPU mesh
    with an extrapolated metric instead of producing nothing.
    """
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return False
    code = "import jax; assert jax.default_backend() != 'cpu'; print('ACCEL_OK')"
    try:
        res = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
        return "ACCEL_OK" in res.stdout
    except Exception:
        return False


def main():
    # persistent compile cache: repeated protocol runs (and retries after
    # tunnel hiccups) skip the expensive remote compiles
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    if not _probe_accelerator():
        print(
            "[bench] accelerator backend unavailable (or wedged); falling "
            "back to the virtual CPU mesh with an extrapolated metric.",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        # the TPU plugin may have force-set the already-imported jax config at
        # interpreter startup; undo both the config and the factory so no code
        # path can touch the wedged tunnel
        import jax as _jax
        from jax._src import xla_bridge as _xb

        _jax.config.update("jax_platforms", "cpu")
        for _name in list(_xb._backend_factories):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)

    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    n_rows = int(os.environ.get("BENCH_ROWS", 11_000_000 if on_tpu else 200_000))
    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100 if on_tpu else 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    actors = int(os.environ.get("BENCH_ACTORS", max(1, len(jax.devices()))))
    hist_impl = os.environ.get("BENCH_HIST_IMPL", "auto")

    print(
        f"[bench] backend={backend} rows={n_rows} features={n_feat} "
        f"rounds={rounds} depth={depth} actors={actors} hist_impl={hist_impl}",
        file=sys.stderr,
    )

    t0 = time.time()
    x, y = make_higgs_like(n_rows, n_feat)
    print(f"[bench] data generated in {time.time() - t0:.1f}s", file=sys.stderr)

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    dtrain = RayDMatrix(x, y)
    params = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss"],
        "max_depth": depth,
        "eta": 0.1,
        "max_bin": 256,
        "tree_method": "tpu_hist",
        "hist_impl": hist_impl,
    }

    train_start = time.time()
    bst = train(
        params,
        dtrain,
        num_boost_round=rounds,
        ray_params=RayParams(num_actors=actors, checkpoint_frequency=0),
    )
    train_time = time.time() - train_start
    print(f"[bench] TRAIN TIME TAKEN: {train_time:.2f}s", file=sys.stderr)
    assert bst.num_boosted_rounds() == rounds

    # normalize to the full protocol (11M rows x 100 rounds) when a smaller
    # config was run, so the metric stays comparable across environments
    scale = (11_000_000 / n_rows) * (100 / rounds)
    normalized = train_time * scale
    metric = (
        "higgs11m_100r_train_wall_clock"
        if scale == 1.0
        else "higgs11m_100r_train_wall_clock_extrapolated"
    )
    if on_tpu and actors == 1:
        # BASELINE.md's north-star machine is a v5e-8 (8 chips, 8 actors,
        # data-parallel); this environment exposes ONE chip. The headline
        # metric stays the honest single-chip measurement.
        print(
            f"[bench] single-chip measurement (the BASELINE.md target "
            f"machine is a v5e-8; a measured/8 = {normalized / 8:.1f}s "
            f"figure would be an IDEALIZED upper bound assuming perfect "
            f"8-way scaling — it is NOT a measured multi-chip result)",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(normalized, 2),
                "unit": "s",
                "vs_baseline": round(BASELINE_GPU_HIST_S / normalized, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
