"""Headline benchmark: HIGGS-protocol training wall-clock (BASELINE.md).

Reproduces the reference's benchmark protocol
(``xgboost_ray/tests/release/benchmark_cpu_gpu.py:22-106``: N workers, 100
boosting rounds, ``TRAIN TIME TAKEN``) on TPU. The real HIGGS csv (11M x 28)
is not downloadable in this zero-egress image, so the dataset is a
synthetic HIGGS-shaped binary-classification problem of the same size and
dtype; wall-clock is shape-bound (histograms over 11M x 28 x 256 bins), not
data-content-bound, so timings are protocol-comparable.

vs_baseline: BASELINE.json publishes no reference number (the reference
writes res.csv at runtime only), so we normalize against the BASELINE.md
north-star target of 120 s for `gpu_hist` on HIGGS-11M/100 rounds.
vs_baseline > 1.0 means faster than that target.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Structure: the parent process probes the accelerator and launches the actual
measurement in a child process (``--run``), so a TPU worker crash mid-train
(the round-2 failure mode, tpu_logs/r2.log:180) cannot wedge the parent —
the parent retries with a smaller fused-scan chunk, then falls back to the
virtual CPU mesh with an unmistakably-labeled extrapolated metric.
"""

import contextlib
import glob
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

BASELINE_GPU_HIST_S = 120.0

# ---------------------------------------------------------------------------
# REGRESSION NOTE (r4 -> r5 "52% CPU-mesh slowdown", investigated r6): the
# recorded BENCH_r04 (0.76 s/round) vs BENCH_r05 (1.44 s/round) delta is NOT
# a code regression. Re-running both snapshots' bench on one machine under
# identical conditions gives r4-end 4.17 s/round vs r5-end 4.11 s/round
# (within 1.5%) — the recorded gap was environmental (different machine
# load/hardware during the driver's capture runs). Two confounds make the
# recorded numbers fragile: (a) with 10 rounds fused into one scan chunk,
# round_times_s is (compile + run)/10, so compile-time variance lands in the
# "per-round" figure; (b) absolute CPU-mesh throughput varies ~5x across
# capture environments. The tripwire below exists so the next such delta is
# flagged AT CAPTURE TIME instead of a round later; cross-machine noise can
# still trip it — treat a firing as "investigate", not "revert".
#
# r6 closes the item with in-process data: every CPU-mesh capture now also
# emits an ``r4_regression_recheck`` section (see ``r4_paired_recheck``)
# pairing two same-process re-measurements of the protocol config; the
# pair ratio bounds same-environment variance, and the recorded 1.89x
# r4->r5 delta sits far outside it => environmental, recorded in the
# BENCH_r06 snapshot itself.
# ---------------------------------------------------------------------------

# tripwire: warn when the steady per-round time regresses more than this
# factor vs the newest recorded BENCH_*.json of the same backend
TRIPWIRE_RATIO = 1.2

# serving p99 latency gets a looser band: tail latency on a shared CPU mesh
# is noisier than steady per-round medians (scheduler jitter lands directly
# in the p99), so 1.2x would fire on environmental noise alone
SERVE_TRIPWIRE_RATIO = 1.5

# paired heap-vs-node-array serving arms run back-to-back in ONE process
# under an identical closed-loop config, so same-environment variance is
# bounded and the band can be the tight 20%: fire when the node-array
# arm's p99 exceeds 1.2x the heap arm's (the FIL-style layout's p99 cut
# regressed)
SERVE_LAYOUT_TRIPWIRE_RATIO = 1.2

# chaos recovery: flag >20% time-to-recover regressions across snapshots
CHAOS_TRIPWIRE_RATIO = 1.2

# restart-vs-continue: flag >20% regressions of the elastic continuation's
# recovery advantage (continue_ttr / restart_ttr) across snapshots — the
# guard that keeps "zero-replay continuation is actually faster than
# restart-from-checkpoint" from silently rotting
ELASTIC_TRIPWIRE_RATIO = 1.2

# sampled-config round time: flag >20% regressions of the subsample=0.5
# ablation arm across snapshots — the guard that keeps "subsample is
# actually cheaper" from silently rotting back into zeroed-gh full-row cost
SAMPLING_TRIPWIRE_RATIO = 1.2

# instrumentation overhead: the obs plane's per-round spans ride the round
# loop of EVERY traced run, so their cost budget is absolute — tracing on
# may cost at most 2% of steady round time over tracing off. Unlike the
# other tripwires this one fires on the current run's own paired
# measurement (the budget), not only on cross-snapshot drift; the section
# still lands in every BENCH_*.json so history stays queryable.
OBS_OVERHEAD_RATIO = 1.02

# wide-feature 2D mesh: flag >20% regressions of the (4,2) row x feature
# arm's per-round time across snapshots — the guard that keeps "feature
# sharding is actually cheaper on wide data" from silently rotting. The
# byte cut itself is trace-deterministic and carries its own >=1.5x floor
# inside the section (byte_cut_ok).
WIDE_FEATURE_TRIPWIRE_RATIO = 1.2
WIDE_FEATURE_BYTE_CUT_MIN = 1.5

# low-precision gh plane: flag >20% regressions of the gh_precision='int8'
# ablation arm's steady per-round time across snapshots — the guard that
# keeps "int8 gradients are at worst round-time-neutral" from silently
# rotting into a slow path. The gh-plane byte cut itself is static layout
# arithmetic certified by rxgbverify (the traced programs really carry the
# narrow dtype), and carries its own >=3.5x floor inside the section.
LOW_PRECISION_TRIPWIRE_RATIO = 1.2
LOW_PRECISION_GH_CUT_MIN = 3.5
# accuracy gate: quantized-gradient arms must land within this of the f32
# arm's final logloss (the PR 4 sampling discipline, applied to precision)
LOW_PRECISION_LOGLOSS_TOL = 5e-4
# steady-round budget: int8 gh may cost at most this factor of f32 per round
LOW_PRECISION_ROUND_TIME_MAX = 1.05

# vectorized HPO: one vmapped-K=4 program vs 4 sequential trials of the same
# configs. cost_ratio = vmapped total wall / sequential total wall — the
# gate is the shipping contract (the lane axis exists to amortize compile
# and per-round dispatch across candidates, so the packed program must cost
# well under the sum of its lanes), and the >20% tripwire guards
# cross-snapshot drift of the ratio itself.
HPO_COST_RATIO_GATE = 0.6
HPO_TRIPWIRE_RATIO = 1.2


def _load_latest_bench_record(bench_dir):
    """Newest BENCH_*.json result dict (by round number, then mtime).

    The driver writes ``{"n": ..., "parsed": {...}}`` wrappers; accept both
    that shape and a bare result dict."""
    paths = glob.glob(os.path.join(bench_dir, "BENCH_*.json"))

    def key(p):
        m = re.search(r"BENCH_r?0*(\d+)", os.path.basename(p))
        return (int(m.group(1)) if m else -1, os.path.getmtime(p))

    for p in sorted(paths, key=key, reverse=True):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rec = doc.get("parsed", doc) if isinstance(doc, dict) else None
        if isinstance(rec, dict) and "metric" in rec:
            return rec, os.path.basename(p)
    return None, None


def _steady_per_round(round_times, chunk, total_s, rounds):
    """The one steady-state per-round estimator every ablation arm uses:
    median of the rounds after the compile-carrying first chunk, mean of
    the recorded times when there is no post-chunk sample, whole-train
    average as the last resort. Shared so the chunk-exclusion protocol
    cannot drift between call sites."""
    rt = round_times or []
    if len(rt) > chunk:
        return float(np.median(rt[chunk:]))
    if rt:
        return float(np.mean(rt))
    return float(total_s) / max(rounds, 1)


def _per_round_seconds(rec):
    """Best available per-round figure from a bench record, with its basis.

    Returns ``(seconds, basis)``: basis "steady" (compile excluded) or
    "compile_inclusive" (first-chunk mean / whole-train average)."""
    if not isinstance(rec, dict):
        return None, None
    if rec.get("steady_median_s"):
        return float(rec["steady_median_s"]), "steady"
    if rec.get("first_chunk_mean_s"):
        return float(rec["first_chunk_mean_s"]), "compile_inclusive"
    if rec.get("train_time_s") and rec.get("rounds"):
        return (
            float(rec["train_time_s"]) / float(rec["rounds"]),
            "compile_inclusive",
        )
    return None, None


def round_time_tripwire(current_s, prev_rec, prev_name=None, backend=None,
                        threshold=TRIPWIRE_RATIO,
                        current_basis="compile_inclusive"):
    """Compare the current per-round time against the newest recorded bench.

    Returns a dict ``{prev_per_round_s, prev_record, basis, ratio, fired}``
    or ``None`` when no comparable record exists (different backend,
    missing timing). Only fires when both figures share the same basis —
    a compile-inclusive first-chunk mean against a prior run's steady
    median would measure XLA compile time, not a regression; a
    basis-mismatched comparison is still reported, with ``fired`` False
    and the mismatch named. Fires (warns on stderr) when ``current >
    threshold * prev`` — the guard the r4->r5 CPU-mesh "regression"
    (environmental, see the note above) slipped past uninspected."""
    if not current_s or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev, prev_basis = _per_round_seconds(prev_rec)
    if not prev:
        return None
    ratio = float(current_s) / prev
    out = {
        "prev_per_round_s": round(prev, 4),
        "prev_record": prev_name,
        "basis": current_basis,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_basis != current_basis:
        out["basis_mismatch"] = f"prev={prev_basis}"
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] TRIPWIRE: per-round time {current_s:.4f}s is "
            f"{ratio:.2f}x the newest recorded run "
            f"({prev:.4f}s in {prev_name or 'BENCH_*.json'}, "
            f"basis={current_basis}) — >{(threshold - 1) * 100:.0f}% "
            f"regression. Investigate before trusting this build's round "
            f"times.",
            file=sys.stderr,
        )
    return out


def serve_latency_tripwire(current_serve, prev_rec, prev_name=None,
                           backend=None, threshold=SERVE_TRIPWIRE_RATIO,
                           section="serve"):
    """Compare this run's serve p99 against the newest recorded bench.

    The serving analog of ``round_time_tripwire``: returns
    ``{prev_p99_ms, prev_record, ratio, fired}`` or None when no comparable
    record exists (different backend, no recorded ``section`` — "serve" by
    default, "serve_node_array" for the paired layout arm). Only fires
    like-for-like — when the recorded run used a different closed-loop
    config (clients / max_batch / deadline / request profile), the
    comparison is still reported with ``config_mismatch`` set and ``fired``
    False, since a p99 under different load is not a regression signal."""
    if not isinstance(current_serve, dict):
        return None
    cur = current_serve.get("latency_p99_ms")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_serve = prev_rec.get(section)
    if not isinstance(prev_serve, dict):
        return None
    prev = prev_serve.get("latency_p99_ms")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_p99_ms": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_serve.get("config") != current_serve.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] SERVE TRIPWIRE: p99 latency {cur:.2f}ms is "
            f"{ratio:.2f}x the newest recorded run ({prev:.2f}ms in "
            f"{prev_name or 'BENCH_*.json'}) — >{(threshold - 1) * 100:.0f}% "
            f"regression. Investigate before trusting this build's serving "
            f"tail.",
            file=sys.stderr,
        )
    return out


def serve_layout_tripwire(heap_serve, na_serve,
                          threshold=SERVE_LAYOUT_TRIPWIRE_RATIO):
    """Paired-arm tripwire: heap vs node-array p99 from the SAME process.

    Both arms serve the same model under the identical closed-loop config,
    back to back, so this is the low-variance comparison: returns
    ``{heap_p99_ms, node_array_p99_ms, ratio, fired}`` (ratio =
    node_array / heap) or None when either arm is missing its p99. Fires
    when the node-array arm's p99 exceeds ``threshold``x the heap arm's —
    the FIL-style layout's measured tail-latency cut has regressed >20%.
    A config difference between the arms (everything but the ``layout``
    key) is reported with ``config_mismatch`` and never fires."""
    if not isinstance(heap_serve, dict) or not isinstance(na_serve, dict):
        return None
    heap_p99 = heap_serve.get("latency_p99_ms")
    na_p99 = na_serve.get("latency_p99_ms")
    if not heap_p99 or not na_p99:
        return None
    ratio = float(na_p99) / float(heap_p99)
    out = {
        "heap_p99_ms": round(float(heap_p99), 4),
        "node_array_p99_ms": round(float(na_p99), 4),
        "ratio": round(ratio, 3),
        "fired": False,
    }

    def _cfg(section):
        cfg = section.get("config")
        if not isinstance(cfg, dict):
            return None
        return {k: v for k, v in cfg.items() if k != "layout"}

    if _cfg(heap_serve) != _cfg(na_serve):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] SERVE LAYOUT TRIPWIRE: node-array p99 "
            f"{float(na_p99):.2f}ms is {ratio:.2f}x the paired heap arm's "
            f"({float(heap_p99):.2f}ms) — the FIL-style layout's p99 cut "
            f"regressed >{(threshold - 1) * 100:.0f}%. Investigate before "
            f"trusting this build's node-array serving path.",
            file=sys.stderr,
        )
    return out


def chaos_recovery_tripwire(current_chaos, prev_rec, prev_name=None,
                            backend=None, threshold=CHAOS_TRIPWIRE_RATIO):
    """Compare this run's time-to-recover against the newest recorded bench.

    The recovery analog of ``round_time_tripwire``: returns
    ``{prev_time_to_recover_s, prev_record, ratio, fired}`` or None when no
    comparable record exists (different backend, no recorded ``chaos``
    section). Like-for-like only: a different chaos config (rows / rounds /
    actors / fault schedule) is reported with ``config_mismatch`` set and
    never fires."""
    if not isinstance(current_chaos, dict):
        return None
    cur = current_chaos.get("time_to_recover_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_chaos = prev_rec.get("chaos")
    if not isinstance(prev_chaos, dict):
        return None
    prev = prev_chaos.get("time_to_recover_s")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_time_to_recover_s": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_chaos.get("config") != current_chaos.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] CHAOS TRIPWIRE: time-to-recover {cur:.2f}s is "
            f"{ratio:.2f}x the newest recorded run ({prev:.2f}s in "
            f"{prev_name or 'BENCH_*.json'}) — >{(threshold - 1) * 100:.0f}% "
            f"regression. Investigate the recovery path before trusting "
            f"this build's fault tolerance.",
            file=sys.stderr,
        )
    return out


def elastic_recovery_tripwire(current_chaos, prev_rec, prev_name=None,
                              backend=None, threshold=ELASTIC_TRIPWIRE_RATIO):
    """Compare this run's continue-vs-restart recovery ratio against the
    newest recorded bench.

    The elastic-continuation analog of ``chaos_recovery_tripwire``: the
    tracked figure is ``continue_vs_restart.ratio`` (elastic in-flight
    recovery time over restart-from-checkpoint recovery time — smaller is
    better, < 1 means continuation keeps its edge), compared for the base
    pairing AND the per-config pairings (``elastic_2d`` /
    ``elastic_streamed`` — the 2D-mesh and streamed arms that used to be
    fallback cases — and ``elastic_domain``, the correlated host-loss
    arm). Returns ``{prev_ratio, prev_record, ratio, fired[,
    arms]}`` or None when no comparable record exists (different backend,
    no recorded base pairing); ``fired`` is True when ANY arm regresses
    past the threshold. Like-for-like only: a different chaos config is
    reported with ``config_mismatch`` set and never fires (per arm for the
    per-config pairings)."""
    if not isinstance(current_chaos, dict):
        return None
    cur = (current_chaos.get("continue_vs_restart") or {}).get("ratio")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_chaos = prev_rec.get("chaos")
    if not isinstance(prev_chaos, dict):
        return None
    prev = (prev_chaos.get("continue_vs_restart") or {}).get("ratio")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_ratio": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    base_config_matches = (
        prev_chaos.get("config") == current_chaos.get("config")
    )
    if not base_config_matches:
        # the base pairing is reported-but-never-fired on a config change;
        # the per-config arms below still compare (each against its OWN
        # config), so a soak-config change cannot mask an arm regression
        out["config_mismatch"] = True

    def _fire(label, c, p, r):
        out["fired"] = True
        print(
            f"[bench] ELASTIC TRIPWIRE [{label}]: continue-vs-restart "
            f"recovery ratio {c:.3f} is {r:.2f}x the newest recorded run "
            f"({p:.3f} in {prev_name or 'BENCH_*.json'}) — "
            f">{(threshold - 1) * 100:.0f}% regression of the zero-replay "
            f"continuation's advantage. Investigate the in-flight recovery "
            f"path before trusting this build's elastic training.",
            file=sys.stderr,
        )

    if base_config_matches and ratio > threshold:
        _fire("base", float(cur), float(prev), ratio)
    arms = {}
    for key in ("elastic_2d", "elastic_streamed", "elastic_domain"):
        cur_arm = current_chaos.get(key) or {}
        prev_arm = prev_chaos.get(key) or {}
        c = (cur_arm.get("continue_vs_restart") or {}).get("ratio")
        p = (prev_arm.get("continue_vs_restart") or {}).get("ratio")
        if not c or not p:
            continue  # arm absent on one side (older record) — not comparable
        a_ratio = float(c) / float(p)
        arm_out = {
            "prev_ratio": round(float(p), 4),
            "ratio": round(a_ratio, 3),
            "fired": False,
        }
        if prev_arm.get("config") != cur_arm.get("config"):
            arm_out["config_mismatch"] = True
        elif a_ratio > threshold:
            arm_out["fired"] = True
            _fire(key, float(c), float(p), a_ratio)
        arms[key] = arm_out
    if arms:
        out["arms"] = arms
    return out


def sampling_round_time_tripwire(current_sampling, prev_rec, prev_name=None,
                                 backend=None,
                                 threshold=SAMPLING_TRIPWIRE_RATIO):
    """Compare this run's sampled-config (subsample=0.5 arm) steady
    per-round time against the newest recorded bench.

    The sampling analog of ``round_time_tripwire``: returns
    ``{prev_per_round_s, prev_record, ratio, fired}`` or None when no
    comparable record exists (different backend, no recorded ``sampling``
    section). Like-for-like only: a different ablation config (rows /
    rounds / actors / rates) is reported with ``config_mismatch`` set and
    never fires."""
    if not isinstance(current_sampling, dict):
        return None
    cur = (current_sampling.get("subsample") or {}).get("per_round_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_samp = prev_rec.get("sampling")
    if not isinstance(prev_samp, dict):
        return None
    prev = (prev_samp.get("subsample") or {}).get("per_round_s")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_per_round_s": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_samp.get("config") != current_sampling.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] SAMPLING TRIPWIRE: sampled per-round time {cur:.4f}s "
            f"is {ratio:.2f}x the newest recorded run ({prev:.4f}s in "
            f"{prev_name or 'BENCH_*.json'}) — >{(threshold - 1) * 100:.0f}% "
            f"regression. The compacted-build win is eroding; investigate "
            f"before trusting this build's sampled rounds.",
            file=sys.stderr,
        )
    return out


def obs_overhead_tripwire(current_obs, prev_rec=None, prev_name=None,
                          backend=None, threshold=OBS_OVERHEAD_RATIO):
    """Check the tracing-on/tracing-off paired measurement against the
    ≤2% instrumentation budget.

    The obs analog of ``round_time_tripwire``, with one deliberate
    difference: the tracked figure (``overhead_ratio`` = tracing-on steady
    per-round time over tracing-off) is a within-run pairing, so the
    tripwire fires on the CURRENT run's own budget violation — no prior
    snapshot needed. When the newest recorded bench carries a comparable
    ``obs_overhead`` section (same backend, same config), its ratio is
    reported alongside so cross-snapshot drift of the overhead itself stays
    visible. Returns ``{overhead_ratio, budget, fired, ...}`` or ``None``
    when the current section has no ratio (an arm failed to measure)."""
    if not isinstance(current_obs, dict):
        return None
    cur = current_obs.get("overhead_ratio")
    if not cur:
        return None
    out = {
        "overhead_ratio": round(float(cur), 4),
        "budget": threshold,
        "fired": False,
    }
    prev_obs = (prev_rec or {}).get("obs_overhead") \
        if isinstance(prev_rec, dict) else None
    if isinstance(prev_obs, dict) and prev_obs.get("overhead_ratio"):
        if backend and prev_rec.get("backend") \
                and prev_rec["backend"] != backend:
            prev_obs = None
        elif prev_obs.get("config") != current_obs.get("config"):
            out["config_mismatch"] = True
            prev_obs = None
    if isinstance(prev_obs, dict) and prev_obs.get("overhead_ratio"):
        out["prev_overhead_ratio"] = round(
            float(prev_obs["overhead_ratio"]), 4
        )
        out["prev_record"] = prev_name
    if float(cur) > threshold:
        out["fired"] = True
        print(
            f"[bench] OBS OVERHEAD TRIPWIRE: tracing-on steady round time "
            f"is {float(cur):.4f}x tracing-off — over the "
            f"{(threshold - 1) * 100:.0f}% instrumentation budget. The "
            f"span emission path has grown a hot-loop cost; profile "
            f"obs.trace before trusting traced-run timings.",
            file=sys.stderr,
        )
    return out


def run_obs_overhead(x=None, y=None, base_params=None, actors=None):
    """Paired tracing-on vs tracing-off steady-round measurement.

    Two fresh back-to-back trainings of the identical config — one with
    ``RXGB_TRACE=0`` (the tracer's ``span()``/``event()`` become near-free
    no-ops), one with tracing on (the default every production run gets) —
    each 2 scan chunks so the steady median excludes the compile-carrying
    first chunk. The ratio is the price of the obs plane itself, which the
    ≤2% budget (``OBS_OVERHEAD_RATIO``) keeps honest: instrumentation that
    costs real round time is a perf regression like any other. Returns the
    ``obs_overhead`` section for the BENCH record."""
    import jax

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
    rounds = int(os.environ.get("BENCH_OBS_OVERHEAD_ROUNDS", 2 * chunk))
    if x is None or y is None:
        n_rows = int(os.environ.get("BENCH_OBS_OVERHEAD_ROWS", 25_000))
        x, y = make_higgs_like(n_rows, 28, seed=5)
    if actors is None:
        actors = int(os.environ.get(
            "BENCH_ACTORS", max(1, len(jax.devices()))
        ))
    params = {
        "objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
        "max_bin": 256, "tree_method": "tpu_hist",
    }
    if base_params:
        params.update(base_params)

    out = {"rounds": rounds}
    saved = os.environ.get("RXGB_TRACE")
    try:
        for arm, flag in (("tracing_off", "0"), ("tracing_on", "1")):
            os.environ["RXGB_TRACE"] = flag
            res = {}
            t0 = time.time()
            train(
                params, RayDMatrix(x, y), num_boost_round=rounds,
                additional_results=res,
                ray_params=RayParams(num_actors=actors,
                                     checkpoint_frequency=0),
            )
            arm_time = time.time() - t0
            out[arm] = {
                "per_round_s": round(_steady_per_round(
                    res.get("round_times_s"), chunk, arm_time, rounds
                ), 4),
                "train_time_s": round(arm_time, 2),
            }
            if flag == "1":
                obs_res = res.get("obs") or {}
                out[arm]["records"] = len(obs_res.get("timeline") or [])
                out[arm]["dropped_spans"] = obs_res.get("dropped_spans", 0)
    finally:
        if saved is None:
            os.environ.pop("RXGB_TRACE", None)
        else:
            os.environ["RXGB_TRACE"] = saved
    off_s = out["tracing_off"]["per_round_s"]
    if off_s:
        out["overhead_ratio"] = round(
            out["tracing_on"]["per_round_s"] / off_s, 4
        )
        out["within_budget"] = out["overhead_ratio"] <= OBS_OVERHEAD_RATIO
    out["config"] = {
        "rows": int(x.shape[0]), "features": int(x.shape[1]),
        "rounds": rounds, "actors": actors,
        "max_depth": int(params.get("max_depth", 6)),
    }
    print(f"[bench] obs overhead: {out}", file=sys.stderr)
    return out


def run_sampling_ablation(x, y, base_params, actors):
    """Paired full/sampled training ablation on the ambient mesh.

    Three arms, fresh and back-to-back (identical environment): full rows,
    ``subsample=0.5``, and GOSS (``sampling_method='gradient_based'``,
    a=0.1 / b=0.1). Each runs 2 scan chunks so the steady per-round median
    excludes the compile-carrying first chunk, and each records its final
    train logloss — the win must show up in wall clock WITHOUT the metric
    drifting outside the documented tolerance. Arms train with NO eval
    sets (logloss is computed post-hoc from the predicted margins) so the
    "full" arm is config-identical to the protocol run and the hist_quant
    ablation's "none" arm — ``r4_paired_recheck`` depends on that
    like-for-like pairing. Returns the ``sampling`` section with per-arm
    timings and sampled/full ratios."""
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
    abl_rounds = int(
        os.environ.get("BENCH_SAMPLING_ABLATION_ROUNDS", 2 * chunk)
    )
    arms = {
        "full": {},
        "subsample": {"subsample": 0.5},
        "goss": {"sampling_method": "gradient_based", "top_rate": 0.1,
                 "other_rate": 0.1},
    }

    def binary_logloss(margin):
        p = 1.0 / (1.0 + np.exp(-np.asarray(margin, np.float64).ravel()))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    out = {"rounds": abl_rounds}
    for name, extra in arms.items():
        p = dict(base_params)
        p.update(extra)
        res = {}
        t0 = time.time()
        bst = train(
            p,
            RayDMatrix(x, y),
            num_boost_round=abl_rounds,
            additional_results=res,
            ray_params=RayParams(num_actors=actors, checkpoint_frequency=0),
        )
        arm_time = time.time() - t0
        per_round = _steady_per_round(
            res.get("round_times_s"), chunk, arm_time, abl_rounds
        )
        out[name] = {
            "per_round_s": round(per_round, 4),
            "train_time_s": round(arm_time, 2),
            "final_logloss": round(
                binary_logloss(bst.predict(x, output_margin=True)), 5
            ),
        }
    full_s = out["full"]["per_round_s"]
    if full_s:
        out["subsample_per_round_vs_full"] = round(
            out["subsample"]["per_round_s"] / full_s, 3
        )
        out["goss_per_round_vs_full"] = round(
            out["goss"]["per_round_s"] / full_s, 3
        )
    full_ll = out["full"]["final_logloss"]
    out["subsample_logloss_delta"] = round(
        out["subsample"]["final_logloss"] - full_ll, 5
    )
    out["goss_logloss_delta"] = round(
        out["goss"]["final_logloss"] - full_ll, 5
    )
    out["config"] = {
        "rows": int(x.shape[0]), "features": int(x.shape[1]),
        "rounds": abl_rounds, "actors": actors,
        "max_depth": int(base_params.get("max_depth", 6)),
        # derived from the arms dict so the recorded config (the tripwire's
        # like-for-like key) cannot drift from what actually ran
        "subsample_rate": arms["subsample"]["subsample"],
        "goss_top_rate": arms["goss"]["top_rate"],
        "goss_other_rate": arms["goss"]["other_rate"],
    }
    print(f"[bench] sampling ablation: {out}", file=sys.stderr)
    return out


def low_precision_tripwire(current_lp, prev_rec, prev_name=None,
                           backend=None,
                           threshold=LOW_PRECISION_TRIPWIRE_RATIO):
    """Compare this run's gh_precision='int8' arm steady per-round time
    against the newest recorded bench's ``low_precision`` section, and —
    when both records carry it — the composed ``int8_block_wire`` arm too
    (records predating the block wire simply lack the arm; the watch is
    skipped, never fired, so old snapshots stay comparable).

    The quantized-gradient analog of ``sampling_round_time_tripwire``:
    returns ``{prev_per_round_s, prev_record, ratio, fired}`` or None when
    no comparable record exists. Like-for-like only (config key)."""
    if not isinstance(current_lp, dict):
        return None
    cur = (current_lp.get("int8") or {}).get("per_round_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_lp = prev_rec.get("low_precision")
    if not isinstance(prev_lp, dict):
        return None
    prev = (prev_lp.get("int8") or {}).get("per_round_s")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_per_round_s": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_lp.get("config") != current_lp.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] LOW-PRECISION TRIPWIRE: int8-gh per-round time "
            f"{cur:.4f}s is {ratio:.2f}x the newest recorded run "
            f"({prev:.4f}s in {prev_name or 'BENCH_*.json'}) — "
            f">{(threshold - 1) * 100:.0f}% regression. The quantized-"
            f"gradient mode is rotting into a slow path; investigate "
            f"before trusting this build's low-precision numbers.",
            file=sys.stderr,
        )
    cur_b = (current_lp.get("int8_block_wire") or {}).get("per_round_s")
    prev_b = (prev_lp.get("int8_block_wire") or {}).get("per_round_s")
    if cur_b and prev_b:
        bratio = float(cur_b) / float(prev_b)
        out["block_wire_ratio"] = round(bratio, 3)
        out["prev_block_wire_per_round_s"] = round(float(prev_b), 4)
        if bratio > threshold:
            out["fired"] = True
            print(
                f"[bench] LOW-PRECISION TRIPWIRE: int8_block_wire per-round "
                f"time {cur_b:.4f}s is {bratio:.2f}x the newest recorded "
                f"run ({prev_b:.4f}s in {prev_name or 'BENCH_*.json'}) — "
                f">{(threshold - 1) * 100:.0f}% regression. The block-"
                f"scaled ring is rotting into a slow path; investigate "
                f"before trusting this build's wire numbers.",
                file=sys.stderr,
            )
    return out


def run_low_precision_ablation(x, y, base_params, actors):
    """Paired gh-precision ablation on the ambient mesh: f32 vs int16 vs
    int8 quantized gradients (ROADMAP item 3's measured contract).

    Six arms, fresh and back-to-back (identical environment), each
    config-identical to the protocol run except the precision knobs — and
    the f32 reference runs TWICE, bracketing the quantized arms
    (f32, int16, int8, int8_row_wire, int8_block_wire, f32_recheck): the
    two wire arms compose int8 gradients with the quantized actors-axis
    histogram wire (row scales vs block scales) and carry the block
    format's measured byte-cut and block-vs-row logloss-parity gates.
    Same-process round time drifts a few
    percent over a multi-minute capture (the r4_paired_recheck lesson), so
    comparing the last arm against the first conflates that drift with the
    mode under test. Ratios are judged against the bracket MEAN, and the
    recheck/first ratio is recorded as ``f32_drift_ratio`` so every capture
    carries its own noise bound. Per arm: steady per-round time (min over
    the post-compile chunks' true wall times), the static per-shard
    gh-plane bytes (the
    memory metric the mode is bought for — int8 must cut
    >= LOW_PRECISION_GH_CUT_MIN x; rxgbverify certifies the traced
    programs really carry the narrow dtype), and the final train logloss.
    The section asserts the shipping contract: both quantized arms within
    LOW_PRECISION_LOGLOSS_TOL of f32 (judged on UNROUNDED loglosses), and
    int8 steady-round time <= LOW_PRECISION_ROUND_TIME_MAX x the f32
    bracket mean, with the budget widened by the capture's own measured
    f32-vs-f32 drift (a gate tighter than the reference's same-config
    noise would fire on machine weather, not on the mode)."""
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
    # three chunks per arm (one compile-carrying + two steady) so the
    # steady figure can be the MIN over steady chunks: shared-box
    # contention only ever inflates a chunk, so the minimum is the
    # statistic least polluted by co-scheduling hiccups (the timeit
    # discipline) — medians over a single steady chunk inherit whichever
    # weather that chunk ran under
    abl_rounds = int(
        os.environ.get("BENCH_LOW_PRECISION_ROUNDS", 3 * chunk)
    )
    arms = {
        "f32": {},
        "int16": {"gh_precision": "int16"},
        "int8": {"gh_precision": "int8"},
        # composed wire arms (PR 19): int8 gradients x quantized actors-axis
        # histogram wire, row scales vs block scales — the paired comparison
        # the block format is bought for. min_bytes=0 so every level really
        # takes the quantized wire at ablation scale.
        "int8_row_wire": {"gh_precision": "int8", "hist_quant": "int8",
                          "hist_quant_min_bytes": 0},
        "int8_block_wire": {"gh_precision": "int8",
                            "hist_quant": "int8_block",
                            "hist_quant_min_bytes": 0},
        "f32_recheck": {},
    }

    def steady(res, arm_time):
        """Min steady per-round over the post-compile chunks from the TRUE
        per-dispatch chunk wall times; falls back to the shared estimator
        when chunk times are absent (per-round stepping paths)."""
        chunks = [
            c["seconds"] / max(1, c["rounds"])
            for c in (res.get("chunk_times_s") or [])[1:]
            if isinstance(c, dict) and c.get("rounds")
        ]
        if chunks:
            return min(chunks)
        return _steady_per_round(
            res.get("round_times_s"), chunk, arm_time, abl_rounds
        )

    def binary_logloss(margin):
        p = 1.0 / (1.0 + np.exp(-np.asarray(margin, np.float64).ravel()))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    out = {"rounds": abl_rounds}
    ll_exact = {}  # unrounded per-arm loglosses: the tolerance gate's inputs
    pr_exact = {}  # unrounded per-arm steady times: the round-time gate's
    #   inputs (stored per_round_s is display — the same discipline as the
    #   gh-bytes and logloss gates)
    for name, extra in arms.items():
        p = dict(base_params)
        p.update(extra)
        res = {}
        t0 = time.time()
        bst = train(
            p,
            RayDMatrix(x, y),
            num_boost_round=abl_rounds,
            additional_results=res,
            ray_params=RayParams(num_actors=actors, checkpoint_frequency=0),
        )
        arm_time = time.time() - t0
        pr_exact[name] = steady(res, arm_time)
        ll_exact[name] = binary_logloss(bst.predict(x, output_margin=True))
        arm = {
            "per_round_s": round(pr_exact[name], 4),
            "train_time_s": round(arm_time, 2),
            "final_logloss": round(ll_exact[name], 6),
        }
        gh_bytes = res.get("gh_plane_bytes_per_shard")
        if gh_bytes is not None:
            arm["gh_plane_bytes_per_shard"] = int(gh_bytes)
        wire_bytes = res.get("hist_allreduce_bytes_per_round")
        if wire_bytes is not None:
            arm["hist_allreduce_bytes_per_round"] = int(wire_bytes)
        out[name] = arm
    # drift-resistant f32 reference: the mean of the two bracket arms (the
    # int arms ran between them), plus the recheck/first drift bound
    f32_s = 0.5 * (pr_exact["f32"] + pr_exact["f32_recheck"])
    drift = 1.0
    if pr_exact["f32"]:
        drift = pr_exact["f32_recheck"] / pr_exact["f32"]
        out["f32_drift_ratio"] = round(drift, 3)
    if f32_s:
        out["int16_per_round_vs_f32"] = round(pr_exact["int16"] / f32_s, 3)
        out["int8_per_round_vs_f32"] = round(pr_exact["int8"] / f32_s, 3)
        # the budget is widened by the capture's OWN measured same-config
        # noise (the two f32 arms trained the identical program): a gate
        # tighter than the drift the reference itself exhibits would fire
        # on machine weather, not on the mode under test — the
        # r4_paired_recheck "pair ratio bounds same-env variance" logic
        budget = LOW_PRECISION_ROUND_TIME_MAX * max(1.0, drift)
        out["round_time_budget"] = round(budget, 3)
        out["round_time_ok"] = pr_exact["int8"] / f32_s <= budget
        if not out["round_time_ok"]:
            print(
                f"[bench] LOW-PRECISION ROUND TIME over budget: int8-gh "
                f"steady round is {out['int8_per_round_vs_f32']}x the f32 "
                f"bracket mean (budget {LOW_PRECISION_ROUND_TIME_MAX}x "
                f"widened to {out['round_time_budget']}x by the capture's "
                f"own f32 drift).",
                file=sys.stderr,
            )
    b_f32 = out["f32"].get("gh_plane_bytes_per_shard")
    b_int8 = out["int8"].get("gh_plane_bytes_per_shard")
    if b_f32 and b_int8:
        # the gate reads the unrounded ratio; the stored value is display
        out["gh_bytes_cut"] = round(b_f32 / b_int8, 2)
        out["gh_bytes_cut_ok"] = (b_f32 / b_int8) >= LOW_PRECISION_GH_CUT_MIN
        if not out["gh_bytes_cut_ok"]:
            print(
                f"[bench] LOW-PRECISION GH-PLANE CUT below floor: int8 "
                f"stores only {out['gh_bytes_cut']}x fewer gh bytes/shard "
                f"than f32 (floor {LOW_PRECISION_GH_CUT_MIN}x).",
                file=sys.stderr,
            )
    # parity judged on the UNROUNDED per-arm loglosses (the wide_feature
    # discipline: rounding first can slip a near-miss under the gate)
    for name in ("int16", "int8"):
        delta = ll_exact[name] - ll_exact["f32"]
        out[f"{name}_logloss_delta"] = round(delta, 6)
        out[f"{name}_logloss_ok"] = abs(delta) <= LOW_PRECISION_LOGLOSS_TOL
        if not out[f"{name}_logloss_ok"]:
            print(
                f"[bench] LOW-PRECISION LOGLOSS drift: {name}-gh final "
                f"logloss differs from f32 by {out[f'{name}_logloss_delta']} "
                f"(> {LOW_PRECISION_LOGLOSS_TOL}). Quantized-gradient "
                f"accuracy is drifting; fall back to gh_precision='float32' "
                f"until understood.",
                file=sys.stderr,
            )
    # composed wire arms: the block format's measured contract is (a) the
    # ppermute ring moves strictly fewer bytes than the row-scale wire at
    # the same payload and (b) the two int8-granularity wires agree in
    # final logloss (block-vs-row parity; both sit ~1e-3 absolute from f32
    # at this protocol — row and block alike — so the 5e-4 ABSOLUTE gate
    # stays on the gh arms where it physically holds, and the per-arm f32
    # deltas are recorded unGated for the drift history)
    wb_row = out["int8_row_wire"].get("hist_allreduce_bytes_per_round")
    wb_block = out["int8_block_wire"].get("hist_allreduce_bytes_per_round")
    if wb_row and wb_block:
        out["block_wire_bytes_cut"] = round(wb_row / wb_block, 4)
        out["block_wire_bytes_ok"] = wb_block < wb_row
        if not out["block_wire_bytes_ok"]:
            print(
                f"[bench] BLOCK WIRE BYTES not below row wire: int8_block "
                f"moved {wb_block} B/round vs int8 row {wb_row} B/round — "
                f"the in-band-scale ring lost its byte cut; see the "
                f"low-precision runbook in README.",
                file=sys.stderr,
            )
    for name in ("int8_row_wire", "int8_block_wire"):
        out[f"{name}_logloss_delta"] = round(
            ll_exact[name] - ll_exact["f32"], 6
        )
    wire_delta = ll_exact["int8_block_wire"] - ll_exact["int8_row_wire"]
    out["block_vs_row_logloss_delta"] = round(wire_delta, 6)
    # two-tier accuracy contract for the block wire (mirrors
    # tests/test_hist_quant.py): ALWAYS gate "block no worse than the row
    # wire vs f32" — the scale-robust check that catches block-format
    # accuracy rot — and gate the strict 5e-4 block-vs-row parity only at
    # protocol scale (>=100k rows; at smoke shapes the two wires path-
    # diverge by ~1e-3 from sheer sample noise, which says nothing about
    # the wire format)
    d_row = abs(ll_exact["int8_row_wire"] - ll_exact["f32"])
    d_block = abs(ll_exact["int8_block_wire"] - ll_exact["f32"])
    out["block_no_worse_than_row_ok"] = (
        d_block <= d_row + LOW_PRECISION_LOGLOSS_TOL
    )
    if not out["block_no_worse_than_row_ok"]:
        print(
            f"[bench] BLOCK WIRE LOGLOSS drift: int8_block sits "
            f"{d_block:.6f} from f32 vs the row wire's {d_row:.6f} "
            f"(margin {LOW_PRECISION_LOGLOSS_TOL}). The block-scale "
            f"rounding is drifting from the row-scale reference; fall "
            f"back to hist_quant='int8' until understood (README "
            f"runbook).",
            file=sys.stderr,
        )
    if x.shape[0] >= 100_000:
        out["block_vs_row_logloss_ok"] = (
            abs(wire_delta) <= LOW_PRECISION_LOGLOSS_TOL
        )
        if not out["block_vs_row_logloss_ok"]:
            print(
                f"[bench] BLOCK WIRE PARITY: block-vs-row logloss delta "
                f"{out['block_vs_row_logloss_delta']} exceeds "
                f"{LOW_PRECISION_LOGLOSS_TOL} at protocol scale — the two "
                f"int8 wires no longer track each other (measured 6e-5 at "
                f"200k when healthy); see README runbook.",
                file=sys.stderr,
            )
    out["config"] = {
        "rows": int(x.shape[0]), "features": int(x.shape[1]),
        "rounds": abl_rounds, "actors": actors,
        "max_depth": int(base_params.get("max_depth", 6)),
        # derived from the arms dict so the recorded config (the tripwire's
        # like-for-like key) cannot drift from what actually ran; the
        # bracket design (two f32 arms) is part of the protocol identity
        # lists, not tuples: the prev record round-trips through JSON and
        # the tripwire's like-for-like comparison is plain ==
        "arm_modes": [
            [k, v.get("gh_precision", "float32"),
             v.get("hist_quant", "none")] for k, v in arms.items()
        ],
    }
    print(f"[bench] low-precision ablation: {out}", file=sys.stderr)
    return out


#: streamed-ingest throughput guard: prev/current rows-per-second beyond
#: this fires (a >20% ingest slowdown — the streaming hot path is host
#: binning + H2D, both easy to silently regress)
STREAMING_TRIPWIRE_RATIO = 1.25

#: the streamed-vs-materialized accuracy contract at bench scale (same
#: bound the acceptance criterion and tests/test_streaming.py pin)
STREAMING_LOGLOSS_TOL = 5e-4


def streaming_ingest_tripwire(current_streaming, prev_rec, prev_name=None,
                              backend=None,
                              threshold=STREAMING_TRIPWIRE_RATIO):
    """Compare this run's streamed ingest throughput (rows/s) against the
    newest recorded bench's ``streaming`` section.

    Returns ``{prev_rows_per_s, prev_record, ratio, fired}`` or None when
    no comparable record exists; like-for-like only (config key), cross-
    backend records skipped. ``ratio`` is prev/current, so >threshold
    means ingest got >(threshold-1)x slower."""
    if not isinstance(current_streaming, dict):
        return None
    cur = (current_streaming.get("streamed") or {}).get("rows_per_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_sec = prev_rec.get("streaming")
    if not isinstance(prev_sec, dict):
        return None
    prev = (prev_sec.get("streamed") or {}).get("rows_per_s")
    if not prev:
        return None
    ratio = float(prev) / float(cur)
    out = {
        "prev_rows_per_s": round(float(prev), 1),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_sec.get("config") != current_streaming.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] STREAMING TRIPWIRE: streamed ingest throughput "
            f"{cur:.0f} rows/s is {ratio:.2f}x slower than the newest "
            f"recorded run ({prev:.0f} rows/s in "
            f"{prev_name or 'BENCH_*.json'}) — "
            f">{(threshold - 1) * 100:.0f}% ingest regression. The "
            f"sketch/bin/H2D pipeline is rotting; investigate before "
            f"trusting this build's out-of-core numbers.",
            file=sys.stderr,
        )
    return out


class _RssPeakSampler:
    """Peak process RSS over the sampled window (background thread, 5 ms).

    psutil when present, /proc/self/statm otherwise — psutil is not in
    setup.py's install_requires, and the streaming ablation is default-on
    for CPU bench runs, so a bare install must still be able to sample.
    """

    def __init__(self):
        self._read_rss = self._pick_reader()
        self.baseline = 0
        self.peak = 0

    @staticmethod
    def _pick_reader():
        try:
            import psutil

            proc = psutil.Process()
            return lambda: proc.memory_info().rss
        except ImportError:
            pass
        try:
            page = os.sysconf("SC_PAGE_SIZE")

            def read_statm():
                with open("/proc/self/statm") as fh:
                    return int(fh.read().split()[1]) * page

            read_statm()  # probe: /proc is Linux-only
            return read_statm
        except (OSError, ValueError):
            pass
        # last resort (macOS/BSD without psutil): lifetime peak RSS via
        # getrusage — monotone, so window deltas under-count only when an
        # earlier phase peaked higher
        import resource

        scale = 1 if sys.platform == "darwin" else 1024  # bytes vs KiB
        return lambda: resource.getrusage(
            resource.RUSAGE_SELF
        ).ru_maxrss * scale

    def __enter__(self):
        import threading

        self._stop = threading.Event()
        self.baseline = self._read_rss()
        self.peak = self.baseline

        def run():
            while not self._stop.is_set():
                self.peak = max(self.peak, self._read_rss())
                time.sleep(0.005)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join()
        self.peak = max(self.peak, self._read_rss())

    @property
    def delta_mb(self):
        return (self.peak - self.baseline) / 2**20


def run_streaming_ablation(x, y, base_params, actors):
    """Materialized-vs-streamed ingestion ablation on the ambient mesh
    (ROADMAP item 1's measured contract).

    Two arms over the SAME data, fresh and back-to-back: the materialized
    engine (raw f32 shard device-put + on-device sketch) and the streamed
    engine (chunked two-pass sketch→bin with double-buffered upload). Per
    arm: peak host RSS delta while the engine builds + trains (streamed
    must drop — the raw f32 copies never exist), ingest wall time, and the
    final train logloss; the streamed arm additionally records ingest
    throughput (the tripwire metric), the sketch/bin/H2D phase split from
    the engine's stream stats, and the overlap efficiency — the fraction
    of the smaller of (bin, H2D) hidden behind the other by the double
    buffer. The accuracy contract (|streamed - materialized| final logloss
    <= STREAMING_LOGLOSS_TOL) is recorded as ``logloss_delta_ok`` and a
    violation prints a LOUD stderr line — tests/test_streaming.py pins the
    bound itself; the bench records it at scale.
    """
    import gc

    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params
    from xgboost_ray_tpu.stream.reader import array_shard_stream

    rounds = int(os.environ.get("BENCH_STREAM_ROUNDS", "8"))
    chunk_rows = int(os.environ.get(
        "BENCH_STREAM_CHUNK", str(max(4096, x.shape[0] // 16))
    ))
    parsed = parse_params({
        k: v for k, v in base_params.items() if k != "tree_method"
    })

    def binary_logloss(margin):
        p = 1.0 / (1.0 + np.exp(-np.asarray(margin, np.float64).ravel()))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    out = {"rounds": rounds}
    logloss = {}
    for arm in ("materialized", "streamed"):
        gc.collect()
        with _RssPeakSampler() as rss:
            t0 = time.time()
            if arm == "streamed":
                shards = [array_shard_stream(x, label=y,
                                             chunk_rows=chunk_rows)]
            else:
                shards = [{"data": x, "label": y}]
            eng = TpuEngine(shards, parsed, num_actors=actors)
            ingest_s = time.time() - t0
            for i in range(rounds):
                eng.step(i)
        margin = eng._fetch_rows(eng.margins, eng.valid, x.shape[0])
        logloss[arm] = binary_logloss(margin)
        arm_out = {
            "rss_peak_delta_mb": round(rss.delta_mb, 1),
            "ingest_s": round(ingest_s, 3),
            "final_logloss": round(logloss[arm], 6),
        }
        if arm == "streamed":
            stats = eng._stream_stats or {}
            arm_out["rows_per_s"] = round(x.shape[0] / max(ingest_s, 1e-9), 1)
            for k in ("chunks", "sketch_s", "bin_s", "transfer_s",
                      "pass2_wall_s", "rank_error_bound_max"):
                if k in stats:
                    arm_out[k] = stats[k]
            bin_s = float(stats.get("bin_s") or 0.0)
            h2d_s = float(stats.get("transfer_s") or 0.0)
            wall2 = float(stats.get("pass2_wall_s") or 0.0)
            hidden = max(0.0, bin_s + h2d_s - wall2)
            denom = max(min(bin_s, h2d_s), 1e-9)
            arm_out["overlap_efficiency"] = round(
                min(1.0, hidden / denom), 3
            )
        out[arm] = arm_out
        del eng
    out["logloss_delta"] = round(
        abs(logloss["streamed"] - logloss["materialized"]), 6
    )
    out["logloss_delta_ok"] = out["logloss_delta"] <= STREAMING_LOGLOSS_TOL
    if not out["logloss_delta_ok"]:
        print(
            f"[bench] STREAMING ACCURACY: streamed final logloss drifted "
            f"{out['logloss_delta']} from materialized "
            f"(tolerance {STREAMING_LOGLOSS_TOL}) — the sketch path's cuts "
            f"moved; see the streaming runbook in README.",
            file=sys.stderr,
        )
    out["rss_drop_ok"] = (
        out["streamed"]["rss_peak_delta_mb"]
        < out["materialized"]["rss_peak_delta_mb"]
    )
    out["config"] = {
        "rows": int(x.shape[0]),
        "features": int(x.shape[1]),
        "rounds": rounds,
        "chunk_rows": chunk_rows,
        "actors": actors,
        "max_depth": int(parsed.max_depth),
    }
    return out


#: --large drift guard: >20% steady per-round regression of the composed
#: (streamed x int8-gh x int8_block-wire) arm across snapshots
LARGE_TRIPWIRE_RATIO = 1.2
#: --large accuracy envelope, RELATIVE to the f32 reference logloss: the
#: composed arm carries int8-granularity wire rounding, which sits ~2e-3
#: relative from f32 at the 200k/10-round protocol (row and block scales
#: alike — the 5e-4 ABSOLUTE bound is pinned where it physically holds:
#: int16_block vs f32 and block-vs-row, tests/test_hist_quant.py). The
#: relative gate catches the failure mode that matters at scale: the
#: composed pipeline drifting from "tracks f32" to "trains a different
#: model".
LARGE_LOGLOSS_REL_TOL = 5e-3


def _meminfo_available_mb():
    """MemAvailable from /proc/meminfo in MB, or None off-Linux."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return None


def _synthetic_higgs_stream(n_rows, n_feat, seed=0, chunk_rows=None):
    """A fully synthetic generator-backed ShardStream: ``chunk_fn``
    SYNTHESIZES HIGGS-shaped rows (the make_higgs_like recipe) for
    [lo, hi) on demand, so the full matrix never exists on the host —
    peak host memory is O(chunk), which is what lets --large reach rows
    that a materialized ``make_higgs_like`` array could not.

    Rows are generated in fixed 65536-row blocks each seeded by
    (seed, block index), so the dataset is a pure function of
    (n_rows, n_feat, seed) — independent of chunk boundaries, identical
    across the two-pass read and across arms."""
    from xgboost_ray_tpu.stream.reader import ShardStream, StreamConfig

    block = 65536

    def _block(bi):
        rng = np.random.RandomState((int(seed) * 1000003 + bi) % (2 ** 31))
        lo = bi * block
        rows = min(block, n_rows - lo)
        bx = rng.standard_normal(size=(rows, n_feat)).astype(np.float32)
        logits = (0.8 * bx[:, 0] - 0.6 * bx[:, 1]
                  + 0.4 * bx[:, 2] * bx[:, 3] + 0.3 * bx[:, 4])
        by = (logits + rng.standard_normal(rows).astype(np.float32)
              > 0).astype(np.float32)
        return bx, by

    def chunk_fn(lo, hi):
        xs, ys = [], []
        for bi in range(lo // block, (hi - 1) // block + 1):
            bx, by = _block(bi)
            s = slice(max(0, lo - bi * block), min(block, hi - bi * block))
            xs.append(bx[s])
            ys.append(by[s])
        return {"data": np.concatenate(xs), "label": np.concatenate(ys)}

    stream = ShardStream(
        n_rows, n_feat, chunk_fn,
        config=StreamConfig(chunk_rows=chunk_rows),
        source_token=("synthetic_higgs", int(n_rows), int(n_feat),
                      int(seed)),
    )
    return {"stream": stream}, chunk_fn


def run_large_measurement():
    """``--large``: the composed-headline run, MEASURED — never
    extrapolated. Streams a HIGGS-shaped dataset (11M rows when the host
    allows; auto-scaled DOWN and recorded/printed otherwise, never
    silently) through the full low-precision pipeline — streamed binned
    ingest x gh_precision=int8 x hist_quant=int8_block — against a
    config-identical f32 reference arm on the same synthetic stream.

    Per arm: peak host RSS delta over build+train, per-device peak memory
    when the backend reports it (recorded as unavailable otherwise),
    steady per-round time (min over post-compile rounds), measured wire
    bytes per round, and the final train logloss via chunked predict over
    the regenerated stream (the matrix is never materialized). Gates:
    peak host RSS within the memory budget (2x the binned matrix + 768 MB
    slack by default, BENCH_LARGE_MEM_BUDGET_MB overrides), composed
    logloss within LARGE_LOGLOSS_REL_TOL relative of f32, and the
    composed arm moving strictly fewer wire bytes than the f32 psum."""
    import gc

    import jax

    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params

    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_LARGE_ROUNDS", 20))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    actors = int(os.environ.get("BENCH_ACTORS",
                                max(1, len(jax.devices()))))
    requested = int(os.environ.get("BENCH_LARGE_ROWS", 11_000_000))

    # auto-scale rows to the host: the streamed pipeline's resident set is
    # ~(1 binned byte per feature + bookkeeping) per row; cap the run so
    # the estimate stays under 40% of MemAvailable. NEVER silent: the
    # requested and actual row counts are both recorded and printed.
    avail_mb = _meminfo_available_mb()
    est_bytes_per_row = n_feat + 64
    rows = requested
    if avail_mb is not None:
        cap = int(avail_mb * 0.4 * 2 ** 20 / est_bytes_per_row)
        rows = min(requested, cap)
    if rows < requested:
        print(
            f"[bench] --large AUTO-SCALED: host MemAvailable "
            f"{avail_mb} MB supports ~{rows} rows at "
            f"{est_bytes_per_row} B/row estimated; requested {requested}. "
            f"Running the MEASURED smaller shape — figures below are real "
            f"measurements at rows={rows}, not the requested scale.",
            file=sys.stderr,
        )
    chunk_rows = int(os.environ.get(
        "BENCH_LARGE_CHUNK", str(max(65536, rows // 64))
    ))
    binned_mb = rows * n_feat / 2 ** 20
    budget_mb = float(os.environ.get(
        "BENCH_LARGE_MEM_BUDGET_MB", str(2.0 * binned_mb + 768.0)
    ))

    base = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss"],
        "max_depth": depth,
        "eta": 0.1,
        "max_bin": 256,
    }
    arms = {
        "f32": {},
        "composed": {"gh_precision": "int8", "hist_quant": "int8_block",
                     "hist_quant_min_bytes": 0},
    }
    out = {
        "rows_requested": requested,
        "rows": rows,
        "auto_scaled": rows < requested,
        "features": n_feat,
        "rounds": rounds,
        "actors": actors,
        "chunk_rows": chunk_rows,
        "host_mem_available_mb": avail_mb,
        "mem_budget_mb": round(budget_mb, 1),
    }

    def _device_peak_mb():
        peaks = []
        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and stats.get("peak_bytes_in_use"):
                peaks.append(stats["peak_bytes_in_use"])
        if peaks:
            return round(sum(peaks) / 2 ** 20, 1)
        return None

    ll_exact = {}
    for name, extra in arms.items():
        gc.collect()
        shard, chunk_fn = _synthetic_higgs_stream(
            rows, n_feat, seed=0, chunk_rows=chunk_rows
        )
        parsed = parse_params(dict(base, **extra))
        with _RssPeakSampler() as rss:
            t0 = time.time()
            eng = TpuEngine([shard], parsed, num_actors=actors)
            ingest_s = time.time() - t0
            round_s = []
            for i in range(rounds):
                r0 = time.time()
                eng.step(i)
                round_s.append(time.time() - r0)
        train_s = sum(round_s)
        # chunked logloss over the regenerated stream: predict per chunk,
        # accumulate the sum — the matrix is never materialized
        bst = eng.get_booster()
        n_seen, ll_sum = 0, 0.0
        for lo in range(0, rows, chunk_rows):
            hi = min(lo + chunk_rows, rows)
            fields = chunk_fn(lo, hi)
            margin = np.asarray(
                bst.predict(fields["data"], output_margin=True), np.float64
            ).ravel()
            p = np.clip(1.0 / (1.0 + np.exp(-margin)), 1e-15, 1 - 1e-15)
            cy = fields["label"].astype(np.float64)
            ll_sum += float(-np.sum(cy * np.log(p)
                                    + (1 - cy) * np.log1p(-p)))
            n_seen += hi - lo
        ll_exact[name] = ll_sum / max(1, n_seen)
        arm_out = {
            "ingest_s": round(ingest_s, 3),
            "train_s": round(train_s, 2),
            "steady_per_round_s": round(min(round_s[1:]) if len(round_s) > 1
                                        else round_s[0], 4),
            "rss_peak_delta_mb": round(rss.delta_mb, 1),
            "final_logloss": round(ll_exact[name], 6),
        }
        dev_mb = _device_peak_mb()
        arm_out["device_peak_mb"] = (
            dev_mb if dev_mb is not None else "unavailable"
        )
        wire = eng.hist_allreduce_bytes_per_round()
        if wire is not None:
            arm_out["hist_allreduce_bytes_per_round"] = int(wire)
        gh = getattr(eng, "gh_plane_bytes_per_shard", None)
        if callable(gh):
            arm_out["gh_plane_bytes_per_shard"] = int(gh())
        out[name] = arm_out
        del eng
    # gates — all three recorded, all three loud on failure
    peak = max(out["f32"]["rss_peak_delta_mb"],
               out["composed"]["rss_peak_delta_mb"])
    out["mem_budget_ok"] = peak <= budget_mb
    if not out["mem_budget_ok"]:
        print(
            f"[bench] LARGE MEMORY over budget: peak host RSS delta "
            f"{peak} MB exceeds the {budget_mb:.0f} MB budget "
            f"(2x binned + slack) — a full-f32 materialization has crept "
            f"into the streamed path.",
            file=sys.stderr,
        )
    delta = ll_exact["composed"] - ll_exact["f32"]
    out["logloss_delta"] = round(delta, 6)
    rel = abs(delta) / max(abs(ll_exact["f32"]), 1e-9)
    out["logloss_rel_delta"] = round(rel, 6)
    out["logloss_ok"] = rel <= LARGE_LOGLOSS_REL_TOL
    if not out["logloss_ok"]:
        print(
            f"[bench] LARGE LOGLOSS drift: composed arm differs from f32 "
            f"by {rel:.2%} relative (> {LARGE_LOGLOSS_REL_TOL:.1%}) — the "
            f"low-precision composition is no longer tracking the "
            f"reference; fall back per the README runbook.",
            file=sys.stderr,
        )
    wb_f32 = out["f32"].get("hist_allreduce_bytes_per_round")
    wb_comp = out["composed"].get("hist_allreduce_bytes_per_round")
    if wb_f32 and wb_comp:
        out["wire_bytes_cut"] = round(wb_f32 / wb_comp, 2)
        out["wire_bytes_ok"] = wb_comp < wb_f32
        if not out["wire_bytes_ok"]:
            print(
                f"[bench] LARGE WIRE BYTES: composed arm moved {wb_comp} "
                f"B/round vs the f32 psum's {wb_f32} — the quantized ring "
                f"lost its cut.",
                file=sys.stderr,
            )
    out["config"] = {
        "rows": rows, "features": n_feat, "rounds": rounds,
        "actors": actors, "max_depth": depth, "chunk_rows": chunk_rows,
        "arm_modes": [
            [k, v.get("gh_precision", "float32"),
             v.get("hist_quant", "none")] for k, v in arms.items()
        ],
    }
    print(f"[bench] large measurement: {out}", file=sys.stderr)
    return out


def large_tripwire(current_large, prev_rec, prev_name=None, backend=None,
                   threshold=LARGE_TRIPWIRE_RATIO):
    """Compare this run's composed-arm steady per-round time against the
    newest recorded bench's ``large`` section. Same shape as the other
    tripwires: None when no comparable record exists (records predating
    --large simply lack the section), like-for-like config only."""
    if not isinstance(current_large, dict):
        return None
    cur = (current_large.get("composed") or {}).get("steady_per_round_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_sec = prev_rec.get("large")
    if not isinstance(prev_sec, dict):
        return None
    prev = (prev_sec.get("composed") or {}).get("steady_per_round_s")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_per_round_s": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_sec.get("config") != current_large.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] LARGE TRIPWIRE: composed-arm steady per-round time "
            f"{cur:.4f}s is {ratio:.2f}x the newest recorded run "
            f"({prev:.4f}s in {prev_name or 'BENCH_*.json'}) — "
            f">{(threshold - 1) * 100:.0f}% regression at the headline "
            f"scale; investigate before trusting this build's large-run "
            f"numbers.",
            file=sys.stderr,
        )
    return out


def wide_feature_round_time_tripwire(current_wide, prev_rec, prev_name=None,
                                     backend=None,
                                     threshold=WIDE_FEATURE_TRIPWIRE_RATIO):
    """Compare this run's (4,2) 2D-mesh arm steady per-round time against
    the newest recorded bench's ``wide_feature`` section.

    The feature-parallel analog of ``sampling_round_time_tripwire``:
    returns ``{prev_per_round_s, prev_record, ratio, fired}`` or None when
    no comparable record exists. Like-for-like only (config key)."""
    if not isinstance(current_wide, dict):
        return None
    cur = (current_wide.get("2d") or {}).get("per_round_s")
    if not cur or not isinstance(prev_rec, dict):
        return None
    if backend and prev_rec.get("backend") and prev_rec["backend"] != backend:
        return None
    prev_wide = prev_rec.get("wide_feature")
    if not isinstance(prev_wide, dict):
        return None
    prev = (prev_wide.get("2d") or {}).get("per_round_s")
    if not prev:
        return None
    ratio = float(cur) / float(prev)
    out = {
        "prev_per_round_s": round(float(prev), 4),
        "prev_record": prev_name,
        "ratio": round(ratio, 3),
        "fired": False,
    }
    if prev_wide.get("config") != current_wide.get("config"):
        out["config_mismatch"] = True
        return out
    if ratio > threshold:
        out["fired"] = True
        print(
            f"[bench] WIDE-FEATURE TRIPWIRE: 2D-mesh per-round time "
            f"{cur:.4f}s is {ratio:.2f}x the newest recorded run "
            f"({prev:.4f}s in {prev_name or 'BENCH_*.json'}) — "
            f">{(threshold - 1) * 100:.0f}% regression. The feature-"
            f"parallel win is eroding; investigate before trusting this "
            f"build on wide data.",
            file=sys.stderr,
        )
    return out


def run_wide_feature_ablation(actors=8):
    """Synthetic wide-feature (ads/CTR-shaped) 1D-vs-2D mesh ablation.

    Requires an even ``actors >= 4`` (returns None otherwise): the 2D arm
    runs on ``(actors // 2, 2)``, and with fewer/odd actors the comparison
    degenerates — a (1, 2) mesh has NO actors-axis histogram traffic (ring
    terms are zero on one actor) so the byte-cut gate would pass
    vacuously, and odd counts would compare meshes of different total
    device counts.

    F=2048 sparse-ish columns, the regime ROADMAP item 2 targets: on the
    8-device mesh the same data/params train as (8, 1) pure row sharding
    and as the (4, 2) row x feature mesh (``feature_parallel=2``). Each arm
    records true per-chunk wall times, the steady per-round figure, the
    measured per-chip AllreduceBytes (ring model, from the compiled
    program), and the final train logloss. The section asserts the two
    contracts the 2D mesh ships under: per-round collective bytes cut
    >= WIDE_FEATURE_BYTE_CUT_MIN (the F/C histogram payload win must beat
    the election/broadcast overhead it buys), and logloss parity <= 1e-5
    (feature sharding must not change the model beyond reduction-order
    noise)."""
    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    if actors < 4 or actors % 2:
        print(
            f"[bench] wide-feature ablation skipped: needs an even "
            f"actors >= 4 for a like-for-like (R,1)-vs-(R/2,2) pairing "
            f"(got {actors}).",
            file=sys.stderr,
        )
        return None
    chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
    abl_rounds = int(os.environ.get("BENCH_WIDE_ROUNDS", 2 * chunk))
    n_rows = int(os.environ.get("BENCH_WIDE_ROWS", 4096))
    n_feat = int(os.environ.get("BENCH_WIDE_FEATURES", 2048))
    depth = int(os.environ.get("BENCH_WIDE_DEPTH", 4))
    max_bin = int(os.environ.get("BENCH_WIDE_MAX_BIN", 32))

    rng = np.random.RandomState(11)
    # CTR-shaped: mostly-zero wide columns, a sparse true weight vector
    x = (rng.rand(n_rows, n_feat) < 0.1).astype(np.float32)
    x *= rng.rand(n_rows, n_feat).astype(np.float32)
    w_true = rng.randn(n_feat).astype(np.float32) * (rng.rand(n_feat) < 0.05)
    y = ((x @ w_true + 0.2 * rng.randn(n_rows)) > 0).astype(np.float32)

    base = {
        "objective": "binary:logistic",
        "max_depth": depth,
        "max_bin": max_bin,
        "eta": 0.1,
        "tree_method": "tpu_hist",
    }
    arms = {
        "1d": (dict(base), actors),                          # (8, 1)
        "2d": ({**base, "feature_parallel": 2}, actors // 2),  # (4, 2)
    }

    def binary_logloss(margin):
        p = 1.0 / (1.0 + np.exp(-np.asarray(margin, np.float64).ravel()))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    out = {"rounds": abl_rounds}
    ll_exact = {}  # unrounded per-arm loglosses: the parity gate's inputs
    for name, (p, arm_actors) in arms.items():
        res = {}
        t0 = time.time()
        bst = train(
            p,
            RayDMatrix(x, y),
            num_boost_round=abl_rounds,
            additional_results=res,
            ray_params=RayParams(
                num_actors=arm_actors, checkpoint_frequency=0
            ),
        )
        arm_time = time.time() - t0
        per_round = _steady_per_round(
            res.get("round_times_s"), chunk, arm_time, abl_rounds
        )
        ll_exact[name] = binary_logloss(bst.predict(x, output_margin=True))
        arm = {
            "mesh": [arm_actors, p.get("feature_parallel", 1)],
            "per_round_s": round(per_round, 4),
            "train_time_s": round(arm_time, 2),
            # true per-dispatch wall times, NOT the replicated chunk mean
            "chunk_times_s": res.get("chunk_times_s"),
            "final_logloss": round(ll_exact[name], 6),
        }
        ar_bytes = res.get("hist_allreduce_bytes_per_round")
        if ar_bytes is not None:
            arm["allreduce_bytes_per_round"] = int(ar_bytes)
        out[name] = arm
    b1 = out["1d"].get("allreduce_bytes_per_round")
    b2 = out["2d"].get("allreduce_bytes_per_round")
    if b1 and b2:
        # the gate reads the UNROUNDED ratio; the stored value is display
        out["allreduce_bytes_cut"] = round(b1 / b2, 2)
        out["byte_cut_ok"] = (b1 / b2) >= WIDE_FEATURE_BYTE_CUT_MIN
        if not out["byte_cut_ok"]:
            print(
                f"[bench] WIDE-FEATURE BYTE CUT below floor: (4,2) moves "
                f"only {out['allreduce_bytes_cut']}x fewer bytes than "
                f"(8,1) (floor {WIDE_FEATURE_BYTE_CUT_MIN}x).",
                file=sys.stderr,
            )
    if out["1d"]["per_round_s"]:
        out["2d_per_round_vs_1d"] = round(
            out["2d"]["per_round_s"] / out["1d"]["per_round_s"], 3
        )
    # parity judged on the UNROUNDED per-arm loglosses (rounding the arms
    # first would let a ~1.05e-5 miss slip under the 1e-5 gate); the stored
    # delta is rounded for display only
    ll_delta = ll_exact["2d"] - ll_exact["1d"]
    out["logloss_delta"] = round(ll_delta, 6)
    out["logloss_parity_ok"] = abs(ll_delta) <= 1e-5
    if not out["logloss_parity_ok"]:
        print(
            f"[bench] WIDE-FEATURE LOGLOSS PARITY broken: (4,2) final "
            f"logloss differs from (8,1) by {out['logloss_delta']} "
            f"(> 1e-5).",
            file=sys.stderr,
        )
    out["config"] = {
        "rows": n_rows, "features": n_feat, "rounds": abl_rounds,
        "max_depth": depth, "max_bin": max_bin, "actors": actors,
        "mesh_1d": out["1d"]["mesh"], "mesh_2d": out["2d"]["mesh"],
    }
    print(f"[bench] wide-feature ablation: {out}", file=sys.stderr)
    return out


def hpo_cost_ratio_tripwire(current_hpo, prev_rec=None, prev_name=None,
                            backend=None, gate=HPO_COST_RATIO_GATE,
                            threshold=HPO_TRIPWIRE_RATIO):
    """Check the vmapped-K-vs-sequential HPO pairing against its gate.

    Like ``obs_overhead_tripwire``, the tracked figure (``cost_ratio`` =
    vmapped-K=4 total wall over 4 sequential trials) is a within-run
    pairing, so the tripwire fires on the CURRENT run's own gate violation
    (cost_ratio >= HPO_COST_RATIO_GATE) — no prior snapshot needed. When
    the newest recorded bench carries a comparable ``hpo`` section (same
    backend, same config), the >20% cross-snapshot drift check applies on
    top. Returns ``{cost_ratio, gate, fired, ...}`` or ``None`` when the
    current section has no ratio (an arm failed to measure)."""
    if not isinstance(current_hpo, dict):
        return None
    cur = current_hpo.get("cost_ratio")
    if not cur:
        return None
    out = {
        "cost_ratio": round(float(cur), 4),
        "gate": gate,
        "fired": False,
    }
    prev_hpo = prev_rec.get("hpo") if isinstance(prev_rec, dict) else None
    if isinstance(prev_hpo, dict) and prev_hpo.get("cost_ratio"):
        if backend and prev_rec.get("backend") \
                and prev_rec["backend"] != backend:
            prev_hpo = None
        elif prev_hpo.get("config") != current_hpo.get("config"):
            out["config_mismatch"] = True
            prev_hpo = None
    if isinstance(prev_hpo, dict) and prev_hpo.get("cost_ratio"):
        out["prev_cost_ratio"] = round(float(prev_hpo["cost_ratio"]), 4)
        out["prev_record"] = prev_name
        ratio = float(cur) / float(prev_hpo["cost_ratio"])
        out["ratio"] = round(ratio, 3)
        if ratio > threshold:
            out["fired"] = True
            print(
                f"[bench] HPO TRIPWIRE: vmapped-K cost ratio {cur:.3f} is "
                f"{ratio:.2f}x the newest recorded run "
                f"({float(prev_hpo['cost_ratio']):.3f} in "
                f"{prev_name or 'BENCH_*.json'}) — "
                f">{(threshold - 1) * 100:.0f}% regression of the packed-"
                f"program win.",
                file=sys.stderr,
            )
    if float(cur) >= gate:
        out["fired"] = True
        print(
            f"[bench] HPO GATE: vmapped-K=4 total wall is {float(cur):.3f}x "
            f"the 4 sequential trials — over the {gate}x gate. The packed "
            f"program is no longer amortizing compile/dispatch across "
            f"lanes; investigate before trusting vectorized sweeps.",
            file=sys.stderr,
        )
    return out


def run_hpo_ablation(x, y, base_params, actors):
    """Paired HPO measurement: 4 sequential trials vs one vmapped-K=4 run.

    Both arms train the SAME four candidate configs (the protocol params
    with eta swept over 4 values) on the same data. The sequential arm is
    the status-quo sweep — one engine per trial, each paying its own
    compile and dispatching its own per-round program. The vmapped arm
    packs all four candidates as lanes of ONE ``engine.step_vmapped``
    program (``enable_lanes`` on a ``vectorize_params`` pack): one compile,
    one dispatch per round, collectives per-lane-batched. Headline figures:
    trials-per-hour for each arm and ``cost_ratio`` (vmapped wall over
    sequential wall), gated at HPO_COST_RATIO_GATE. The section also
    asserts lane parity: each lane's final train logloss must match its
    sequential twin to 1e-5 (same data, same per-lane params, masks not
    engaged — the lanes ARE the sequential runs, batched)."""
    from xgboost_ray_tpu.engine import TpuEngine
    from xgboost_ray_tpu.params import parse_params, vectorize_params

    k = 4
    rounds = int(os.environ.get("BENCH_HPO_ROUNDS", "8"))
    rows = min(int(x.shape[0]), int(os.environ.get("BENCH_HPO_ROWS", "50000")))
    hx, hy = x[:rows], y[:rows]
    shards = [{"data": hx, "label": hy}]
    evals = [(shards, "train")]
    etas = (0.3, 0.2, 0.1, 0.05)
    configs = []
    for eta in etas:
        cfg = dict(base_params)
        cfg["learning_rate"] = eta
        cfg.pop("eta", None)
        configs.append(cfg)

    def _final_logloss(res):
        return float(res["train"]["logloss"])

    seq_start = time.time()
    seq_ll = []
    for cfg in configs:
        eng = TpuEngine(shards, parse_params(cfg), num_actors=actors,
                        evals=evals)
        for it in range(rounds):
            res = eng.step(it)
        seq_ll.append(_final_logloss(res))
        del eng
    seq_time = time.time() - seq_start

    vm_start = time.time()
    lp = vectorize_params(configs)
    veng = TpuEngine(shards, lp.base, num_actors=actors, evals=evals)
    veng.enable_lanes(lp)
    for it in range(rounds):
        vres = veng.step_vmapped(it)
    vm_ll = [_final_logloss(r) for r in vres]
    vm_time = time.time() - vm_start

    ll_delta = max(abs(a - b) for a, b in zip(seq_ll, vm_ll))
    cost_ratio = vm_time / seq_time if seq_time else None
    out = {
        "k": k,
        "rounds": rounds,
        "sequential": {
            "total_s": round(seq_time, 2),
            "trials_per_hour": round(k / (seq_time / 3600.0), 1),
            "compiles": k,
        },
        "vmapped": {
            "total_s": round(vm_time, 2),
            "trials_per_hour": round(k / (vm_time / 3600.0), 1),
            "compiles": 1,
        },
        "cost_ratio": round(cost_ratio, 4) if cost_ratio else None,
        "gate": HPO_COST_RATIO_GATE,
        "gate_ok": bool(cost_ratio is not None
                        and cost_ratio < HPO_COST_RATIO_GATE),
        # parity judged on the unrounded values (see wide-feature ablation)
        "logloss_max_delta": round(ll_delta, 7),
        "logloss_parity_ok": ll_delta <= 1e-5,
        "config": {
            "rows": rows, "features": int(x.shape[1]), "rounds": rounds,
            "actors": actors, "k": k, "etas": list(etas),
            "max_depth": int(base_params.get("max_depth", 6)),
        },
    }
    if not out["logloss_parity_ok"]:
        print(
            f"[bench] HPO LANE PARITY broken: max per-lane final-logloss "
            f"delta vmapped-vs-sequential is {out['logloss_max_delta']} "
            f"(> 1e-5).",
            file=sys.stderr,
        )
    print(f"[bench] hpo ablation: {out}", file=sys.stderr)
    return out


def r4_paired_recheck(detail):
    """Close the r4->r5 "52% CPU-bench regression" open item with DATA.

    The recorded BENCH_r04 -> BENCH_r05 delta (0.76 -> 1.44 s/round, 1.89x)
    came from captures on different machines/load; the r6 bisect re-ran
    both snapshots on one machine and saw parity (see the REGRESSION NOTE
    above). This section adds the in-process control: the hist_quant
    ablation's "none" arm and the sampling ablation's "full" arm are the
    SAME protocol config measured minutes apart in the SAME process — their
    pair ratio bounds same-environment run-to-run variance. A recorded
    1.89x delta far outside that band is environmental capture noise, not
    code; the verdict lands in the BENCH snapshot for the open item."""
    quant = detail.get("hist_quant_ablation") or {}
    samp = detail.get("sampling") or {}
    a = (quant.get("none") or {}).get("per_round_s")
    b = (samp.get("full") or {}).get("per_round_s")
    if not a or not b:
        return None
    pair_ratio = max(a, b) / min(a, b)
    recorded = 1.89  # BENCH_r04 0.7628 -> BENCH_r05 1.4421 s/round
    out = {
        "pair_a_per_round_s": round(float(a), 4),
        "pair_b_per_round_s": round(float(b), 4),
        "pair_ratio": round(pair_ratio, 3),
        "recorded_r4_r5_ratio": recorded,
        "verdict": (
            "environmental"
            if recorded > pair_ratio * TRIPWIRE_RATIO
            else "inconclusive"
        ),
        "note": (
            "pair = same protocol config re-measured minutes apart in one "
            "process (quant-ablation none arm vs sampling-ablation full "
            "arm); recorded r4->r5 delta far outside the pair band => "
            "capture-environment noise, closing VERDICT r5 open item"
        ),
    }
    print(f"[bench] r4 regression recheck: {out}", file=sys.stderr)
    return out


def run_phase_breakdown():
    """Per-phase round-cost breakdown (sample / hist / split / partition /
    margin / allreduce) for the full, subsample=0.5, and GOSS configs —
    consumed from the RUNTIME trace.

    Each arm trains a short run with fenced phase profiling enabled
    (``RXGB_TRACE_PHASES=1``); the engine itself emits the per-phase spans
    at its true per-shard shapes (compile vs execute separated via
    ``block_until_ready``, sibling-subtraction fan-outs, the engine's real
    sampling budget and split params), and the table below is read back
    from ``additional_results["obs"]["phase_profile"]``. This replaced the
    bench's former standalone duplicate timers: the numbers now come from
    the same instrumentation any traced production run produces."""
    import jax

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    n_rows = int(os.environ.get("BENCH_PHASE_ROWS", 25_000))
    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    actors = int(
        os.environ.get("BENCH_PHASE_ACTORS", max(1, len(jax.devices())))
    )
    rounds = 2
    x, y = make_higgs_like(n_rows, n_feat, seed=3)
    arms = {
        "full": {},
        "subsample": {"subsample": 0.5},
        "goss": {"sampling_method": "gradient_based", "top_rate": 0.1,
                 "other_rate": 0.1},
    }
    section = {}
    saved = os.environ.get("RXGB_TRACE_PHASES")
    os.environ["RXGB_TRACE_PHASES"] = "1"
    try:
        for name, extra in arms.items():
            params = {
                "objective": "binary:logistic", "max_depth": depth,
                "eta": 0.1, "max_bin": 256, "tree_method": "tpu_hist",
            }
            params.update(extra)
            res = {}
            train(
                params, RayDMatrix(x, y), num_boost_round=rounds,
                additional_results=res,
                ray_params=RayParams(num_actors=actors,
                                     checkpoint_frequency=0),
            )
            prof = (res.get("obs") or {}).get("phase_profile")
            if not prof:
                print(
                    f"[bench] phase breakdown: no phase profile in the "
                    f"trace for arm {name!r}; skipping",
                    file=sys.stderr,
                )
                continue
            phases = prof["phases"]
            section[name] = {
                "rows_per_level": prof["sample_rows"],
                "sample_ms": phases["sample"]["execute_ms"],
                "hist_ms": phases["hist"]["execute_ms"],
                "split_ms": phases["split"]["execute_ms"],
                "partition_ms": phases["partition"]["execute_ms"],
                "margin_ms": phases["margin"]["execute_ms"],
                "allreduce_ms": phases["allreduce"]["execute_ms"],
                "allreduce_bytes_per_round": phases["allreduce"][
                    "bytes_per_round"
                ],
                "compile_ms": round(
                    sum(p.get("compile_ms", 0.0) for p in phases.values()), 3
                ),
                "total_ms": prof["total_execute_ms"],
                "rows_per_shard": prof["rows_per_shard"],
            }
    finally:
        if saved is None:
            os.environ.pop("RXGB_TRACE_PHASES", None)
        else:
            os.environ["RXGB_TRACE_PHASES"] = saved
    if section.get("full", {}).get("total_ms"):
        for arm in ("subsample", "goss"):
            if section.get(arm):
                section[f"{arm}_total_vs_full"] = round(
                    section[arm]["total_ms"] / section["full"]["total_ms"], 3
                )
    section["config"] = {
        "rows": n_rows, "features": n_feat, "depth": depth,
        "max_bin": 256, "actors": actors,
        "source": "runtime trace (engine.profile_phases spans)",
        "note": "fenced standalone phase programs at the engine's real "
                "shard shapes; phase-share approximation — the compiled "
                "round fuses phases",
    }
    print(f"[bench] phase breakdown: {section}", file=sys.stderr)
    return section


def _timeline_recovery_s(timeline):
    """Failure→recovery seconds reconstructed from a run's trace timeline
    (``obs.recovery_time_s``), or None when the run produced no timeline
    (tracing disabled) so callers can fall back to the robustness dict."""
    if not timeline:
        return None
    from xgboost_ray_tpu import obs

    return round(obs.recovery_time_s(timeline), 4)


def _timeline_fault_events(timeline):
    """The chaos story as the timeline tells it: the ordered
    ``fault.injected`` / ``failure.detected`` / ``world.shrink`` /
    ``world.grow`` / ``world.restart`` / ``recovered`` events with their
    round indices — the machine-readable sequence the BENCH snapshot
    records instead of a prose description of what the soak did."""
    names = {
        "fault.injected", "failure.detected", "world.shrink", "world.grow",
        "world.restart", "recovered", "backoff", "world.domain_down",
        "world.domain_up", "world.deaths_coalesced",
    }
    out = []
    for rec in timeline or []:
        if rec.get("kind") != "event" or rec.get("name") not in names:
            continue
        row = {"event": rec["name"]}
        if "round" in rec:
            row["round"] = rec["round"]
        attrs = rec.get("attrs") or {}
        for k in ("world", "ranks", "site", "action", "orphaned_rows",
                  "domain", "extra"):
            if k in attrs:
                row[k] = attrs[k]
        out.append(row)
    return out


@contextlib.contextmanager
def _immediate_reintegration_env():
    """Zero the elastic scheduler's resource-check/grace knobs for the
    scope (the immediate-reintegration posture every continue arm runs
    under), restoring the ambient values after — shared by the base
    restart-vs-continue pairing and the per-config arms so the two cannot
    drift on which knobs define 'continue'."""
    saved = {}
    for k in ("RXGB_ELASTIC_RESTART_RESOURCE_CHECK_S",
              "RXGB_ELASTIC_RESTART_GRACE_PERIOD_S"):
        saved[k] = os.environ.get(k)
        os.environ[k] = "0"
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _continue_vs_restart_block(restart_ttr, cont_ttr, label):
    """The tripwire-tracked pairing dict (or None when either recovery is
    unmeasured), with the shared not-faster warning — ONE definition of
    the ratio semantics for the base pairing and every per-config arm."""
    if not restart_ttr or not cont_ttr:
        return None
    ratio = round(cont_ttr / restart_ttr, 4)
    if ratio >= 1.0:
        print(
            f"[bench] WARNING: {label} elastic continuation recovered in "
            f"{cont_ttr:.2f}s, NOT faster than the restart-from-checkpoint "
            f"policy ({restart_ttr:.2f}s) — the zero-replay path has lost "
            f"its edge.",
            file=sys.stderr,
        )
    return {
        "restart_time_to_recover_s": restart_ttr,
        "continue_time_to_recover_s": cont_ttr,
        "ratio": ratio,
        "continue_faster": ratio < 1.0,
    }


def run_chaos_measurement():
    """Deterministic chaos soak on the ambient mesh: one training run with a
    mid-run rank kill plus a straggler delay (driven by a ``FaultPlan``, no
    sleep-and-kill races), checked bit-identical against the uninterrupted
    run; then a corrupt-newest-checkpoint resume through the retention
    fallback. Returns the ``chaos`` section: time-to-recover, rounds
    replayed, restart count, and the two identity verdicts."""
    import tempfile

    import jax

    from xgboost_ray_tpu import RayDMatrix, RayParams, faults, train
    from xgboost_ray_tpu.launcher import (
        load_round_checkpoint,
        save_round_checkpoint,
    )

    n_rows = int(os.environ.get("BENCH_CHAOS_ROWS", 20_000))
    rounds = int(os.environ.get("BENCH_CHAOS_ROUNDS", 12))
    actors = int(os.environ.get("BENCH_CHAOS_ACTORS",
                                max(1, len(jax.devices()))))
    straggle_s = float(os.environ.get("BENCH_CHAOS_STRAGGLE_S", 0.25))
    # kill on an ODD round: with checkpoint_frequency=2 the newest
    # checkpoint then trails the kill by one round, so the soak measurably
    # replays work (rounds_replayed >= 1) instead of resuming for free
    kill_round = max(1, rounds // 3) | 1
    straggle_round = max(kill_round + 1, (2 * rounds) // 3)
    # short, bounded backoff: the soak measures recovery, not the storm guard
    os.environ.setdefault("RXGB_RESTART_BACKOFF_BASE_S", "0.05")

    x, y = make_higgs_like(n_rows, 28, seed=2)
    params = {
        "objective": "binary:logistic", "eval_metric": ["logloss"],
        "max_depth": 6, "eta": 0.1, "max_bin": 256,
        "tree_method": "tpu_hist",
    }
    print(
        f"[bench] chaos soak: rows={n_rows} rounds={rounds} actors={actors} "
        f"kill@r{kill_round} straggle@r{straggle_round} (+{straggle_s}s)",
        file=sys.stderr,
    )

    # uninterrupted reference — run it under a never-firing plan targeting
    # the same site so BOTH runs take the per-round path (bit-identity must
    # not compare a fused-scan forest against a per-round one)
    noop_plan = faults.FaultPlan(rules=[{
        "site": "actor.train_round", "action": "raise",
        "match": {"round": -1},
    }])
    with faults.active_plan(noop_plan):
        ref = train(
            params, RayDMatrix(x, y), rounds,
            ray_params=RayParams(num_actors=actors, checkpoint_frequency=2),
        )
    ref_margin = ref.predict(x, output_margin=True)

    plan = faults.FaultPlan(rules=[
        {"site": "actor.train_round", "action": "raise",
         "match": {"round": kill_round}, "ranks": [actors - 1],
         "message": "chaos: scheduled rank kill"},
        {"site": "actor.train_round", "action": "delay",
         "match": {"round": straggle_round}, "delay_s": straggle_s},
    ])
    res = {}
    soak_started = time.time()
    with faults.active_plan(plan):
        bst = train(
            params, RayDMatrix(x, y), rounds,
            additional_results=res,
            ray_params=RayParams(num_actors=actors, checkpoint_frequency=2,
                                 max_actor_restarts=2),
        )
    soak_s = time.time() - soak_started
    rob = res.get("robustness", {})
    # recovery numbers come from the RUN TIMELINE, not the robustness dict:
    # each "recovered" event closes the clock its "failure.detected" opened
    # (obs.recovery_time_s mirrors the driver's accounting — the dict value
    # is kept alongside as a cross-check; the two must agree)
    soak_timeline = (res.get("obs") or {}).get("timeline") or []
    ttr_timeline = _timeline_recovery_s(soak_timeline)
    # the restart recomputes resume margins from the checkpoint forest — a
    # different f32 summation order than the uninterrupted run's incremental
    # accumulation — so the match is pinned at atol=1e-5 (NOT bitwise), with
    # the observed max divergence reported alongside (structural drift shows
    # up as >> 1e-5). Chaos-vs-chaos reruns of the same plan ARE bitwise
    # identical (pinned by tests/test_faults.py).
    chaos_margin = bst.predict(x, output_margin=True)
    model_max_abs_diff = float(np.max(np.abs(chaos_margin - ref_margin)))
    model_matches = bool(np.allclose(chaos_margin, ref_margin, atol=1e-5))

    # corrupt-newest-checkpoint resume: bank two retained checkpoints from
    # the reference forest, corrupt the newest via the checkpoint.save fault
    # site, and resume through the retention fallback to the full model
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt.json")
        k = rounds - 2
        corrupt_plan = faults.FaultPlan(rules=[{
            "site": "checkpoint.save", "action": "corrupt", "at": 2,
            "nbytes": 64,
        }], seed=13)
        with faults.active_plan(corrupt_plan):
            save_round_checkpoint(ref.slice_rounds(0, k - 1), ckpt, k - 2)
            save_round_checkpoint(ref.slice_rounds(0, k), ckpt, k - 1)
        fb, fb_rounds = load_round_checkpoint(ckpt)
        resume_matches = False
        if fb is not None:
            noop_plan.reset()
            with faults.active_plan(noop_plan):  # per-round path, as above
                resumed = train(
                    params, RayDMatrix(x, y), rounds - fb_rounds,
                    xgb_model=fb,
                    ray_params=RayParams(num_actors=actors,
                                         checkpoint_frequency=0),
                )
            # the on-disk JSON roundtrip is not bit-exact (float reprs), so
            # the file-resume check uses the same tolerance as the
            # launcher resume test (1e-4, vs the soak's in-memory 1e-5)
            resume_matches = bool(np.allclose(
                resumed.predict(x, output_margin=True), ref_margin,
                atol=1e-4,
            ))

    section = {
        "restarts": rob.get("restarts", 0),
        "rounds_replayed": rob.get("rounds_replayed", 0),
        "time_to_recover_s": (
            ttr_timeline if ttr_timeline is not None
            else rob.get("time_to_recover_s", 0.0)
        ),
        "recovery_source": (
            "timeline" if ttr_timeline is not None else "robustness_dict"
        ),
        "time_to_recover_robustness_s": rob.get("time_to_recover_s", 0.0),
        "fault_events": _timeline_fault_events(soak_timeline),
        "backoff_s": rob.get("backoff_s", 0.0),
        "soak_train_time_s": round(soak_s, 2),
        "model_matches": model_matches,  # vs uninterrupted, atol=1e-5
        "model_max_abs_diff": model_max_abs_diff,
        "ckpt_fallback_rounds": fb_rounds,
        "ckpt_resume_matches": resume_matches,  # vs uninterrupted, atol=1e-4
        "config": {
            "rows": n_rows, "rounds": rounds, "actors": actors,
            "kill_round": kill_round, "straggle_round": straggle_round,
            "straggle_s": straggle_s, "max_depth": 6,
        },
    }

    # paired restart-vs-continue: the SAME kill schedule once more, now with
    # elastic in-flight continuation (immediate reintegration: resource
    # check + grace period zeroed) — recovery must be strictly faster than
    # the restart-from-checkpoint policy measured above, with ZERO rounds
    # replayed; the final model stays within the soak tolerance of the
    # uninterrupted run (the kill fires before the round's step, so no
    # survivor-world round is ever boosted).
    if actors >= 2:
        cont_plan = faults.FaultPlan(rules=[
            {"site": "actor.train_round", "action": "raise",
             "match": {"round": kill_round}, "ranks": [actors - 1],
             "message": "chaos: scheduled rank kill"},
            {"site": "actor.train_round", "action": "delay",
             "match": {"round": straggle_round}, "delay_s": straggle_s},
        ])
        res_cont = {}
        with _immediate_reintegration_env():
            with faults.active_plan(cont_plan):
                bst_cont = train(
                    params, RayDMatrix(x, y), rounds,
                    additional_results=res_cont,
                    ray_params=RayParams(
                        num_actors=actors, checkpoint_frequency=2,
                        elastic_training=True,
                        max_failed_actors=actors - 1,
                        max_actor_restarts=2,
                    ),
                )
        rob_c = res_cont.get("robustness", {})
        cont_timeline = (res_cont.get("obs") or {}).get("timeline") or []
        cont_ttr_timeline = _timeline_recovery_s(cont_timeline)
        cont_ttr = (
            cont_ttr_timeline if cont_ttr_timeline is not None
            else rob_c.get("time_to_recover_s", 0.0)
        )
        restart_ttr = section["time_to_recover_s"]
        cont_matches = bool(np.allclose(
            bst_cont.predict(x, output_margin=True), ref_margin, atol=1e-5
        ))
        section["elastic"] = {
            "time_to_recover_s": cont_ttr,
            "recovery_source": (
                "timeline" if cont_ttr_timeline is not None
                else "robustness_dict"
            ),
            "time_to_recover_robustness_s": rob_c.get(
                "time_to_recover_s", 0.0
            ),
            "rounds_replayed": rob_c.get("rounds_replayed", 0),
            "restarts": rob_c.get("restarts", 0),
            "shrinks": rob_c.get("shrinks", 0),
            "grows": rob_c.get("grows", 0),
            "orphaned_rows": rob_c.get("orphaned_rows", 0),
            "recompile_s": rob_c.get("recompile_s", 0.0),
            "model_matches": cont_matches,  # vs uninterrupted, atol=1e-5
            # the kill→shrink→grow (or immediate-reintegration) sequence as
            # the timeline recorded it, round indices included
            "fault_events": _timeline_fault_events(cont_timeline),
        }
        cvr = _continue_vs_restart_block(restart_ttr, cont_ttr, "base")
        if cvr is not None:
            section["continue_vs_restart"] = cvr
    # per-config pairings: the SAME restart-vs-continue experiment over the
    # configurations that used to be fallback cases — the 2D row x feature
    # mesh and the streamed (out-of-core) matrix. Each arm runs its own
    # uninterrupted reference, a kill under the restart-from-checkpoint
    # policy, and the same kill under elastic in-flight continuation; the
    # continue_vs_restart ratios feed elastic_recovery_tripwire alongside
    # the base pairing.
    if actors >= 2:
        arm_rows = int(os.environ.get("BENCH_CHAOS_ARM_ROWS",
                                      min(n_rows, 8_000)))
        arm_rounds = int(os.environ.get("BENCH_CHAOS_ARM_ROUNDS", rounds))
        arm_kill = max(1, arm_rounds // 3) | 1
        ax, ay = make_higgs_like(arm_rows, 28, seed=3)
        actors_2d = max(2, actors // 2)
        if actors_2d * 2 <= len(jax.devices()):
            section["elastic_2d"] = _paired_continue_vs_restart(
                label="2d",
                params={**params, "feature_parallel": 2},
                make_dmatrix=lambda: RayDMatrix(ax, ay),
                x=ax,
                rounds=arm_rounds, actors=actors_2d, kill_round=arm_kill,
                config={"rows": arm_rows, "rounds": arm_rounds,
                        "actors": actors_2d, "feature_parallel": 2,
                        "kill_round": arm_kill, "max_depth": 6},
            )
        chunk_rows = max(256, arm_rows // 8)
        section["elastic_streamed"] = _paired_continue_vs_restart(
            label="streamed",
            params=params,
            make_dmatrix=lambda: RayDMatrix(
                ax, ay, stream=True, chunk_rows=chunk_rows
            ),
            x=ax,
            rounds=arm_rounds, actors=actors, kill_round=arm_kill,
            config={"rows": arm_rows, "rounds": arm_rounds,
                    "actors": actors, "streamed": True,
                    "chunk_rows": chunk_rows, "kill_round": arm_kill,
                    "max_depth": 6},
        )
        # correlated host loss: a whole fault domain (2 of 4 ranks under
        # RXGB_FAULT_DOMAINS=2) dies at once — the continue arm must fold
        # both deaths into ONE shrink (or one immediate reintegration),
        # never two sequential recompile cycles
        actors_dom = 4
        if actors_dom <= len(jax.devices()):
            section["elastic_domain"] = _paired_continue_vs_restart(
                label="domain",
                params=params,
                make_dmatrix=lambda: RayDMatrix(ax, ay),
                x=ax,
                rounds=arm_rounds, actors=actors_dom, kill_round=arm_kill,
                config={"rows": arm_rows, "rounds": arm_rounds,
                        "actors": actors_dom, "fault_domains": 2,
                        "kill_round": arm_kill, "max_depth": 6},
                kill_rule={"site": "actor.train_round",
                           "action": "domain_kill", "domain": 1,
                           "ranks": [actors_dom - 1],
                           "match": {"round": arm_kill},
                           "message": "chaos: correlated domain kill"},
                extra_env={"RXGB_FAULT_DOMAINS": "2"},
            )
    print(f"[bench] chaos section: {section}", file=sys.stderr)
    return section


def _paired_continue_vs_restart(label, params, make_dmatrix, x, rounds,
                                actors, kill_round, config,
                                kill_rule=None, extra_env=None):
    """One restart-vs-continue pairing for a specific training config: the
    same deterministic kill, once under the restart-from-checkpoint policy
    and once under elastic in-flight continuation (immediate
    reintegration). Returns the arm dict with both recoveries, the
    continue arm's zero-replay/identity verdicts, and the
    ``continue_vs_restart`` ratio the elastic tripwire tracks.

    ``kill_rule`` overrides the default single-rank kill (the
    ``elastic_domain`` arm injects a correlated ``domain_kill`` instead);
    ``extra_env`` sets env vars for BOTH chaos runs (e.g.
    ``RXGB_FAULT_DOMAINS``) so the pairing stays like-for-like."""
    from xgboost_ray_tpu import RayParams, faults, train

    @contextlib.contextmanager
    def _arm_env():
        saved = {}
        for k, v in (extra_env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    noop = faults.FaultPlan(rules=[{
        "site": "actor.train_round", "action": "raise",
        "match": {"round": -1},
    }])
    with faults.active_plan(noop):
        ref = train(params, make_dmatrix(), rounds,
                    ray_params=RayParams(num_actors=actors,
                                         checkpoint_frequency=2))
    ref_margin = ref.predict(x, output_margin=True)

    def kill_plan():
        return faults.FaultPlan(rules=[dict(kill_rule) if kill_rule else {
            "site": "actor.train_round", "action": "raise",
            "match": {"round": kill_round}, "ranks": [actors - 1],
            "message": f"chaos: scheduled rank kill ({label})",
        }])

    # restart-from-checkpoint policy
    res_r = {}
    with _arm_env(), faults.active_plan(kill_plan()):
        bst_r = train(params, make_dmatrix(), rounds, additional_results=res_r,
                      ray_params=RayParams(num_actors=actors,
                                           checkpoint_frequency=2,
                                           max_actor_restarts=2))
    rob_r = res_r.get("robustness", {})
    tl_r = (res_r.get("obs") or {}).get("timeline") or []
    restart_ttr = _timeline_recovery_s(tl_r) or rob_r.get(
        "time_to_recover_s", 0.0
    )

    # elastic in-flight continuation, immediate reintegration
    res_c = {}
    with _arm_env(), _immediate_reintegration_env():
        with faults.active_plan(kill_plan()):
            bst_c = train(params, make_dmatrix(), rounds,
                          additional_results=res_c,
                          ray_params=RayParams(num_actors=actors,
                                               checkpoint_frequency=2,
                                               elastic_training=True,
                                               max_failed_actors=actors - 1,
                                               max_actor_restarts=2))
    rob_c = res_c.get("robustness", {})
    tl_c = (res_c.get("obs") or {}).get("timeline") or []
    cont_ttr = _timeline_recovery_s(tl_c) or rob_c.get(
        "time_to_recover_s", 0.0
    )
    arm = {
        "restart": {
            "time_to_recover_s": restart_ttr,
            "restarts": rob_r.get("restarts", 0),
            "rounds_replayed": rob_r.get("rounds_replayed", 0),
            "model_matches": bool(np.allclose(
                bst_r.predict(x, output_margin=True), ref_margin, atol=1e-5
            )),
        },
        "elastic": {
            "time_to_recover_s": cont_ttr,
            "restarts": rob_c.get("restarts", 0),
            "rounds_replayed": rob_c.get("rounds_replayed", 0),
            "shrinks": rob_c.get("shrinks", 0),
            "grows": rob_c.get("grows", 0),
            "domains_lost": rob_c.get("domains_lost", 0),
            "deaths_coalesced": rob_c.get("deaths_coalesced", 0),
            "model_matches": bool(np.allclose(
                bst_c.predict(x, output_margin=True), ref_margin, atol=1e-5
            )),
            "fault_events": _timeline_fault_events(tl_c),
        },
        "config": config,
    }
    cvr = _continue_vs_restart_block(restart_ttr, cont_ttr, label)
    if cvr is not None:
        arm["continue_vs_restart"] = cvr
    print(f"[bench] chaos {label} pairing: {arm}", file=sys.stderr)
    return arm


def _train_serve_model():
    """Train the small served model once; shared by the paired heap and
    node-array serving arms so both serve the IDENTICAL forest."""
    import jax

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    n_rows = int(os.environ.get("BENCH_SERVE_TRAIN_ROWS", 20_000))
    rounds = int(os.environ.get("BENCH_SERVE_TRAIN_ROUNDS", 5))
    n_feat = 28
    x, y = make_higgs_like(n_rows, n_feat, seed=1)
    bst = train(
        {"objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
         "max_bin": 256, "tree_method": "tpu_hist"},
        RayDMatrix(x, y), num_boost_round=rounds,
        ray_params=RayParams(num_actors=max(1, len(jax.devices())),
                             checkpoint_frequency=0),
    )
    return bst, x


def run_serve_measurement(layout="heap", trained=None):
    """Closed-loop serving benchmark: train a small model (or reuse
    ``trained`` — the ``_train_serve_model()`` result — for a paired arm),
    serve it over loopback HTTP on the ambient mesh with the requested
    forest ``layout``, drive it with concurrent clients, and return the
    endpoint's /metrics snapshot (plus the loop config) as the ``serve`` /
    ``serve_node_array`` section of the bench record."""
    import json as json_mod
    import threading
    import urllib.request

    import jax

    from xgboost_ray_tpu import serve as serve_mod

    n_rows = int(os.environ.get("BENCH_SERVE_TRAIN_ROWS", 20_000))
    rounds = int(os.environ.get("BENCH_SERVE_TRAIN_ROUNDS", 5))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 16))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", 256))
    max_delay_ms = float(os.environ.get("BENCH_SERVE_MAX_DELAY_MS", 2.0))
    req_rows_max = int(os.environ.get("BENCH_SERVE_REQ_ROWS", 32))
    duration_s = float(os.environ.get("BENCH_SERVE_SECONDS", 6.0))
    warm_s = float(os.environ.get("BENCH_SERVE_WARM_SECONDS", 1.5))

    if trained is None:
        trained = _train_serve_model()
    bst, x = trained
    handle = serve_mod.create_server(
        bst, devices=jax.devices(), max_batch=max_batch,
        max_delay_ms=max_delay_ms, layout=layout,
    )
    print(f"[bench] serve endpoint up at {handle.url} "
          f"(devices={len(jax.devices())} max_batch={max_batch} "
          f"max_delay_ms={max_delay_ms} clients={clients} "
          f"layout={layout})", file=sys.stderr)

    stop = threading.Event()
    errors = []

    def client(seed):
        rng = np.random.RandomState(seed)
        while not stop.is_set():
            n = int(rng.randint(1, req_rows_max + 1))
            lo = int(rng.randint(0, n_rows - n))
            body = json_mod.dumps(
                {"data": x[lo : lo + n].tolist()}
            ).encode("utf-8")
            req = urllib.request.Request(
                handle.url + "/predict", body,
                {"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30.0) as r:
                    r.read()
            except Exception as exc:  # noqa: BLE001 - counted, loop on
                if not stop.is_set():
                    errors.append(repr(exc))

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(warm_s)  # steady-state only: warmup traffic excluded
        handle.metrics.reset()  # also re-baselines the recompile counter
        del errors[:]  # client_errors must describe the measured window too
        time.sleep(duration_s)
        # recompile_count is since-reset, i.e. inside the measured window
        # (the steady-state claim: this should be 0)
        snap = handle.metrics.snapshot()
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
        handle.shutdown()
    section = {
        k: snap[k]
        for k in (
            "requests", "rows", "errors", "qps", "rows_per_s", "batches",
            "mean_batch_rows", "padding_waste", "latency_p50_ms",
            "latency_p95_ms", "latency_p99_ms", "latency_mean_ms",
            "recompile_count",
        )
    }
    section["client_errors"] = len(errors)
    section["config"] = {
        "clients": clients,
        "max_batch": max_batch,
        "max_delay_ms": max_delay_ms,
        "req_rows_max": req_rows_max,
        "duration_s": duration_s,
        "devices": len(jax.devices()),
        # served-model size changes per-batch predict cost: part of
        # like-for-like, so a different model never compares as "same run"
        "train_rows": n_rows,
        "train_rounds": rounds,
        "max_depth": 6,
        "layout": layout,
    }
    print(f"[bench] serve closed-loop: {section}", file=sys.stderr)
    return section


def make_higgs_like(n_rows: int, n_features: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.standard_normal(size=(n_rows, n_features)).astype(np.float32)
    # learnable structure: a few informative features + mild nonlinearity
    logits = 0.8 * x[:, 0] - 0.6 * x[:, 1] + 0.4 * x[:, 2] * x[:, 3] + 0.3 * x[:, 4]
    y = (logits + rng.standard_normal(n_rows).astype(np.float32) > 0).astype(np.float32)
    return x, y


def _probe_accelerator(timeout_s: float = 180.0, attempts: int = 3,
                       backoff_s: float = 60.0) -> bool:
    """Check in a subprocess that the accelerator backend actually comes up.

    The TPU plugin initializes at backend-init time and can hang indefinitely
    if its tunnel/lease is wedged; probing in a killable child keeps the
    benchmark from hanging. Tunnel hiccups are often transient (a previous
    client's lease must expire), so the probe retries with backoff before
    giving up — round 2's driver capture fell to the CPU mesh on a single
    failed probe while the tunnel recovered minutes later.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return False
    # distinguish "no accelerator plugin registered" (deterministic — skip
    # the backoff) from "plugin present but init failed/hung" (transient —
    # retry); jax silently falls back to cpu in the latter case when
    # JAX_PLATFORMS is unset, so checking default_backend() alone conflates
    # the two. The public default_backend() check runs FIRST so the happy
    # path never depends on the private _backend_factories attr; the private
    # lookup is guarded and an unknown answer is treated as transient.
    code = (
        "import jax\n"
        "if jax.default_backend() != 'cpu':\n"
        "    print('ACCEL_OK')\n"
        "else:\n"
        "    try:\n"
        "        from jax._src import xla_bridge as xb\n"
        "        plats = [p for p in xb._backend_factories if p != 'cpu']\n"
        "    except Exception:\n"
        "        plats = None  # unknown -> assume transient, retry\n"
        "    print('NO_PLUGIN' if plats == [] else 'INIT_FAIL')\n"
    )
    for attempt in range(attempts):
        try:
            res = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=timeout_s,
            )
            if "ACCEL_OK" in res.stdout:
                return True
            if "NO_PLUGIN" in res.stdout:
                print("[bench] no accelerator backend installed", file=sys.stderr)
                return False
            err = (res.stderr or "").strip().splitlines()
            print(
                f"[bench] accelerator probe {attempt + 1}/{attempts} failed"
                + (f": {err[-1][:160]}" if err else ""),
                file=sys.stderr,
            )
        except Exception as exc:
            print(
                f"[bench] accelerator probe {attempt + 1}/{attempts} "
                f"{type(exc).__name__}",
                file=sys.stderr,
            )
        if attempt + 1 < attempts:
            time.sleep(backoff_s)
    return False


def _force_cpu_mesh():
    """Point this process at the 8-device virtual CPU mesh, severing any
    path to the (possibly wedged) accelerator plugin."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax as _jax
    from jax._src import xla_bridge as _xb

    _jax.config.update("jax_platforms", "cpu")
    for _name in list(_xb._backend_factories):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)


def run_measurement():
    """Child-process entry: run the protocol once and print the JSON line."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        _force_cpu_mesh()
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    if os.environ.get("BENCH_EXPECT_TPU") == "1" and not on_tpu:
        # the parent probed an accelerator but this child came up on cpu
        # (plugin init failed after the probe): abort WITHOUT a result line
        # so the parent's re-probe/retry logic runs, instead of emitting a
        # plausible-looking extrapolated metric
        print("[bench] expected an accelerator but backend resolved to cpu; "
              "aborting this attempt", file=sys.stderr)
        sys.exit(3)

    n_rows = int(os.environ.get("BENCH_ROWS", 11_000_000 if on_tpu else 200_000))
    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100 if on_tpu else 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    actors = int(os.environ.get("BENCH_ACTORS", max(1, len(jax.devices()))))
    hist_impl = os.environ.get("BENCH_HIST_IMPL", "auto")
    hist_quant = os.environ.get("BENCH_HIST_QUANT", "none")

    print(
        f"[bench] backend={backend} rows={n_rows} features={n_feat} "
        f"rounds={rounds} depth={depth} actors={actors} hist_impl={hist_impl} "
        f"hist_quant={hist_quant} "
        f"scan_chunk={os.environ.get('RXGB_SCAN_MAX_CHUNK', 'default')}",
        file=sys.stderr,
    )

    t0 = time.time()
    x, y = make_higgs_like(n_rows, n_feat)
    print(f"[bench] data generated in {time.time() - t0:.1f}s", file=sys.stderr)

    from xgboost_ray_tpu import RayDMatrix, RayParams, train

    dtrain = RayDMatrix(x, y)
    params = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss"],
        "max_depth": depth,
        "eta": 0.1,
        "max_bin": 256,
        "tree_method": "tpu_hist",
        "hist_impl": hist_impl,
        "hist_quant": hist_quant,
    }

    from xgboost_ray_tpu import progreg

    train_start = time.time()
    additional_results = {}
    # capture the protocol run's compiled-program signatures so the snapshot
    # carries their jaxpr fingerprints (tools/rxgbverify) — a PR that
    # silently changes a compiled program shows up as a fingerprint diff
    # across BENCH_*.json files. Capture costs one early-returning branch
    # per registration site; the abstract re-trace below runs post-timing.
    with progreg.capture():
        progreg.clear()
        bst = train(
            params,
            dtrain,
            num_boost_round=rounds,
            additional_results=additional_results,
            ray_params=RayParams(num_actors=actors, checkpoint_frequency=0),
        )
        train_time = time.time() - train_start
        print(f"[bench] TRAIN TIME TAKEN: {train_time:.2f}s", file=sys.stderr)
        assert bst.num_boosted_rounds() == rounds
        try:
            from tools.rxgbverify import fingerprint_registry

            program_fingerprints = fingerprint_registry()
        except Exception as exc:  # fingerprinting must never fail the bench
            print(f"[bench] program fingerprinting failed: {exc}",
                  file=sys.stderr)
            program_fingerprints = {}
    progreg.clear()  # drop the engine references the records keep alive

    # per-round time series: the artifact the single-chip -> 8-chip projection
    # argues from (VERDICT r3 weak #7). First chunk carries the compile; the
    # median of the rest is the steady-state marginal.
    rt = additional_results.get("round_times_s") or []
    detail = {}
    if rt:
        chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
        detail = {
            "round_times_s": [round(v, 4) for v in rt],
            "first_chunk_mean_s": round(float(np.mean(rt[:chunk])), 4),
        }
        # true per-dispatch wall times: round_times_s above replicates each
        # fused chunk's MEAN across its rounds (per-round variance inside a
        # chunk is invisible by construction), so the real distribution is
        # recorded separately as [{rounds, seconds}] per compiled dispatch
        chunk_times = additional_results.get("chunk_times_s")
        if chunk_times:
            detail["chunk_times_s"] = chunk_times
        if len(rt) > chunk:
            # steady-state excludes the compile-carrying first chunk; with
            # fewer rounds than one chunk there IS no steady sample — omit
            # rather than mislabel compile time
            steady = rt[chunk:]
            detail["steady_median_s"] = round(float(np.median(steady)), 4)
            detail["steady_p90_s"] = round(float(np.percentile(steady, 90)), 4)
        print(f"[bench] round-time detail: {detail}", file=sys.stderr)

    if program_fingerprints:
        detail["program_fingerprints"] = program_fingerprints
        print(f"[bench] {len(program_fingerprints)} program fingerprints "
              f"recorded", file=sys.stderr)

    # measured collective wire bytes per round (the hist_quant metric; see
    # ops/histogram.py AllreduceBytes for the ring-model accounting)
    ar_bytes = additional_results.get("hist_allreduce_bytes_per_round")
    if ar_bytes is not None:
        detail["hist_allreduce_bytes_per_round"] = int(ar_bytes)

    # regression tripwire vs the newest recorded BENCH_*.json (like-for-like
    # bases only: steady-vs-steady or compile-inclusive-vs-same)
    if detail.get("steady_median_s"):
        current_s, current_basis = detail["steady_median_s"], "steady"
    elif detail.get("first_chunk_mean_s"):
        current_s, current_basis = (
            detail["first_chunk_mean_s"], "compile_inclusive"
        )
    else:
        current_s, current_basis = (
            train_time / max(rounds, 1), "compile_inclusive"
        )
    prev_rec, prev_name = _load_latest_bench_record(
        os.path.dirname(os.path.abspath(__file__))
    )
    trip = round_time_tripwire(current_s, prev_rec, prev_name,
                               backend=backend, current_basis=current_basis)
    if trip is not None:
        detail["regression_tripwire"] = trip

    # hist_quant ablation: paired none-vs-int8 runs measuring wire bytes AND
    # compile-free steady per-round wall clock. Both arms run fresh,
    # back-to-back, for 2 scan chunks so the steady median excludes the
    # compile-carrying first chunk (the protocol run's 10-rounds-in-1-chunk
    # figure conflates compile and steady and would unfairly penalize the
    # bigger int8 program). Default on for the CPU mesh; opt-in on TPU via
    # BENCH_QUANT_ABLATION=1 (it adds two short extra trainings).
    abl_env = os.environ.get("BENCH_QUANT_ABLATION")
    run_ablation = hist_quant == "none" and (
        abl_env == "1" or (abl_env is None and not on_tpu)
    )
    if run_ablation:
        chunk = max(1, int(os.environ.get("RXGB_SCAN_MAX_CHUNK", "10")))
        abl_rounds = int(os.environ.get("BENCH_QUANT_ABLATION_ROUNDS", 2 * chunk))
        arms = {}
        for hq in ("none", "int8"):
            abl_params = dict(params)
            abl_params["hist_quant"] = hq
            abl_results = {}
            abl_start = time.time()
            train(
                abl_params,
                RayDMatrix(x, y),
                num_boost_round=abl_rounds,
                additional_results=abl_results,
                ray_params=RayParams(num_actors=actors, checkpoint_frequency=0),
            )
            abl_time = time.time() - abl_start
            per_round = _steady_per_round(
                abl_results.get("round_times_s"), chunk, abl_time, abl_rounds
            )
            arms[hq] = {
                "per_round_s": round(per_round, 4),
                "train_time_s": round(abl_time, 2),
            }
            abl_bytes = abl_results.get("hist_allreduce_bytes_per_round")
            if abl_bytes is not None:
                arms[hq]["hist_allreduce_bytes_per_round"] = int(abl_bytes)
        abl = {"rounds": abl_rounds, **{k: v for k, v in arms.items()}}
        b_none = arms["none"].get("hist_allreduce_bytes_per_round")
        b_int8 = arms["int8"].get("hist_allreduce_bytes_per_round")
        if b_none and b_int8:
            abl["allreduce_bytes_reduction"] = round(b_none / b_int8, 2)
        if arms["none"]["per_round_s"]:
            abl["int8_per_round_vs_none"] = round(
                arms["int8"]["per_round_s"] / arms["none"]["per_round_s"], 3
            )
        detail["hist_quant_ablation"] = abl
        print(f"[bench] hist_quant ablation: {abl}", file=sys.stderr)

    # full/sampled training ablation (the row-sampling counterpart of the
    # hist_quant ablation: hist_quant cut the wire bytes, the compacted
    # sampled build cuts the per-round FLOPs/HBM feeding them). Default on
    # for the CPU mesh; opt-in on TPU via BENCH_SAMPLING_ABLATION=1.
    samp_env = os.environ.get("BENCH_SAMPLING_ABLATION")
    if samp_env == "1" or (samp_env is None and not on_tpu):
        samp_section = run_sampling_ablation(x, y, params, actors)
        strip = sampling_round_time_tripwire(
            samp_section, prev_rec, prev_name, backend=backend
        )
        if strip is not None:
            samp_section["regression_tripwire"] = strip
        detail["sampling"] = samp_section
        recheck = r4_paired_recheck(detail)
        if recheck is not None:
            detail["r4_regression_recheck"] = recheck

    # low-precision (gh_precision) ablation: f32 vs int16 vs int8 quantized
    # gradients on the protocol data — per-round time, the static gh-plane
    # bytes/shard, and final-logloss deltas with their gates. Default on
    # for the CPU mesh; opt-in on TPU via BENCH_LOW_PRECISION=1.
    lp_env = os.environ.get("BENCH_LOW_PRECISION")
    if lp_env == "1" or (lp_env is None and not on_tpu):
        lp_section = run_low_precision_ablation(x, y, params, actors)
        ltrip = low_precision_tripwire(
            lp_section, prev_rec, prev_name, backend=backend
        )
        if ltrip is not None:
            lp_section["regression_tripwire"] = ltrip
        detail["low_precision"] = lp_section

    # streamed-vs-materialized ingestion ablation (ROADMAP item 1): peak
    # host RSS, ingest wall time, overlap efficiency, and the 5e-4 final-
    # logloss contract, with the >20% ingest-throughput tripwire. Default
    # on for the CPU mesh; opt-in on TPU via BENCH_STREAMING=1.
    stream_env = os.environ.get("BENCH_STREAMING")
    if stream_env == "1" or (stream_env is None and not on_tpu):
        stream_section = run_streaming_ablation(x, y, params, actors)
        strip2 = streaming_ingest_tripwire(
            stream_section, prev_rec, prev_name, backend=backend
        )
        if strip2 is not None:
            stream_section["regression_tripwire"] = strip2
        detail["streaming"] = stream_section
        print(f"[bench] streaming ablation: {stream_section}", file=sys.stderr)

    # wide-feature (F=2048, CTR-shaped) 1D-vs-2D mesh ablation: (8,1) row
    # sharding vs the (4,2) row x feature mesh, recording per-round time,
    # AllreduceBytes, and logloss parity. Default on for the 8-dev CPU
    # mesh; opt-in on TPU via BENCH_WIDE_FEATURE=1.
    wide_env = os.environ.get("BENCH_WIDE_FEATURE")
    if (wide_env == "1" or (wide_env is None and not on_tpu)) and \
            actors >= 4 and actors % 2 == 0:
        wide_section = run_wide_feature_ablation(actors=actors)
        if wide_section is not None:
            wtrip = wide_feature_round_time_tripwire(
                wide_section, prev_rec, prev_name, backend=backend
            )
            if wtrip is not None:
                wide_section["regression_tripwire"] = wtrip
            detail["wide_feature"] = wide_section

    # vectorized-HPO pairing: 4 sequential trials vs one vmapped-K=4
    # program (engine.step_vmapped) on the same data — trials-per-hour for
    # each arm, the cost_ratio gate, and the >20% drift tripwire. Default
    # on for the CPU mesh; opt-in on TPU via BENCH_HPO=1.
    hpo_env = os.environ.get("BENCH_HPO")
    if hpo_env == "1" or (hpo_env is None and not on_tpu):
        hpo_section = run_hpo_ablation(x, y, params, actors)
        htrip = hpo_cost_ratio_tripwire(
            hpo_section, prev_rec, prev_name, backend=backend
        )
        if htrip is not None:
            hpo_section["regression_tripwire"] = htrip
        detail["hpo"] = hpo_section

    # per-phase round-cost breakdown (sample/hist/split/partition/margin),
    # consumed from the runtime trace — shows WHERE sampling saves. Default
    # on for the CPU mesh; opt-in on TPU via BENCH_PHASE_BREAKDOWN=1.
    phase_env = os.environ.get("BENCH_PHASE_BREAKDOWN")
    if phase_env == "1" or (phase_env is None and not on_tpu):
        detail["phase_breakdown"] = run_phase_breakdown()

    # the protocol run's own obs snapshot: per-round span stats, ring-buffer
    # truncation accounting, wire bytes, and (when the breakdown above ran)
    # the per-phase means — recorded so future tripwires can query phases
    # straight out of BENCH_*.json without re-instrumenting
    obs_res = additional_results.get("obs") or {}
    if obs_res:
        round_durs = [
            r["dur_s"] for r in obs_res.get("rounds") or []
            if r.get("dur_s") is not None
        ]
        obs_section = {
            "rounds_traced": len(round_durs),
            "events": len(obs_res.get("events") or []),
            "dropped_spans": obs_res.get("dropped_spans", 0),
            "capacity": obs_res.get("capacity"),
        }
        if round_durs:
            obs_section["round_dur_mean_s"] = round(
                float(np.mean(round_durs)), 4
            )
            obs_section["round_dur_median_s"] = round(
                float(np.median(round_durs)), 4
            )
        if ar_bytes is not None:
            obs_section["allreduce_bytes_per_round"] = int(ar_bytes)
        full_phases = (detail.get("phase_breakdown") or {}).get("full")
        if full_phases:
            obs_section["phase_ms"] = {
                k: full_phases[k]
                for k in ("sample_ms", "hist_ms", "split_ms", "partition_ms",
                          "margin_ms", "allreduce_ms")
                if k in full_phases
            }
        detail["obs"] = obs_section
        print(f"[bench] obs snapshot: {obs_section}", file=sys.stderr)

    # instrumentation-overhead pairing (tracing on vs off) with the ≤2%
    # budget tripwire. Default on for the CPU mesh; opt-in on TPU via
    # BENCH_OBS_OVERHEAD=1 (two short extra trainings).
    obs_env = os.environ.get("BENCH_OBS_OVERHEAD")
    if obs_env == "1" or (obs_env is None and not on_tpu):
        obs_overhead = run_obs_overhead(x, y, params, actors)
        otrip = obs_overhead_tripwire(
            obs_overhead, prev_rec, prev_name, backend=backend
        )
        if otrip is not None:
            obs_overhead["regression_tripwire"] = otrip
        detail["obs_overhead"] = obs_overhead

    # closed-loop serving benchmark (the online-inference counterpart of the
    # training protocol). Default on for the CPU mesh; opt-in on TPU via
    # BENCH_SERVE=1 (it adds a short extra training + a few seconds of
    # serving traffic).
    serve_env = os.environ.get("BENCH_SERVE")
    if serve_env == "1" or (serve_env is None and not on_tpu):
        serve_trained = _train_serve_model()
        serve_section = run_serve_measurement(trained=serve_trained)
        strip = serve_latency_tripwire(
            serve_section, prev_rec, prev_name, backend=backend
        )
        if strip is not None:
            serve_section["regression_tripwire"] = strip
        detail["serve"] = serve_section
        # paired arm: the identical model + closed loop on the FIL-style
        # node-array layout; its p99 is gated against BOTH the recorded
        # history and (tightly) the in-process heap arm
        na_section = run_serve_measurement(
            layout="node_array", trained=serve_trained
        )
        natrip = serve_latency_tripwire(
            na_section, prev_rec, prev_name, backend=backend,
            section="serve_node_array",
        )
        if natrip is not None:
            na_section["regression_tripwire"] = natrip
        ltrip = serve_layout_tripwire(serve_section, na_section)
        if ltrip is not None:
            na_section["layout_tripwire"] = ltrip
            na_section["p99_speedup_vs_heap"] = round(
                1.0 / ltrip["ratio"], 3
            ) if ltrip["ratio"] else None
        detail["serve_node_array"] = na_section

    # deterministic chaos soak (the recovery counterpart of the protocol
    # run). Default on for the CPU mesh so every recorded BENCH_*.json
    # snapshot carries a `chaos` section for the time-to-recover tripwire
    # to compare against; opt-in on TPU via BENCH_CHAOS=1.
    chaos_env = os.environ.get("BENCH_CHAOS")
    if chaos_env == "1" or (chaos_env is None and not on_tpu):
        chaos_section = run_chaos_measurement()
        ctrip = chaos_recovery_tripwire(
            chaos_section, prev_rec, prev_name, backend=backend
        )
        if ctrip is not None:
            chaos_section["regression_tripwire"] = ctrip
        etrip = elastic_recovery_tripwire(
            chaos_section, prev_rec, prev_name, backend=backend
        )
        if etrip is not None:
            chaos_section["elastic_regression_tripwire"] = etrip
        detail["chaos"] = chaos_section

    # normalize to the full protocol (11M rows x 100 rounds) when a smaller
    # config was run, so the metric stays comparable across environments
    scale = (11_000_000 / n_rows) * (100 / rounds)
    normalized = train_time * scale
    metric = (
        "higgs11m_100r_train_wall_clock"
        if scale == 1.0
        else "higgs11m_100r_train_wall_clock_extrapolated"
    )
    if not on_tpu:
        # an extrapolation from the virtual CPU mesh is NOT a benchmark —
        # make the fallback impossible to mistake for a measurement
        metric = "higgs11m_100r_train_wall_clock_extrapolated"
        print(
            "[bench] WARNING: CPU-mesh fallback; the value below is a "
            f"{scale:.0f}x extrapolation, not a TPU measurement. For a "
            "MEASURED large-scale figure on this host, run "
            "`python bench.py --large` (streams the HIGGS shape at the "
            "largest row count the host holds, auto-scale recorded).",
            file=sys.stderr,
        )
    if on_tpu and actors == 1:
        # BASELINE.md's north-star machine is a v5e-8 (8 chips, 8 actors,
        # data-parallel); this environment exposes ONE chip. The headline
        # metric stays the honest single-chip measurement.
        print(
            f"[bench] single-chip measurement (the BASELINE.md target "
            f"machine is a v5e-8; a measured/8 = {normalized / 8:.1f}s "
            f"figure would be an IDEALIZED upper bound assuming perfect "
            f"8-way scaling — it is NOT a measured multi-chip result)",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(normalized, 2),
                "unit": "s",
                "vs_baseline": round(BASELINE_GPU_HIST_S / normalized, 3),
                "backend": backend,
                "rows": n_rows,
                "rounds": rounds,
                "actors": actors,
                "train_time_s": round(train_time, 2),
                **detail,
            }
        )
    )


def _run_child(extra_env, timeout_s):
    """Run the measurement in a child; return its JSON line or None."""
    env = dict(os.environ)
    env.update(extra_env)
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run"],
            env=env, timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired as exc:
        print("[bench] measurement child timed out; its last output:",
              file=sys.stderr)
        for stream in (exc.stdout, exc.stderr):
            if not stream:
                continue
            if isinstance(stream, bytes):
                stream = stream.decode(errors="replace")
            for t in stream.strip().splitlines()[-6:]:
                print(f"[bench]   {t[:200]}", file=sys.stderr)
        return None
    sys.stderr.write(res.stderr)
    for line in reversed(res.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            return line
    print(f"[bench] measurement child exited rc={res.returncode} without a "
          f"result line", file=sys.stderr)
    tail = res.stdout.strip().splitlines()[-3:]
    for t in tail:
        print(f"[bench]   child stdout: {t[:200]}", file=sys.stderr)
    return None


def main():
    # persistent compile cache: repeated protocol runs (and retries after
    # tunnel hiccups) skip the expensive remote compiles
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", 3000))
    if _probe_accelerator():
        line = _run_child({"BENCH_EXPECT_TPU": "1"}, timeout_s)
        if line is None:
            # TPU attempt failed (worker crash / timeout): a dead client's
            # tunnel lease takes a while to expire, so re-probe (with its
            # built-in backoff) until the backend answers again, then retry
            # once with a smaller fused-scan chunk — smaller compiled
            # programs, less live at once — before the CPU fallback
            print("[bench] re-probing backend before the TPU retry",
                  file=sys.stderr)
            if _probe_accelerator(attempts=5, backoff_s=90.0):
                print("[bench] retrying on TPU with RXGB_SCAN_MAX_CHUNK=4",
                      file=sys.stderr)
                line = _run_child(
                    {"BENCH_EXPECT_TPU": "1", "RXGB_SCAN_MAX_CHUNK": "4"},
                    timeout_s,
                )
        if line is not None:
            print(line)
            return
        print("[bench] TPU attempts exhausted; falling back to the virtual "
              "CPU mesh with an extrapolated metric.", file=sys.stderr)
    else:
        print(
            "[bench] accelerator backend unavailable (or wedged); falling "
            "back to the virtual CPU mesh with an extrapolated metric.",
            file=sys.stderr,
        )
    line = _run_child({"BENCH_FORCE_CPU": "1"}, timeout_s)
    if line is not None:
        print(line)
    else:
        sys.exit(1)


def chaos_only_main():
    """``--chaos``: run ONLY the chaos soak and print one JSON line headlined
    by its time-to-recover, with the full ``chaos`` section and the >20%
    recovery-regression tripwire vs the newest BENCH_*.json. Runs on the
    8-device virtual CPU mesh unless BENCH_CHAOS_ON_ACCEL=1 keeps the
    ambient accelerator backend."""
    if os.environ.get("BENCH_CHAOS_ON_ACCEL") != "1":
        _force_cpu_mesh()
    import jax

    backend = jax.default_backend()
    section = run_chaos_measurement()
    prev_rec, prev_name = _load_latest_bench_record(
        os.path.dirname(os.path.abspath(__file__))
    )
    trip = chaos_recovery_tripwire(section, prev_rec, prev_name,
                                   backend=backend)
    if trip is not None:
        section["regression_tripwire"] = trip
    etrip = elastic_recovery_tripwire(section, prev_rec, prev_name,
                                      backend=backend)
    if etrip is not None:
        section["elastic_regression_tripwire"] = etrip
    ok = section["model_matches"] and section["ckpt_resume_matches"]
    elastic_sec = section.get("elastic")
    if elastic_sec is not None:
        # the elastic continuation must replay nothing, reproduce the
        # uninterrupted model, and recover strictly faster than the
        # restart-from-checkpoint policy
        ok = ok and elastic_sec["model_matches"]
        ok = ok and elastic_sec["rounds_replayed"] == 0
        cvr = section.get("continue_vs_restart")
        if cvr is not None:
            ok = ok and cvr["continue_faster"]
    # the per-config pairings carry the same contract: zero replay,
    # uninterrupted-model identity, continuation strictly faster
    for key in ("elastic_2d", "elastic_streamed", "elastic_domain"):
        arm = section.get(key)
        if arm is None:
            continue
        ok = ok and arm["elastic"]["rounds_replayed"] == 0
        ok = ok and arm["elastic"]["model_matches"]
        cvr = arm.get("continue_vs_restart")
        if cvr is not None:
            ok = ok and cvr["continue_faster"]
    print(
        json.dumps(
            {
                "metric": "chaos_time_to_recover_s",
                "value": section["time_to_recover_s"],
                "unit": "s",
                "backend": backend,
                "chaos": section,
            }
        )
    )
    if not ok:
        # a chaos soak whose recovered model DIFFERS from the uninterrupted
        # run is a correctness failure, not a slow recovery — fail the run
        print("[bench] chaos soak FAILED identity checks", file=sys.stderr)
        sys.exit(1)


def serve_only_main():
    """``--serve``: run ONLY the closed-loop serving benchmark and print one
    JSON line headlined by its QPS, with the full ``serve`` section. Runs on
    the 8-device virtual CPU mesh unless BENCH_SERVE_ON_ACCEL=1 keeps the
    ambient accelerator backend."""
    if os.environ.get("BENCH_SERVE_ON_ACCEL") != "1":
        _force_cpu_mesh()
    import jax

    backend = jax.default_backend()
    trained = _train_serve_model()
    section = run_serve_measurement(trained=trained)
    na_section = run_serve_measurement(layout="node_array", trained=trained)
    prev_rec, prev_name = _load_latest_bench_record(
        os.path.dirname(os.path.abspath(__file__))
    )
    trip = serve_latency_tripwire(section, prev_rec, prev_name,
                                  backend=backend)
    if trip is not None:
        section["regression_tripwire"] = trip
    natrip = serve_latency_tripwire(na_section, prev_rec, prev_name,
                                    backend=backend,
                                    section="serve_node_array")
    if natrip is not None:
        na_section["regression_tripwire"] = natrip
    ltrip = serve_layout_tripwire(section, na_section)
    if ltrip is not None:
        na_section["layout_tripwire"] = ltrip
        na_section["p99_speedup_vs_heap"] = round(
            1.0 / ltrip["ratio"], 3
        ) if ltrip["ratio"] else None
    print(
        json.dumps(
            {
                "metric": "serve_closed_loop_qps",
                "value": section["qps"],
                "unit": "req/s",
                "backend": backend,
                "serve": section,
                "serve_node_array": na_section,
            }
        )
    )


def large_only_main():
    """``--large``: run ONLY the composed-headline large measurement and
    print one JSON line headlined by the composed arm's steady per-round
    time, with the full ``large`` section and the >20% drift tripwire vs
    the newest BENCH_*.json. Runs on the 8-device virtual CPU mesh unless
    BENCH_LARGE_ON_ACCEL=1 keeps the ambient accelerator backend. Exits
    nonzero when any of the section's contracts (memory budget, relative
    logloss envelope, wire byte cut) fails."""
    if os.environ.get("BENCH_LARGE_ON_ACCEL") != "1":
        _force_cpu_mesh()
    import jax

    backend = jax.default_backend()
    section = run_large_measurement()
    prev_rec, prev_name = _load_latest_bench_record(
        os.path.dirname(os.path.abspath(__file__))
    )
    trip = large_tripwire(section, prev_rec, prev_name, backend=backend)
    if trip is not None:
        section["regression_tripwire"] = trip
    print(
        json.dumps(
            {
                "metric": "large_composed_steady_per_round_s",
                "value": section["composed"]["steady_per_round_s"],
                "unit": "s",
                "backend": backend,
                "large": section,
            }
        )
    )
    ok = section["mem_budget_ok"] and section["logloss_ok"]
    ok = ok and section.get("wire_bytes_ok", True)
    if not ok:
        print("[bench] large measurement FAILED its contracts",
              file=sys.stderr)
        sys.exit(1)


def lowprec_only_main():
    """``--lowprec``: run ONLY the low-precision ablation (gh arms + the
    composed row/block wire arms) on protocol-shaped data and print one
    JSON line headlined by the block wire's measured byte cut vs the row
    wire, with the full ``low_precision`` section and its tripwire. Runs
    on the 8-device virtual CPU mesh unless BENCH_LOW_PRECISION_ON_ACCEL=1
    keeps the ambient backend. Exits nonzero when a section gate fails."""
    if os.environ.get("BENCH_LOW_PRECISION_ON_ACCEL") != "1":
        _force_cpu_mesh()
    import jax

    backend = jax.default_backend()
    rows = int(os.environ.get("BENCH_LOW_PRECISION_ROWS", 200_000))
    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    actors = int(os.environ.get("BENCH_ACTORS",
                                max(1, len(jax.devices()))))
    x, y = make_higgs_like(rows, n_feat)
    params = {
        "objective": "binary:logistic",
        "eval_metric": ["logloss"],
        "max_depth": int(os.environ.get("BENCH_DEPTH", 6)),
        "eta": 0.1,
        "max_bin": 256,
        "tree_method": "tpu_hist",
    }
    section = run_low_precision_ablation(x, y, params, actors)
    prev_rec, prev_name = _load_latest_bench_record(
        os.path.dirname(os.path.abspath(__file__))
    )
    trip = low_precision_tripwire(section, prev_rec, prev_name,
                                  backend=backend)
    if trip is not None:
        section["regression_tripwire"] = trip
    print(
        json.dumps(
            {
                "metric": "low_precision_block_wire_bytes_cut",
                "value": section.get("block_wire_bytes_cut"),
                "unit": "x",
                "backend": backend,
                "low_precision": section,
            }
        )
    )
    ok = True
    for gate in ("int16_logloss_ok", "int8_logloss_ok", "round_time_ok",
                 "gh_bytes_cut_ok", "block_wire_bytes_ok",
                 "block_no_worse_than_row_ok", "block_vs_row_logloss_ok"):
        ok = ok and section.get(gate, True)
    if not ok:
        print("[bench] low-precision ablation FAILED its contracts",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    if "--serve" in sys.argv:
        serve_only_main()
    elif "--chaos" in sys.argv:
        chaos_only_main()
    elif "--large" in sys.argv:
        large_only_main()
    elif "--lowprec" in sys.argv:
        lowprec_only_main()
    elif "--run" in sys.argv:
        run_measurement()
    else:
        main()
