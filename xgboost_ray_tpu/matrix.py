"""RayDMatrix: the distributed data handle for train()/predict().

API-compatible re-implementation of ``xgboost_ray/matrix.py`` (RayDMatrix,
RayShardingMode, combine_data, central/distributed loaders, qid sorting),
re-targeted at the TPU runtime: shards are host numpy dicts keyed by actor
rank; the engine device_puts them onto the mesh and bins them there
(HBM-resident quantile-binned blocks replace xgboost's C++ DMatrix).

Central loading (driver loads everything, shards by row) and distributed
loading (each rank loads its own files/partitions) mirror
``matrix.py:431-487`` and ``matrix.py:614-693`` respectively; the sharding
index math and prediction re-assembly mirror ``matrix.py:1088-1157``.
"""

import glob
import os
import uuid
from enum import Enum
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

from xgboost_ray_tpu.data_sources import DataSource, RayFileType, data_sources
from xgboost_ray_tpu.data_sources._distributed import (
    assign_partitions_to_actors,
    get_actor_rank_hosts,
)

Data = Union[str, List[str], np.ndarray, pd.DataFrame, pd.Series, Sequence[Any]]


class RayShardingMode(Enum):
    """How rows (or files, for distributed loading) map to actor ranks.

    Mirrors ``xgboost_ray/matrix.py:106-124``: INTERLEAVED strides rows over
    ranks, BATCH gives contiguous blocks, FIXED pins pre-assigned partitions.
    """

    INTERLEAVED = 1
    BATCH = 2
    FIXED = 3


def _batch_split_points(num_actors: int, n: int) -> np.ndarray:
    """Contiguous BATCH row boundaries (the reference's remainder
    semantics, ``matrix.py:1088-1110``): rank r owns
    ``[points[r], points[r+1])``. The ONE place the split math lives —
    consumed by ``_get_sharding_indices`` and the streamed .npy row
    windows, which must never diverge."""
    n_per_actor, extras = divmod(n, num_actors)
    sizes = [n_per_actor + 1] * extras + [n_per_actor] * (num_actors - extras)
    return np.concatenate([[0], np.cumsum(sizes)])


def _get_sharding_indices(
    sharding: RayShardingMode, rank: int, num_actors: int, n: int
) -> List[int]:
    """Row/file indices owned by ``rank`` (semantics of ``matrix.py:1088-1110``)."""
    if sharding == RayShardingMode.BATCH:
        points = _batch_split_points(num_actors, n)
        return list(range(points[rank], points[rank + 1]))
    if sharding == RayShardingMode.INTERLEAVED:
        return list(range(rank, n, num_actors))
    raise ValueError(
        f"Invalid value for `sharding` parameter: {sharding}. Pass a "
        f"RayShardingMode enum member, e.g. RayShardingMode.BATCH."
    )


def combine_data(sharding: RayShardingMode, data: Iterable) -> np.ndarray:
    """Re-assemble per-rank prediction shards into original row order
    (inverse of ``_get_sharding_indices``; semantics of ``matrix.py:1114-1157``)."""
    if sharding not in (RayShardingMode.BATCH, RayShardingMode.INTERLEAVED):
        raise ValueError(
            f"Invalid value for `sharding` parameter: {sharding}. Pass a "
            f"RayShardingMode enum member, e.g. RayShardingMode.BATCH."
        )
    parts = [np.asarray(d) for d in data if len(d)]
    if not parts:
        return np.array([])
    if sharding == RayShardingMode.BATCH:
        return np.concatenate(parts, axis=0)
    # INTERLEAVED: ranks may be off by one for uneven splits. Stacking on a
    # new axis 1 then flattening restores row order for ANY trailing shape
    # (scalars, softprob [K], SHAP [F+1] / [K,F+1], interactions
    # [F+1,F+1], leaf indices [T]).
    min_len = min(len(d) for d in parts)
    res = np.stack([d[:min_len] for d in parts], axis=1).reshape(
        (len(parts) * min_len,) + parts[0].shape[1:]
    )
    tails = [d[min_len:] for d in parts if len(d) > min_len]
    if tails:
        res = np.concatenate([res] + tails, axis=0)
    return res


def qid_sort_order(qid) -> Optional[np.ndarray]:
    """Stable order making query groups contiguous, or None if already sorted
    (``matrix.py:70-102`` semantics)."""
    order = np.argsort(np.asarray(qid), kind="stable")
    if np.all(order == np.arange(len(order))):
        return None
    return order


def ensure_sorted_by_qid(df: pd.DataFrame, qid) -> Tuple[pd.DataFrame, Any]:
    """Stable-sort rows so query groups are contiguous (``matrix.py:70-102``)."""
    order = qid_sort_order(qid)
    if order is None:
        return df, qid
    qid_sorted = qid.iloc[order] if isinstance(qid, pd.Series) else np.asarray(qid)[order]
    return df.iloc[order], qid_sorted


def translate_category_codes(
    col: np.ndarray, from_cats: Sequence[Any], to_cats: Sequence[Any]
) -> np.ndarray:
    """Re-map category codes encoded against ``from_cats`` onto ``to_cats``.

    Categories absent from ``to_cats`` become NaN (missing) — the same
    behavior xgboost shows for unseen categories at predict time.
    """
    mapping = np.full(len(from_cats), np.nan, np.float32)
    to_index = {v: i for i, v in enumerate(to_cats)}
    for i, v in enumerate(from_cats):
        if v in to_index:
            mapping[i] = to_index[v]
    out = np.full(col.shape, np.nan, np.float32)
    valid = ~np.isnan(col)
    out[valid] = mapping[col[valid].astype(np.int64)]
    return out


def translate_shard_categories(
    shard: Dict[str, Optional[np.ndarray]],
    from_cats: Optional[Dict[int, Sequence[Any]]],
    to_cats: Optional[Dict[int, Sequence[Any]]],
) -> Dict[str, Optional[np.ndarray]]:
    """Align an auto-encoded shard's category codes with a reference mapping
    (the training matrix's): frames with different category sets would
    otherwise assign different codes to the same value and be routed down
    wrong branches."""
    if not from_cats or not to_cats or from_cats == to_cats:
        # nothing auto-encoded on the source side -> codes are already in the
        # caller's mapping; avoid a pointless full copy
        return shard
    data = np.array(shard["data"], copy=True)
    for col, cats in (from_cats or {}).items():
        target = to_cats.get(col)
        if target is None or tuple(cats) == tuple(target):
            continue
        data[:, col] = translate_category_codes(data[:, col], cats, target)
    out = dict(shard)
    out["data"] = data
    return out


class _RayDMatrixLoader:
    """Shared loader logic: source resolution, dataframe splitting."""

    def __init__(
        self,
        data: Data,
        label: Optional[Data] = None,
        weight: Optional[Data] = None,
        feature_weights: Optional[Data] = None,
        base_margin: Optional[Data] = None,
        missing: Optional[float] = None,
        label_lower_bound: Optional[Data] = None,
        label_upper_bound: Optional[Data] = None,
        feature_names: Optional[List[str]] = None,
        feature_types: Optional[List[Any]] = None,
        qid: Optional[Data] = None,
        filetype: Optional[RayFileType] = None,
        ignore: Optional[List[str]] = None,
        enable_categorical: bool = False,
        **kwargs,
    ):
        self.data = data
        self.label = label
        self.weight = weight
        self.feature_weights = feature_weights
        self.base_margin = base_margin
        self.missing = missing
        self.label_lower_bound = label_lower_bound
        self.label_upper_bound = label_upper_bound
        self.feature_names = feature_names
        self.feature_types = feature_types
        self.qid = qid
        self.filetype = filetype
        self.ignore = ignore
        self.enable_categorical = enable_categorical
        self.kwargs = kwargs
        self.data_source: Optional[type] = None
        self.actor_shards: Optional[Dict[int, List[Any]]] = None
        self._resolved_feature_names: Optional[List[str]] = None
        self._resolved_feature_types: Optional[List[str]] = None
        # col index -> category values, recorded when columns auto-encode
        self._resolved_categories: Optional[Dict[int, tuple]] = None

    def get_data_source(self) -> type:
        if self.data_source is not None:
            return self.data_source
        filetype = self.filetype
        data = self.data
        for source in data_sources:
            if filetype is None and hasattr(source, "get_filetype"):
                filetype = source.get_filetype(data) or filetype
        for source in data_sources:
            if source.is_data_type(data, filetype):
                self.data_source = source
                self.filetype = filetype
                return source
        raise ValueError(
            f"Unable to infer data source for data of type {type(data)}. "
            f"Pass a supported data type (numpy array, pandas frame, "
            f"csv/parquet path(s), partition list) or specify `filetype`."
        )

    def _split_dataframe(self, df: pd.DataFrame) -> Dict[str, Optional[np.ndarray]]:
        """Extract label/weight/etc. columns; convert features to float32.

        Semantics of ``matrix.py:283-358``: string references select (and
        exclude) columns of the frame, array-likes attach externally.
        """
        source = self.get_data_source()
        exclude: List[str] = []

        def pick(ref):
            series, col = source.get_column(df, ref)
            if col is not None:
                exclude.append(col)
            return series

        label = pick(self.label)
        weight = pick(self.weight)
        base_margin = pick(self.base_margin)
        ll = pick(self.label_lower_bound)
        lu = pick(self.label_upper_bound)
        qid = pick(self.qid)

        x = df.drop(columns=[c for c in exclude if c in df.columns])
        if self.ignore:
            x = x.drop(columns=[c for c in self.ignore if c in x.columns])

        if qid is not None:
            order = qid_sort_order(qid)
            if order is not None:
                x = x.iloc[order]
                qid = np.asarray(qid)[order]
                label = None if label is None else np.asarray(label)[order]
                weight = None if weight is None else np.asarray(weight)[order]
                base_margin = None if base_margin is None else np.asarray(base_margin)[order]
                ll = None if ll is None else np.asarray(ll)[order]
                lu = None if lu is None else np.asarray(lu)[order]

        self._resolved_feature_names = self.feature_names or [str(c) for c in x.columns]

        # categorical columns -> integer codes ('c' in the feature-type map).
        # Encoding a column requires the global category set, so auto-encoding
        # is a central-loading feature; distributed shards must arrive
        # pre-encoded (pass feature_types=['c', ...] with numeric codes).
        cat_cols = [
            c
            for c in x.columns
            if isinstance(x[c].dtype, pd.CategoricalDtype)
            or not pd.api.types.is_numeric_dtype(x[c].dtype)
        ]
        ftypes = list(self.feature_types) if self.feature_types else None
        if cat_cols:
            if not self.enable_categorical:
                raise ValueError(
                    f"DataFrame has categorical/object columns {cat_cols}; "
                    f"pass enable_categorical=True (or encode them "
                    f"numerically) — mirroring xgboost.DMatrix semantics."
                )
            if isinstance(self, _DistributedRayDMatrixLoader):
                raise ValueError(
                    "categorical columns cannot be auto-encoded under "
                    "distributed loading (per-shard category sets would "
                    "disagree); encode to integer codes and pass "
                    "feature_types, or use central loading."
                )
            if ftypes is None:
                ftypes = [
                    "c" if c in cat_cols else "q" for c in x.columns
                ]
            x = x.copy()
            categories: Dict[int, tuple] = {}
            col_pos = {c: i for i, c in enumerate(x.columns)}
            for c in cat_cols:
                as_cat = x[c].astype("category")
                categories[col_pos[c]] = tuple(as_cat.cat.categories.tolist())
                codes = as_cat.cat.codes.astype(np.float32)
                x[c] = codes.where(codes >= 0, np.nan)  # -1 == missing
            self._resolved_categories = categories
        elif self.enable_categorical and ftypes is None:
            ftypes = ["q"] * len(x.columns)
        self._resolved_feature_types = ftypes

        feats = x.to_numpy(dtype=np.float32, copy=False)
        if self.missing is not None and not np.isnan(self.missing):
            feats = np.where(feats == np.float32(self.missing), np.nan, feats)

        def arr(v, dtype=np.float32):
            return None if v is None else np.asarray(v, dtype=dtype).ravel()

        return {
            "data": feats,
            "label": arr(label),
            "weight": arr(weight),
            "base_margin": arr(base_margin),
            "label_lower_bound": arr(ll),
            "label_upper_bound": arr(lu),
            "qid": None if qid is None else np.asarray(qid).ravel(),
        }


class _CentralRayDMatrixLoader(_RayDMatrixLoader):
    """Driver loads the full dataset once, then row-shards per rank
    (``matrix.py:431-487``)."""

    def load_fields(self) -> Dict[str, Optional[np.ndarray]]:
        """Load + split ONCE without per-rank copies (the streamed central
        path slices chunks out of these arrays lazily)."""
        source = self.get_data_source()
        df = source.load_data(self.data, ignore=self.ignore, **self.kwargs)
        df = source.update_feature_names(df, None)
        return self._split_dataframe(df)

    def load_data(self, num_actors: int, sharding: RayShardingMode):
        fields = self.load_fields()
        n = fields["data"].shape[0]
        if num_actors > n:
            raise RuntimeError(
                f"Trying to shard data for {num_actors} actors, but the "
                f"dataset has only {n} rows. Use fewer actors."
            )
        refs: Dict[int, Dict[str, Optional[np.ndarray]]] = {}
        for rank in range(num_actors):
            idx = _get_sharding_indices(sharding, rank, num_actors, n)
            refs[rank] = {
                k: (v[idx] if v is not None else None) for k, v in fields.items()
            }
        return refs, n


class _DistributedRayDMatrixLoader(_RayDMatrixLoader):
    """Each rank loads only its own files/partitions (``matrix.py:614-693``)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # with per-rank loading, external arrays cannot be aligned to shard
        # rows — only column-name references work (reference matrix.py:533-538)
        for field in ("label", "weight", "base_margin", "label_lower_bound",
                      "label_upper_bound", "qid"):
            val = getattr(self, field)
            if val is not None and not isinstance(val, str):
                raise ValueError(
                    f"Distributed data loading only works with column names "
                    f"for `{field}`, got {type(val)}. Pass the name of the "
                    f"column in your data files, or use central loading "
                    f"(`distributed=False`)."
                )

    def _expand(self) -> Any:
        data = self.data
        if isinstance(data, str) and os.path.isdir(data):
            files = sorted(
                glob.glob(os.path.join(data, "**", "*"), recursive=True)
            )
            files = [f for f in files if os.path.isfile(f)]
            return files
        if isinstance(data, str):
            hits = sorted(glob.glob(data))
            if len(hits) > 1:
                return hits
        return data

    def load_shard(self, rank: int, num_actors: int, sharding: RayShardingMode):
        source = self.get_data_source()
        data = self._expand()
        if self.actor_shards is not None:  # FIXED: pre-assigned partitions
            indices = self.actor_shards.get(rank, [])
            df = source.load_data(
                data, ignore=self.ignore, indices=indices, **self.kwargs
            )
        else:
            n_parts = source.get_n(data)
            if num_actors > n_parts:
                raise RuntimeError(
                    f"Trying to shard {n_parts} files/partitions across "
                    f"{num_actors} actors: use fewer actors or central loading."
                )
            indices = _get_sharding_indices(sharding, rank, num_actors, n_parts)
            df = source.load_data(
                data, ignore=self.ignore, indices=indices, **self.kwargs
            )
        df = source.update_feature_names(df, None)
        return self._split_dataframe(df)

    def assign_shards(self, num_actors: int):
        """FIXED sharding: locality-aware partition assignment
        (``matrix.py:595-612`` + ``_distributed.py:24-112``)."""
        data = self._expand()
        source = self.get_data_source()
        # distributed-frame sources (modin/dask/ray.data) provide their own
        # partition objects + locality assignment
        _, assignment = source.get_actor_shards(data, list(range(num_actors)))
        if assignment:
            self.actor_shards = assignment
            return
        n_parts = source.get_n(data)
        hosts = get_actor_rank_hosts(num_actors)
        host_to_parts = {"localhost": list(range(n_parts))}
        self.actor_shards = assign_partitions_to_actors(host_to_parts, hosts)


class RayDMatrix:
    """Distributed data handle (API of ``xgboost_ray/matrix.py:697-968``).

    Lazy by default: pass ``num_actors`` to load eagerly, or the ``train()``/
    ``predict()`` functions will trigger loading with their actor count.
    """

    def __init__(
        self,
        data: Data,
        label: Optional[Data] = None,
        weight: Optional[Data] = None,
        feature_weights: Optional[Data] = None,
        base_margin: Optional[Data] = None,
        missing: Optional[float] = None,
        label_lower_bound: Optional[Data] = None,
        label_upper_bound: Optional[Data] = None,
        feature_names: Optional[List[str]] = None,
        feature_types: Optional[List[Any]] = None,
        qid: Optional[Data] = None,
        enable_categorical: Optional[bool] = None,
        num_actors: Optional[int] = None,
        filetype: Optional[RayFileType] = None,
        ignore: Optional[List[str]] = None,
        distributed: Optional[bool] = None,
        sharding: RayShardingMode = RayShardingMode.INTERLEAVED,
        lazy: bool = False,
        stream: bool = False,
        chunk_rows: Optional[int] = None,
        budget_mb: Optional[float] = None,
        sketch_capacity: Optional[int] = None,
        **kwargs,
    ):
        # streamed ingestion mode (ROADMAP item 1): shards materialize as
        # chunked readers instead of raw arrays; the engine's two-pass
        # sketch->bin pipeline keeps peak host memory O(chunk + sketch).
        # RXGB_STREAM_* env knobs fill whatever isn't passed explicitly.
        self.streamed = bool(stream)
        self.stream_config = None
        if self.streamed:
            from xgboost_ray_tpu.stream.reader import StreamConfig

            self.stream_config = StreamConfig(
                chunk_rows=chunk_rows,
                budget_mb=budget_mb,
                sketch_capacity=sketch_capacity,
            )
        elif chunk_rows is not None or budget_mb is not None \
                or sketch_capacity is not None:
            raise ValueError(
                "chunk_rows/budget_mb/sketch_capacity require stream=True "
                "(or RayStreamingDMatrix)."
            )
        if kwargs.get("group", None) is not None:
            raise ValueError(
                "`group` parameter is not supported; use `qid` instead."
            )
        if qid is not None and weight is not None:
            raise NotImplementedError("per-group weight is not implemented.")
        kwargs.pop("group", None)

        self._uid = uuid.uuid4().int
        self.feature_names = feature_names
        self.feature_types = feature_types
        self.missing = missing
        self.num_actors = num_actors
        self.sharding = sharding

        if distributed is None:
            distributed = self._can_load_distributed(data)
        elif distributed and not self._can_load_distributed(data):
            raise ValueError(
                f"Distributed loading is not supported for data of type "
                f"{type(data)}; pass file paths or partition lists."
            )
        self.distributed = distributed

        loader_cls = _DistributedRayDMatrixLoader if distributed else _CentralRayDMatrixLoader
        self.loader = loader_cls(
            data=data,
            label=label,
            weight=weight,
            feature_weights=feature_weights,
            base_margin=base_margin,
            missing=missing,
            label_lower_bound=label_lower_bound,
            label_upper_bound=label_upper_bound,
            feature_names=feature_names,
            feature_types=feature_types,
            qid=qid,
            filetype=filetype,
            ignore=ignore,
            enable_categorical=bool(enable_categorical),
            **kwargs,
        )

        self.refs: Dict[int, Dict[str, Optional[np.ndarray]]] = {}
        self.n: Optional[int] = None
        self.loaded = False

        # distributed-frame sources pin partitions to ranks: FIXED sharding
        # is set automatically (reference matrix.py:106-124 docstring)
        if distributed:
            try:
                source = self.loader.get_data_source()
                if getattr(source, "__name__", "") in ("Modin", "Dask", "RayDataset"):
                    self.sharding = RayShardingMode.FIXED
            except ValueError:
                pass  # source resolution errors surface at load time

        if num_actors is not None and not lazy:
            self.load_data(num_actors)

    @property
    def feature_weights(self) -> Optional[np.ndarray]:
        """Per-feature sampling weights (length n_features), resolved to a
        float32 array; biases the engine's colsample_* draws (reference
        surface: xgboost_ray/matrix.py:283-358 -> DMatrix feature_weights)."""
        fw = getattr(self.loader, "feature_weights", None)
        if fw is None:
            return None
        return np.asarray(fw, dtype=np.float32).ravel()

    @staticmethod
    def _can_load_distributed(data: Data) -> bool:
        if isinstance(data, str):
            # a single CSV cannot be row-split across workers; a single
            # parquet can (row groups), directories/globs expand to files
            # (reference semantics: matrix.py:1036-1060)
            return data.endswith(".parquet") or os.path.isdir(data)
        if isinstance(data, (list, tuple)) and data and isinstance(data[0], str):
            return True
        if isinstance(data, (list, tuple)) and data:
            return True  # partition list
        if hasattr(data, "__partitioned__"):
            return True
        # distributed-frame sources (modin/dask/ray.data) own their partitions
        # (reference matrix.py:1036-1060 checks the same frame types)
        for source in data_sources:
            if getattr(source, "supports_distributed_loading", False) and source.is_data_type(data, None):
                return True
        return False

    def assert_enough_shards_for_actors(self, num_actors: int) -> None:
        """Distributed mode: fail fast when files/partitions < actors
        (``xgboost_ray/matrix.py:900-901`` / ``:576-592``)."""
        if not isinstance(self.loader, _DistributedRayDMatrixLoader):
            return
        data = self.loader._expand()
        source = self.loader.get_data_source()
        n_shards = source.get_n(data)
        if num_actors > n_shards:
            raise RuntimeError(
                f"Trying to shard data for {num_actors} actors, but it only "
                f"has {n_shards} files/partitions. Use fewer actors, "
                f"re-partition, or pass `distributed=False` for centralized "
                f"row sharding."
            )

    # -- loading -----------------------------------------------------------

    def load_data(self, num_actors: Optional[int] = None):
        if num_actors is not None:
            if self.num_actors is not None and self.num_actors != num_actors:
                raise ValueError(
                    f"The number of actors of a RayDMatrix cannot change once "
                    f"set ({self.num_actors} -> {num_actors})."
                )
            self.num_actors = num_actors
        if self.num_actors is None:
            raise ValueError("Pass `num_actors` to load a RayDMatrix.")
        if self.loaded:
            return
        if self.streamed:
            self._load_streamed()
            self.loaded = True
            return
        if isinstance(self.loader, _CentralRayDMatrixLoader):
            self.refs, self.n = self.loader.load_data(self.num_actors, self.sharding)
            self.loaded = True
        else:
            # distributed: shards materialize per rank in get_data
            self.loaded = True

    # -- streamed loading --------------------------------------------------

    @staticmethod
    def _is_npy(path) -> bool:
        return isinstance(path, str) and path.endswith(".npy")

    def _load_streamed(self) -> None:
        """Build the per-rank {"stream": ShardStream} refs.

        Three chunk sources: a .npy feature file (raw offset reads; BATCH
        row windows per rank), in-memory central data (lazy row slices of
        the once-loaded arrays — no per-rank copies), and file lists
        (per-rank CSV/Parquet chunk iteration, built lazily in get_data).
        """
        from xgboost_ray_tpu.stream.reader import (
            fields_shard_stream,
            npy_shard_stream,
        )

        if self._is_npy(self.loader.data):
            if self.sharding != RayShardingMode.BATCH:
                raise ValueError(
                    "streamed .npy ingestion reads contiguous row windows; "
                    "pass sharding=RayShardingMode.BATCH."
                )
            for field, val in (("label", self.loader.label),
                               ("weight", self.loader.weight)):
                if val is not None and not self._is_npy(val):
                    raise ValueError(
                        f"streamed .npy ingestion takes `{field}` as a "
                        f".npy path aligned row-for-row with the data file."
                    )
            # anything the npy reader cannot deliver must fail loudly, not
            # silently train without it (the no-silent-fallback invariant)
            for field in ("base_margin", "label_lower_bound",
                          "label_upper_bound", "qid"):
                if getattr(self.loader, field) is not None:
                    raise NotImplementedError(
                        f"streamed .npy ingestion supports label/weight "
                        f"side files only; `{field}` would be silently "
                        f"dropped. Use CSV/Parquet streaming (column "
                        f"references) or materialize the matrix."
                    )
            # ditto for the dataframe-split transforms the raw offset reads
            # bypass: a `missing` sentinel would be sketched/binned as real
            # feature values, and `ignore` has no column names to act on
            if self.loader.missing is not None and \
                    not np.isnan(self.loader.missing):
                raise NotImplementedError(
                    "streamed .npy ingestion does not apply a `missing` "
                    "sentinel (raw offset reads bypass the dataframe "
                    "split); encode missing values as NaN in the .npy "
                    "file, or use CSV/Parquet streaming."
                )
            if self.loader.ignore:
                raise NotImplementedError(
                    "streamed .npy ingestion cannot honor `ignore`: a "
                    ".npy matrix has no column names. Drop the columns "
                    "from the file, or use CSV/Parquet streaming."
                )
            probe = npy_shard_stream(self.loader.data, config=self.stream_config)
            n = probe.n_rows
            if self.num_actors > n:
                raise RuntimeError(
                    f"Trying to shard data for {self.num_actors} actors, "
                    f"but the dataset has only {n} rows. Use fewer actors."
                )
            points = _batch_split_points(self.num_actors, n)
            for rank in range(self.num_actors):
                self.refs[rank] = {"stream": npy_shard_stream(
                    self.loader.data,
                    label_path=self.loader.label,
                    weight_path=self.loader.weight,
                    config=self.stream_config,
                    row_range=(int(points[rank]), int(points[rank + 1])),
                )}
            self.n = n
            return
        if isinstance(self.loader, _CentralRayDMatrixLoader):
            fields = self.loader.load_fields()
            n = fields["data"].shape[0]
            if self.num_actors > n:
                raise RuntimeError(
                    f"Trying to shard data for {self.num_actors} actors, "
                    f"but the dataset has only {n} rows. Use fewer actors."
                )
            for rank in range(self.num_actors):
                idx = np.asarray(_get_sharding_indices(
                    self.sharding, rank, self.num_actors, n
                ))
                self.refs[rank] = {"stream": fields_shard_stream(
                    fields, idx, config=self.stream_config,
                    source_token=("central", self._uid, rank),
                )}
            self.n = n
            return
        # distributed file lists: per-rank streams build lazily in get_data

    def _streamed_file_shard(self, rank: int) -> Dict[str, Any]:
        from xgboost_ray_tpu.stream.reader import file_shard_stream

        loader = self.loader
        data = loader._expand()
        source = loader.get_data_source()
        if loader.actor_shards is not None:
            indices = loader.actor_shards.get(rank, [])
        else:
            n_parts = source.get_n(data)
            if self.num_actors > n_parts:
                raise RuntimeError(
                    f"Trying to shard {n_parts} files/partitions across "
                    f"{self.num_actors} actors: use fewer actors or central "
                    f"loading."
                )
            indices = _get_sharding_indices(
                self.sharding, rank, self.num_actors, n_parts
            )
        files = [data[i] for i in indices] if isinstance(data, (list, tuple)) \
            else ([data] if indices else [])
        if not files or not all(isinstance(f, str) for f in files):
            raise NotImplementedError(
                "streamed distributed loading needs file paths (CSV or "
                "Parquet); partition/frame sources must be materialized."
            )
        ftype = {RayFileType.CSV: "csv", RayFileType.PARQUET: "parquet"}.get(
            loader.filetype
        )
        if ftype is None:
            raise NotImplementedError(
                f"streamed ingestion supports CSV/Parquet/.npy sources; got "
                f"filetype {loader.filetype!r}."
            )

        def split_fn(df):
            df = source.update_feature_names(df, None)
            return loader._split_dataframe(df)

        return {"stream": file_shard_stream(
            files, split_fn, ftype, config=self.stream_config,
            read_kwargs=loader.kwargs,
        )}

    def get_data(
        self, rank: int, num_actors: Optional[int] = None
    ) -> Dict[str, Optional[np.ndarray]]:
        self.load_data(num_actors)
        if rank not in self.refs:
            if not isinstance(self.loader, _DistributedRayDMatrixLoader):
                raise KeyError(f"No shard for rank {rank}")
            if self.streamed:
                self.refs[rank] = self._streamed_file_shard(rank)
            else:
                self.refs[rank] = self.loader.load_shard(
                    rank, self.num_actors, self.sharding
                )
        return self.refs[rank]

    def unload_data(self):
        self.refs = {}
        self.loaded = False

    def assign_shards_to_actors(self, actors: Sequence[Any]) -> bool:
        """FIXED-mode locality assignment before training (``matrix.py:595-612``)."""
        if self.sharding != RayShardingMode.FIXED:
            return False
        if not isinstance(self.loader, _DistributedRayDMatrixLoader):
            return False
        if self.loader.actor_shards is None:
            self.loader.assign_shards(self.num_actors or len(actors))
        return True

    # -- introspection -----------------------------------------------------

    def get_shard_sizes(self) -> Dict[int, int]:
        def size(s):
            if s.get("stream") is not None:
                return s["stream"].n_rows
            return s["data"].shape[0] if s.get("data") is not None else 0

        return {r: size(s) for r, s in self.refs.items()}

    @property
    def resolved_feature_names(self) -> Optional[List[str]]:
        return self.feature_names or self.loader._resolved_feature_names

    @property
    def resolved_feature_types(self) -> Optional[List[str]]:
        """Per-feature type map ('c' categorical / 'q' numeric), from the
        user's feature_types or detected category-dtype columns."""
        if self.feature_types:
            return list(self.feature_types)
        return self.loader._resolved_feature_types

    @property
    def resolved_categories(self) -> Optional[Dict[int, tuple]]:
        """col index -> category values for auto-encoded columns (used to
        align eval/predict frames with the training encoding)."""
        return self.loader._resolved_categories

    @property
    def has_label(self) -> bool:
        return self.loader.label is not None

    def __hash__(self):
        return self._uid

    def __eq__(self, other):
        return isinstance(other, RayDMatrix) and self._uid == other._uid


class RayStreamingDMatrix(RayDMatrix):
    """Out-of-core ingestion mode: shards are chunked readers, not arrays.

    Equivalent to ``RayDMatrix(..., stream=True)``. Training never
    materializes the raw [N, F] float32 shard: a deterministic mergeable
    quantile sketch streams over chunks (pass 1), global cuts merge on the
    mesh through the materialized sketch program's collective shape, and
    each chunk bins straight into the per-actor ``bin_dtype`` buffer with
    double-buffered host→device upload (pass 2). Peak host memory is
    O(chunk + sketch). Loads that fit in one chunk take the EXACT
    materialized path (bitwise-identical cuts, bins, and trained forest).

    Knobs (env fallbacks in parentheses): ``chunk_rows``
    (``RXGB_STREAM_CHUNK_ROWS``), ``budget_mb`` (``RXGB_STREAM_BUDGET_MB``;
    also derives chunk_rows when unset and validates the configured peak),
    ``sketch_capacity`` (``RXGB_STREAM_SKETCH_CAP``). See README
    "Streaming ingestion" for the memory model and composition matrix.
    """

    def __init__(self, *args, **kwargs):
        kwargs["stream"] = True
        super().__init__(*args, **kwargs)


class RayQuantileDMatrix(RayDMatrix):
    """Alias of RayDMatrix: all tpu_hist matrices are quantile-binned on
    device (the reference's distinction, ``matrix.py:971-975``, is a CUDA
    memory optimization that is the default here)."""


class RayDeviceQuantileDMatrix(RayDMatrix):
    """Accepted for API compatibility (``matrix.py:977-1033``); on TPU every
    matrix is already an HBM-resident quantile-binned block, so this behaves
    exactly like RayDMatrix."""

    def __init__(self, *args, max_bin: Optional[int] = None, **kwargs):
        self.max_bin = max_bin
        super().__init__(*args, **kwargs)
