"""Compatibility shims (analog of ``xgboost_ray/compat/__init__.py``).

The reference polyfills xgboost<1.0's function-style callbacks
(``compat/__init__.py:12-42``); here the equivalent is an adapter that wraps
legacy ``callback(env)`` callables into the TrainingCallback protocol, with
the classic ``CallbackEnv`` namedtuple surface.

There is no vendored Rabit tracker to ship (``compat/tracker.py`` in the
reference): rendezvous is native to JAX — see ``xgboost_ray_tpu.distributed``.
"""

from collections import namedtuple
from typing import Callable

from xgboost_ray_tpu.callback import TrainingCallback

LEGACY_CALLBACK = False  # new-style TrainingCallback is always available


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = False):
    """``shard_map`` across jax versions; replication checking off by
    default.

    The replication-static check was renamed ``check_rep`` ->
    ``check_vma`` when shard_map graduated from jax.experimental to the
    top level. On jax versions with the OLD checker (<= 0.4.x), ANY
    program carrying a ``lax.scan`` through shard_map trips a false
    positive ("Scan carry ... mismatched replication types") even when
    the program is replication-correct — measured here: enabling the
    check fails 15/17 of the booster-predict/gblinear/SHAP tests on jax
    0.4.37 while the identical programs run correctly with it off. The
    out_specs still pin the sharding contract. Pass ``check=True`` from a
    call site known to be clean on the deployed jax to opt back into the
    trace-time guard.
    """
    import inspect

    import jax

    try:  # jax >= 0.6 exposes shard_map at top level (check_vma kwarg)
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm  # type: ignore
    params = inspect.signature(sm).parameters
    kw = {}
    if "check_vma" in params:
        kw["check_vma"] = check
    elif "check_rep" in params:
        kw["check_rep"] = check
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

CallbackEnv = namedtuple(
    "CallbackEnv",
    [
        "model",
        "cvfolds",
        "iteration",
        "begin_iteration",
        "end_iteration",
        "rank",
        "evaluation_result_list",
    ],
)


class LegacyCallbackAdapter(TrainingCallback):
    """Wrap a function-style ``callback(env)`` into the class protocol."""

    def __init__(self, fn: Callable, end_iteration: int = 0):
        self.fn = fn
        self.end_iteration = end_iteration

    def _env(self, model, epoch: int, evals_log: dict) -> CallbackEnv:
        results = []
        for set_name, metric_dict in (evals_log or {}).items():
            for metric_name, values in metric_dict.items():
                if values:
                    results.append((f"{set_name}-{metric_name}", values[-1]))
        return CallbackEnv(
            model=model,
            cvfolds=None,
            iteration=epoch,
            begin_iteration=0,
            end_iteration=self.end_iteration,
            rank=0,
            evaluation_result_list=results,
        )

    def after_iteration(self, model, epoch: int, evals_log: dict) -> bool:
        try:
            self.fn(self._env(model, epoch, evals_log))
        except EarlyStopException:
            return True
        return False


class EarlyStopException(Exception):
    """Raised by legacy callbacks to stop training (xgboost<1.0 protocol)."""

    def __init__(self, best_iteration: int = 0):
        super().__init__()
        self.best_iteration = best_iteration


_HOOK_ATTRS = (
    "before_training",
    "after_training",
    "before_iteration",
    "after_iteration",
)


def wrap_callbacks(callbacks, num_boost_round: int):
    """Adapt any function-style entries to the TrainingCallback protocol.

    Objects exposing any of the four hook methods pass through unchanged
    (the training loop probes each hook with hasattr); bare callables are
    treated as legacy ``callback(env)`` functions.
    """
    wrapped = []
    for cb in callbacks or []:
        if any(hasattr(cb, attr) for attr in _HOOK_ATTRS):
            wrapped.append(cb)
        elif callable(cb):
            wrapped.append(LegacyCallbackAdapter(cb, end_iteration=num_boost_round))
        else:
            raise TypeError(f"Unsupported callback type: {type(cb)}")
    return wrapped
