"""Shared SPMD constants — the single source of truth for mesh axis names.

Every ``Mesh`` constructor, ``PartitionSpec`` and collective call site in the
package names the actor axis through :data:`AXIS_ACTORS` instead of a string
literal, so the axis name is declared exactly once. The static-analysis
layers consume the same declaration: ``tools/rxgblint``'s SPMD002 mesh-axis
catalog and ``tools/rxgbverify``'s jaxpr schedule checks both resolve
``AXIS_*`` constants from this module by AST (never importing it), which is
why the module must stay stdlib-only with plain string assignments at module
scope — no computed values, no imports that drag in jax.
"""

#: the data-parallel mesh axis: one slot per logical actor rank (the
#: TPU-native replacement for the reference's one-OS-process-per-actor
#: topology; see engine.py module docstring)
AXIS_ACTORS = "actors"

#: the feature-parallel mesh axis (``feature_parallel`` > 1): histogram
#: feature columns are partitioned over this axis so each chip builds and
#: allreduces only its [N/R, F/C] tile. Histograms psum over
#: :data:`AXIS_ACTORS` only; this axis carries the tiny per-node best-split
#: election gather and the winning feature's bin-column broadcast (see
#: ops/provider.py FeatureShard).
AXIS_FEATURES = "features"

#: synthesized per-row fill for an optional column absent on SOME shards
#: (or streamed chunks) while present on others — the ONE table consumed by
#: both the materialized concat (``engine._concat_shards``) and the
#: streamed ingest (``stream/ingest._concat_optional``), so the
#: streamed/materialized parity contract cannot drift column by column.
#: (qid is absent: its -1 fill is materialized-only — streamed qid gates.)
SHARD_COLUMN_FILLS = {
    "label": 0.0,
    "weight": 1.0,
    "base_margin": 0.0,
    "label_lower_bound": 0.0,
    "label_upper_bound": float("inf"),
}

__all__ = ["AXIS_ACTORS", "AXIS_FEATURES", "SHARD_COLUMN_FILLS"]
