"""Shared SPMD constants — the single source of truth for mesh axis names.

Every ``Mesh`` constructor, ``PartitionSpec`` and collective call site in the
package names the actor axis through :data:`AXIS_ACTORS` instead of a string
literal, so the axis name is declared exactly once. The static-analysis
layers consume the same declaration: ``tools/rxgblint``'s SPMD002 mesh-axis
catalog and ``tools/rxgbverify``'s jaxpr schedule checks both resolve
``AXIS_*`` constants from this module by AST (never importing it), which is
why the module must stay stdlib-only with plain string assignments at module
scope — no computed values, no imports that drag in jax.
"""

#: the 1D data-parallel mesh axis: one slot per logical actor rank (the
#: TPU-native replacement for the reference's one-OS-process-per-actor
#: topology; see engine.py module docstring)
AXIS_ACTORS = "actors"

__all__ = ["AXIS_ACTORS"]
