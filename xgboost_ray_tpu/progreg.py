"""Compiled-program registry: the abstract signatures of every SPMD program.

Every ``jax.jit``/shard_map program the package can produce (engine round
steps, the fused scan, dart, binning/sketch, the gblinear coordinate update,
serve predictor buckets, the booster's SPMD margin walk) registers
``(name, traceable fn, abstract arg signature, donate_argnums, meta)`` here,
so ``tools/rxgbverify`` can enumerate them and re-trace each one abstractly
(``jax.make_jaxpr`` — tracing only, no XLA compile, no execution) to check
collective schedules, precision flow, and recompile-drift fingerprints.

Capture is OFF by default and costs one early-returning branch per
registration site: production training/serving never records anything and
never retains program references (a record keeps the engine closure — and
with it device data — alive, which a long-running server must not do).
The verifier, the fingerprinting bench section, and the tests opt in via
:func:`capture`; registrations only happen while capture is enabled, so
callers must enable it BEFORE building engines/predictors.

Records are keyed by ``(name, meta, input signature)`` — re-building the
same program over the same shapes (the elastic engine-cache's grow-back
path) bumps ``registrations`` on the existing record instead of adding a
new one, which is what the no-silent-recompile test pins.
"""

import contextlib
import dataclasses
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

__all__ = [
    "ProgramRecord",
    "capture",
    "clear",
    "enabled",
    "note_jit_call",
    "records",
    "register_jit",
]

_lock = threading.Lock()
_capture = False
_records: "Dict[tuple, ProgramRecord]" = {}


def _aval(x) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)


@dataclasses.dataclass
class ProgramRecord:
    """One compiled program's abstract identity.

    ``fn`` is the UN-jitted traceable callable (``jax.jit(fn).__wrapped__``),
    ``abstract_args`` the pytree of ``ShapeDtypeStruct`` mirroring the real
    call site's arguments, ``meta`` the config coordinates the cross-world
    checks group by (``world`` plus grower/hist_quant/sampling), and
    ``source`` the ``(file, line)`` of the registration site — what SARIF
    annotations point at.
    """

    name: str
    fn: Callable
    abstract_args: Any
    donate_argnums: Tuple[int, ...]
    meta: Dict[str, Any]
    source: Tuple[str, int]
    registrations: int = 1

    def signature(self) -> Tuple[Tuple[Tuple[int, ...], str], ...]:
        flat, _ = jax.tree.flatten(self.abstract_args)
        return tuple((tuple(a.shape), str(a.dtype)) for a in flat)

    def meta_key(self) -> Tuple[Tuple[str, Any], ...]:
        return tuple(sorted(self.meta.items()))

    def key(self) -> tuple:
        return (self.name, self.meta_key(), self.signature())

    def jaxpr(self):
        """Abstract re-trace: the program's ClosedJaxpr (no execution)."""
        return jax.make_jaxpr(self.fn)(*self.abstract_args)


def enabled() -> bool:
    return _capture


@contextlib.contextmanager
def capture():
    """Enable registration for the scope (nesting-safe)."""
    global _capture
    with _lock:
        prev, _capture = _capture, True
    try:
        yield
    finally:
        with _lock:
            _capture = prev


def clear() -> None:
    with _lock:
        _records.clear()


def records() -> List[ProgramRecord]:
    with _lock:
        return list(_records.values())


def _record(
    name: str,
    fn: Callable,
    example_args: Any,
    donate_argnums: Tuple[int, ...],
    meta: Optional[Dict[str, Any]],
    depth: int,
) -> None:
    if callable(example_args) and not isinstance(example_args, tuple):
        example_args = example_args()
    frame = sys._getframe(depth)
    rec = ProgramRecord(
        name=name,
        fn=fn,
        abstract_args=jax.tree.map(_aval, tuple(example_args)),
        donate_argnums=tuple(donate_argnums),
        meta=dict(meta or {}),
        source=(frame.f_code.co_filename, frame.f_lineno),
    )
    key = rec.key()
    with _lock:
        existing = _records.get(key)
        if existing is not None:
            existing.registrations += 1
        else:
            _records[key] = rec


def register_jit(
    name: str,
    fn: Callable,
    *,
    example_args: Any = None,
    donate_argnums: Tuple[int, ...] = (),
    meta: Optional[Dict[str, Any]] = None,
):
    """``jax.jit(fn, donate_argnums=...)`` plus (capture-gated) registration.

    ``example_args`` is the real call site's argument tuple — or a thunk
    returning it, so building it (e.g. ``_eval_arrs()``) costs nothing when
    capture is off. Only shapes/dtypes are kept.
    """
    jitted = jax.jit(fn, donate_argnums=donate_argnums)
    if _capture and example_args is not None:
        _record(name, fn, example_args, donate_argnums, meta, depth=2)
    return jitted


def note_jit_call(
    name: str,
    jit_fn: Callable,
    args: Tuple[Any, ...],
    *,
    meta: Optional[Dict[str, Any]] = None,
) -> None:
    """Record an already-jitted program at its call site (for programs whose
    input shapes are only known per call, e.g. serve's padded buckets).
    No-op unless capture is enabled."""
    if not _capture:
        return
    fn = getattr(jit_fn, "__wrapped__", jit_fn)
    _record(name, fn, tuple(args), (), meta, depth=2)
