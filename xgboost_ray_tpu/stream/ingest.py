"""Two-pass streamed ingestion pipeline (the engine's streamed data plane).

Pass 1 (``sketch_pass``): each shard's chunk stream runs through a
deterministic :class:`~xgboost_ray_tpu.stream.sketch.StreamSketch` on the
host while the small per-row columns (label/weight/base_margin/bounds)
accumulate — the raw [N, F] float32 matrix never exists; peak memory is
O(chunk + sketch).

Cuts merge (``merged_cuts``): per-device merged summaries ride a shard_map
program with the SAME collective shape as the materialized sketch
(``pmin(min) → pmax(max) → psum(fine histogram) → psum(missing mass)``,
reusing ``ops/binning.py``'s grid and CDF readout) — registered under the
same ``engine.sketch_cuts`` program name so rxgbverify's schedule-identity
pass certifies streamed and materialized worlds execute identical
collective sequences.

Pass 2 (``bin_upload_pass``): chunks re-stream, bin on the host with the
vectorized ``bin_matrix_np`` straight into ``bin_dtype`` blocks, and a
:class:`~xgboost_ray_tpu.stream.upload.DoubleBufferedUploader` overlaps the
H2D transfer of each block part with the binning of the next chunk. Each
phase emits fenced spans (``data.sketch_chunk`` / ``data.cuts_merge`` /
``data.bin_chunk`` / ``data.h2d``), so a streamed load is reconstructible
from the timeline alone.
"""

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from xgboost_ray_tpu import obs, progreg
from xgboost_ray_tpu.compat import shard_map_compat as shard_map
from xgboost_ray_tpu.constants import AXIS_ACTORS, SHARD_COLUMN_FILLS
from xgboost_ray_tpu.ops import binning
from xgboost_ray_tpu.stream.reader import ShardStream
from xgboost_ray_tpu.stream.sketch import DEFAULT_EXPORT_CAPACITY, StreamSketch
from xgboost_ray_tpu.stream.upload import DoubleBufferedUploader


class PassOneResult:
    """Sketches + small columns of one streamed load's first pass."""

    def __init__(self):
        self.sketches: List[StreamSketch] = []
        self.shard_rows: List[int] = []
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.base_margin: Optional[np.ndarray] = None
        self.qid: Optional[np.ndarray] = None
        self.lower: Optional[np.ndarray] = None
        self.upper: Optional[np.ndarray] = None
        self.n_rows = 0
        self.n_features = 0
        self.sketch_s = 0.0
        self.wall_s = 0.0
        self.chunks = 0


def _concat_optional(parts: List[List[Optional[np.ndarray]]],
                     shard_rows: List[int],
                     fill: Optional[float]) -> Optional[np.ndarray]:
    """Concatenate a per-shard list of per-chunk optional columns with
    ``_concat_shards`` semantics: absent everywhere -> None; absent on some
    shards -> synthesized fill for those shards (None fill: zeros)."""
    present = [any(p is not None for p in shard) for shard in parts]
    if not any(present):
        return None
    out = []
    for shard, rows, has in zip(parts, shard_rows, present):
        if has:
            if any(p is None for p in shard):
                raise ValueError(
                    "a streamed column is present in some chunks of a shard "
                    "but not others"
                )
            out.append(np.concatenate([np.asarray(p, np.float32).ravel()
                                       for p in shard]))
        else:
            val = 0.0 if fill is None else fill
            out.append(np.full(rows, val, np.float32))
    return np.concatenate(out) if len(out) > 1 else out[0]




def apriori_sketch_bytes(
    streams: Sequence[ShardStream], n_features: int, cap: int
) -> int:
    """Summed a-priori sketch estimate across shards, per stream at the
    level count it will actually reach (levels ~ log2(rows/capacity),
    ceiling MAX_LEVELS) — a fixed small multiplier would let long streams
    outgrow the budget mid-pass with the fail-fast already passed. Summed
    because the driver holds EVERY shard's sketch concurrently through
    pass 1. Closed form: never allocates sketch-sized arrays itself."""
    from xgboost_ray_tpu.stream.sketch import MAX_LEVELS

    base_bytes = StreamSketch.level_nbytes(n_features, cap)
    return sum(
        base_bytes * min(
            MAX_LEVELS,
            max(1, (max(s.n_rows, 1) // max(cap, 1)).bit_length()) + 1,
        )
        for s in streams
    )


def export_summary_ceiling(n_features: int) -> int:
    """Ceiling on the per-device export-summary item count the cuts merge
    will use (the F-scaled cap in :func:`merged_cuts`) — shared with the
    budget model so the merge's stacked summaries are a charged term."""
    return (
        DEFAULT_EXPORT_CAPACITY if n_features <= 128
        else 2048 if n_features <= 1024 else 512
    )


def prevalidate_budget(
    streams: Sequence[ShardStream],
    block_rows: int,
    bin_itemsize: int,
    n_devices: int,
) -> None:
    """The FULL streaming-budget fail-fast, callable BEFORE any byte
    streams: every input — each shard's declared rows, the mesh block
    size, the bin dtype, the merge's summary ceiling — is known up front,
    so the N-scaling block-buffer and cuts-merge terms must not wait for
    the end of pass 1 (hours of I/O on a beyond-RAM load) to reject the
    config."""
    if not streams:
        return
    n_features = streams[0].n_features
    est = apriori_sketch_bytes(
        streams, n_features, streams[0].sketch_capacity
    )
    # stacked [n_devices, F, export_cap] f32 vals + wts summaries the cuts
    # merge holds on host before device_put
    merge_bytes = (
        n_devices * n_features * export_summary_ceiling(n_features) * 4 * 2
    )
    for s in streams:
        s.config.validate_budget(
            s.n_rows, s.n_features, s.chunk_rows, est,
            block_rows=block_rows, bin_itemsize=bin_itemsize,
            merge_bytes=merge_bytes,
        )


def sketch_pass(
    streams: Sequence[ShardStream],
    max_bin: int,
    cat_features: Sequence[int] = (),
) -> PassOneResult:
    """Pass 1: stream every shard once, building per-shard sketches and the
    small per-row columns."""
    tracer = obs.get_tracer()
    res = PassOneResult()
    res.n_features = streams[0].n_features
    # before any chunk validation indexes columns — the engine's shared
    # loud error, not a fork of it
    binning.validate_feature_types_count(cat_features, res.n_features)
    cap = streams[0].sketch_capacity
    for s in streams:
        if s.n_features != res.n_features:
            raise ValueError(
                f"streamed shards disagree on feature count "
                f"({s.n_features} vs {res.n_features})"
            )
        if s.sketch_capacity != cap:
            raise ValueError("streamed shards disagree on sketch capacity")
    wall0 = time.perf_counter()
    # "qid" is deliberately absent: the per-chunk gate below rejects it on
    # first sight, so collecting it would be dead plumbing
    cols: Dict[str, List[List[Optional[np.ndarray]]]] = {
        k: [] for k in ("label", "weight", "base_margin",
                        "label_lower_bound", "label_upper_bound")
    }
    est_sketch_total = apriori_sketch_bytes(streams, res.n_features, cap)
    for s in streams:
        s.config.validate_budget(
            s.n_rows, s.n_features, s.chunk_rows, est_sketch_total
        )
    for s in streams:
        sketch = StreamSketch(res.n_features, capacity=cap)
        shard_cols = {k: [] for k in cols}
        rows = 0
        for chunk in s.chunks():
            if chunk.get("qid") is not None:
                # gate on the FIRST qid-carrying chunk — a beyond-RAM load
                # must not stream to completion before learning its query
                # groups cannot be honored
                raise NotImplementedError(
                    "streamed ingestion does not support qid/ranking data "
                    "yet (query groups need a global contiguity sort the "
                    "chunk pipeline cannot do); materialize the matrix for "
                    "ranking."
                )
            x = np.asarray(chunk["data"], np.float32)
            binning.validate_categorical_codes(x, cat_features, max_bin)
            t0 = time.perf_counter()
            with tracer.span(
                "data.sketch_chunk", rows=int(x.shape[0]),
                shard=len(res.sketches),
            ):
                sketch.update(x, weight=chunk.get("weight"))
            res.sketch_s += time.perf_counter() - t0
            for k in shard_cols:
                shard_cols[k].append(chunk.get(k))
            rows += x.shape[0]
            res.chunks += 1
        if rows != s.n_rows:
            raise ValueError(
                f"stream produced {rows} rows but declared {s.n_rows}"
            )
        res.sketches.append(sketch)
        res.shard_rows.append(rows)
        for k in cols:
            cols[k].append(shard_cols[k])
    res.n_rows = sum(res.shard_rows)
    fills = SHARD_COLUMN_FILLS  # _concat_shards parity, one table
    res.label = _concat_optional(
        cols["label"], res.shard_rows, fill=fills["label"]
    )
    res.weight = _concat_optional(
        cols["weight"], res.shard_rows, fill=fills["weight"]
    )
    res.base_margin = _concat_optional(
        cols["base_margin"], res.shard_rows, fill=fills["base_margin"]
    )
    res.lower = _concat_optional(
        cols["label_lower_bound"], res.shard_rows,
        fill=fills["label_lower_bound"],
    )
    res.upper = _concat_optional(
        cols["label_upper_bound"], res.shard_rows,
        fill=fills["label_upper_bound"],
    )
    res.wall_s = time.perf_counter() - wall0
    return res


# ---------------------------------------------------------------------------
# cuts merge (device, same collective shape as the materialized sketch)
# ---------------------------------------------------------------------------


def merged_cuts(
    engine,
    pass1: PassOneResult,
) -> Tuple[jnp.ndarray, jnp.ndarray, np.ndarray, np.ndarray]:
    """Merge per-shard sketches into global cuts on the mesh.

    Shard sketches fold deterministically (rank order, round-robin over the
    ``n_devices`` mesh slots), export to fixed-shape summaries, and merge on
    device through pmin/pmax + histogram/missing psums — the materialized
    sketch program's exact collective schedule. Returns (cuts_dev [F, B-1],
    has_missing_dev [F] bool, cuts_np, rank_error_bound [F]).
    """
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    cat_features = engine._cat_features
    n_dev = engine.n_devices
    num_features = pass1.n_features
    with tracer.span("data.cuts_merge", world=n_dev) as span_attrs:
        groups: List[Optional[StreamSketch]] = [None] * n_dev
        for i, sk in enumerate(pass1.sketches):
            d = i % n_dev
            groups[d] = sk if groups[d] is None else groups[d].merge(sk)
        # export shape: tight power-of-two over the fullest group's live
        # items, capped by an F-scaled ceiling — the stacked [D, F, export]
        # summaries are the merge program's memory, so shipping mostly-inert
        # padding (or summaries far finer than the SKETCH_BINS grid they
        # rasterize onto) costs real RSS at wide F for no cut accuracy
        items_max = max(
            (g.item_count() for g in groups if g is not None), default=1
        )
        export_cap = min(
            export_summary_ceiling(num_features),
            max(256, 1 << (items_max - 1).bit_length()),
        )
        mns, mxs, valss, wtss, missws = [], [], [], [], []
        err = np.zeros(num_features, np.float64)
        for g in groups:
            if g is None:
                # inert empty summary, bitwise what an empty sketch exports
                # — without allocating its full [F, cap] level buffers
                mns.append(np.full(num_features, np.inf, np.float32))
                mxs.append(np.full(num_features, -np.inf, np.float32))
                valss.append(
                    np.full((num_features, export_cap), np.inf, np.float32)
                )
                wtss.append(
                    np.zeros((num_features, export_cap), np.float32)
                )
                missws.append(np.zeros(num_features, np.float32))
                continue
            vals, wts, g_err = g.export(export_cap)
            err += g_err
            mns.append(g.min)
            mxs.append(g.max)
            valss.append(vals)
            wtss.append(wts)
            missws.append(g.missing_weight.astype(np.float32))
        rows = NamedSharding(engine.mesh, P(AXIS_ACTORS))
        mn_dev = jax.device_put(np.stack(mns), rows)
        mx_dev = jax.device_put(np.stack(mxs), rows)
        vals_dev = jax.device_put(np.stack(valss), rows)
        wts_dev = jax.device_put(np.stack(wtss), rows)
        miss_dev = jax.device_put(np.stack(missws), rows)

        def fn(mn, mx, vals, wts, missw):
            mn = jax.lax.pmin(mn[0], AXIS_ACTORS)
            mx = jax.lax.pmax(mx[0], AXIS_ACTORS)
            hist = binning.sketch_histogram_items(vals[0], wts[0], mn, mx)
            hist = jax.lax.psum(hist, AXIS_ACTORS)
            cuts = binning.cuts_from_sketch(mn, mx, hist, max_bin)
            if cat_features:
                from xgboost_ray_tpu.ops.grow import cat_mask_const

                cat_mask = cat_mask_const(cat_features, num_features)
                code_cuts = jnp.arange(max_bin - 1, dtype=cuts.dtype) + 0.5
                cuts = jnp.where(cat_mask[:, None], code_cuts[None, :], cuts)
            miss = jax.lax.psum(missw[0], AXIS_ACTORS)
            return cuts, miss > 0

        mapped = shard_map(
            fn,
            mesh=engine.mesh,
            in_specs=(
                P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS),
                P(AXIS_ACTORS), P(AXIS_ACTORS),
            ),
            out_specs=(P(), P()),
        )
        jit_fn = progreg.register_jit(
            "engine.sketch_cuts",
            mapped,
            example_args=(mn_dev, mx_dev, vals_dev, wts_dev, miss_dev),
            meta=engine._program_meta(),
        )
        cuts_dev, has_missing = jit_fn(
            mn_dev, mx_dev, vals_dev, wts_dev, miss_dev
        )
        # the pipeline's ONE documented device->host read: pass 2 bins on
        # the host against these cuts
        cuts_np = np.asarray(cuts_dev)
        span_attrs["rank_error_bound_max"] = float(err.max(initial=0.0))
    return cuts_dev, has_missing, cuts_np, err


# ---------------------------------------------------------------------------
# pass 2: bin on host, double-buffered upload, on-device assembly
# ---------------------------------------------------------------------------


def _mesh_block_devices(engine) -> List[Tuple[Any, List[Any]]]:
    """Per row-block (primary device, replica devices): 1D meshes have no
    replicas; a 2D row x feature mesh replicates each row block over the
    feature axis."""
    dev = np.asarray(engine.mesh.devices)
    if dev.ndim == 1:
        return [(d, []) for d in dev.tolist()]
    return [(row[0], list(row[1:])) for row in dev.tolist()]


def bin_upload_pass(
    engine,
    streams: Sequence[ShardStream],
    cuts_np: np.ndarray,
    sketch_bytes: int = 0,
) -> Tuple[jnp.ndarray, Dict[str, float]]:
    """Pass 2: re-stream chunks, bin each on the host straight into the
    current device block's ``bin_dtype`` buffer, upload completed blocks
    double-buffered, assemble the [pad_to, F] row-sharded device matrix.

    Rows arrive in global row order, so exactly ONE per-actor block buffer
    is being filled at any time; a completed block hands off to the
    background uploader (one H2D transfer per device block — the device
    holds exactly the final binned bytes, no concat/update churn) while the
    next block's chunks bin on the main thread. Peak host memory:
    O(chunk + prefetch·block_bytes), with block_bytes = per-actor rows x F
    in bin_dtype (uint8/int16) — the "rows are born binned" buffer.

    Returns (bins_global, stats). Tail padding rows bin to the missing
    bucket — exactly where the materialized path's NaN-padded rows land, so
    a streamed matrix is indistinguishable downstream.
    """
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    dtype = binning.bin_dtype(max_bin)
    num_features = cuts_np.shape[0]
    pad_to = engine.pad_to
    block = pad_to // engine.n_devices
    block_devices = _mesh_block_devices(engine)
    prefetch = streams[0].config.prefetch
    # the full budget check: now that the mesh layout is known, the
    # N-scaling term (per-actor block buffers alive at once) is included
    streams[0].config.validate_budget(
        sum(s.n_rows for s in streams), num_features,
        max(s.chunk_rows for s in streams), sketch_bytes,
        block_rows=block, bin_itemsize=np.dtype(dtype).itemsize,
    )
    uploader = DoubleBufferedUploader(depth=prefetch, tracer=tracer)
    wall0 = time.perf_counter()
    bin_s = 0.0
    cursor = 0
    buf: Optional[np.ndarray] = None  # the block being filled

    def submit_rows(rows: np.ndarray) -> None:
        nonlocal cursor, buf
        pos = 0
        while pos < rows.shape[0]:
            b = cursor // block
            off = cursor - b * block
            if buf is None:
                buf = np.full((block, num_features), max_bin, dtype)
            take = min(block - off, rows.shape[0] - pos)
            buf[off : off + take] = rows[pos : pos + take]
            pos += take
            cursor += take
            if off + take == block:
                primary, replicas = block_devices[b]
                uploader.submit((b, 0), buf, primary)
                for ci, rdev in enumerate(replicas):
                    uploader.submit((b, ci + 1), buf, rdev)
                buf = None

    try:
        for si, s in enumerate(streams):
            for chunk in s.chunks():
                x = np.asarray(chunk["data"], np.float32)
                t0 = time.perf_counter()
                with tracer.span(
                    "data.bin_chunk", rows=int(x.shape[0]), shard=si
                ):
                    bins_chunk = binning.bin_matrix_np(x, cuts_np, max_bin)
                bin_s += time.perf_counter() - t0
                submit_rows(bins_chunk)
        if cursor < pad_to:
            # padding tail: the partially-filled block buffer already holds
            # the missing bucket in its unwritten rows; flush block by block
            while cursor < pad_to:
                b = cursor // block
                take = block * (b + 1) - cursor
                if buf is None:
                    buf = np.full((block, num_features), max_bin, dtype)
                cursor += take
                primary, replicas = block_devices[b]
                uploader.submit((b, 0), buf, primary)
                for ci, rdev in enumerate(replicas):
                    uploader.submit((b, ci + 1), buf, rdev)
                buf = None
        results = uploader.drain()
    finally:
        uploader.close()

    sharding = engine._row_sharding
    shape = (pad_to, num_features)
    per_device = {}
    for b, (primary, replicas) in enumerate(block_devices):
        for ci, dev in enumerate([primary] + replicas):
            per_device[dev] = results[(b, ci)]
    arrays = [
        per_device[d]
        for d, _idx in sharding.addressable_devices_indices_map(shape).items()
    ]
    bins_global = jax.make_array_from_single_device_arrays(
        shape, sharding, arrays
    )
    stats = dict(uploader.stats())
    stats.update({
        "bin_s": bin_s,
        "pass2_wall_s": time.perf_counter() - wall0,
    })
    return bins_global, stats
