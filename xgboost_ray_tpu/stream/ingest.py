"""Two-pass streamed ingestion pipeline (the engine's streamed data plane).

Pass 1 (``sketch_pass``): each shard's chunk stream runs through a
deterministic :class:`~xgboost_ray_tpu.stream.sketch.StreamSketch` on the
host while the small per-row columns (label/weight/base_margin/bounds)
accumulate — the raw [N, F] float32 matrix never exists; peak memory is
O(chunk + sketch).

Cuts merge (``merged_cuts``): per-device merged summaries ride a shard_map
program with the SAME collective shape as the materialized sketch
(``pmin(min) → pmax(max) → psum(fine histogram) → psum(missing mass)``,
reusing ``ops/binning.py``'s grid and CDF readout) — registered under the
same ``engine.sketch_cuts`` program name so rxgbverify's schedule-identity
pass certifies streamed and materialized worlds execute identical
collective sequences.

Pass 2 (``bin_upload_pass``): chunks re-stream, bin on the host with the
vectorized ``bin_matrix_np`` straight into ``bin_dtype`` blocks, and a
:class:`~xgboost_ray_tpu.stream.upload.DoubleBufferedUploader` overlaps the
H2D transfer of each block part with the binning of the next chunk. Each
phase emits fenced spans (``data.sketch_chunk`` / ``data.cuts_merge`` /
``data.bin_chunk`` / ``data.h2d``), so a streamed load is reconstructible
from the timeline alone.
"""

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from xgboost_ray_tpu import obs, progreg
from xgboost_ray_tpu.compat import shard_map_compat as shard_map
from xgboost_ray_tpu.constants import AXIS_ACTORS, SHARD_COLUMN_FILLS
from xgboost_ray_tpu.ops import binning
from xgboost_ray_tpu.stream.reader import ShardStream
from xgboost_ray_tpu.stream.sketch import DEFAULT_EXPORT_CAPACITY, StreamSketch
from xgboost_ray_tpu.stream.upload import DoubleBufferedUploader


class PassOneResult:
    """Sketches + small columns of one streamed load's first pass."""

    def __init__(self):
        self.sketches: List[StreamSketch] = []
        self.shard_rows: List[int] = []
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.base_margin: Optional[np.ndarray] = None
        self.qid: Optional[np.ndarray] = None
        self.lower: Optional[np.ndarray] = None
        self.upper: Optional[np.ndarray] = None
        self.n_rows = 0
        self.n_features = 0
        self.sketch_s = 0.0
        self.wall_s = 0.0
        self.chunks = 0


def _concat_optional(parts: List[List[Optional[np.ndarray]]],
                     shard_rows: List[int],
                     fill: Optional[float]) -> Optional[np.ndarray]:
    """Concatenate a per-shard list of per-chunk optional columns with
    ``_concat_shards`` semantics: absent everywhere -> None; absent on some
    shards -> synthesized fill for those shards (None fill: zeros)."""
    present = [any(p is not None for p in shard) for shard in parts]
    if not any(present):
        return None
    out = []
    for shard, rows, has in zip(parts, shard_rows, present):
        if has:
            if any(p is None for p in shard):
                raise ValueError(
                    "a streamed column is present in some chunks of a shard "
                    "but not others"
                )
            out.append(np.concatenate([np.asarray(p, np.float32).ravel()
                                       for p in shard]))
        else:
            val = 0.0 if fill is None else fill
            out.append(np.full(rows, val, np.float32))
    return np.concatenate(out) if len(out) > 1 else out[0]




def apriori_sketch_bytes(
    streams: Sequence[ShardStream], n_features: int, cap: int
) -> int:
    """Summed a-priori sketch estimate across shards, per stream at the
    level count it will actually reach (levels ~ log2(rows/capacity),
    ceiling MAX_LEVELS) — a fixed small multiplier would let long streams
    outgrow the budget mid-pass with the fail-fast already passed. Summed
    because the driver holds EVERY shard's sketch concurrently through
    pass 1. Closed form: never allocates sketch-sized arrays itself."""
    from xgboost_ray_tpu.stream.sketch import MAX_LEVELS

    base_bytes = StreamSketch.level_nbytes(n_features, cap)
    return sum(
        base_bytes * min(
            MAX_LEVELS,
            max(1, (max(s.n_rows, 1) // max(cap, 1)).bit_length()) + 1,
        )
        for s in streams
    )


def export_summary_ceiling(n_features: int) -> int:
    """Ceiling on the per-device export-summary item count the cuts merge
    will use (the F-scaled cap in :func:`merged_cuts`) — shared with the
    budget model so the merge's stacked summaries are a charged term."""
    return (
        DEFAULT_EXPORT_CAPACITY if n_features <= 128
        else 2048 if n_features <= 1024 else 512
    )


def prevalidate_budget(
    streams: Sequence[ShardStream],
    block_rows: int,
    bin_itemsize: int,
    n_devices: int,
) -> None:
    """The FULL streaming-budget fail-fast, callable BEFORE any byte
    streams: every input — each shard's declared rows, the mesh block
    size, the bin dtype, the merge's summary ceiling — is known up front,
    so the N-scaling block-buffer and cuts-merge terms must not wait for
    the end of pass 1 (hours of I/O on a beyond-RAM load) to reject the
    config."""
    if not streams:
        return
    n_features = streams[0].n_features
    est = apriori_sketch_bytes(
        streams, n_features, streams[0].sketch_capacity
    )
    # stacked [n_devices, F, export_cap] f32 vals + wts summaries the cuts
    # merge holds on host before device_put
    merge_bytes = (
        n_devices * n_features * export_summary_ceiling(n_features) * 4 * 2
    )
    for s in streams:
        s.config.validate_budget(
            s.n_rows, s.n_features, s.chunk_rows, est,
            block_rows=block_rows, bin_itemsize=bin_itemsize,
            merge_bytes=merge_bytes,
        )


def sketch_pass(
    streams: Sequence[ShardStream],
    max_bin: int,
    cat_features: Sequence[int] = (),
) -> PassOneResult:
    """Pass 1: stream every shard once, building per-shard sketches and the
    small per-row columns."""
    tracer = obs.get_tracer()
    res = PassOneResult()
    res.n_features = streams[0].n_features
    # before any chunk validation indexes columns — the engine's shared
    # loud error, not a fork of it
    binning.validate_feature_types_count(cat_features, res.n_features)
    cap = streams[0].sketch_capacity
    for s in streams:
        if s.n_features != res.n_features:
            raise ValueError(
                f"streamed shards disagree on feature count "
                f"({s.n_features} vs {res.n_features})"
            )
        if s.sketch_capacity != cap:
            raise ValueError("streamed shards disagree on sketch capacity")
    wall0 = time.perf_counter()
    # "qid" is deliberately absent: the per-chunk gate below rejects it on
    # first sight, so collecting it would be dead plumbing
    cols: Dict[str, List[List[Optional[np.ndarray]]]] = {
        k: [] for k in ("label", "weight", "base_margin",
                        "label_lower_bound", "label_upper_bound")
    }
    est_sketch_total = apriori_sketch_bytes(streams, res.n_features, cap)
    for s in streams:
        s.config.validate_budget(
            s.n_rows, s.n_features, s.chunk_rows, est_sketch_total
        )
    for s in streams:
        sketch = StreamSketch(res.n_features, capacity=cap)
        shard_cols = {k: [] for k in cols}
        rows = 0
        for chunk in s.chunks():
            if chunk.get("qid") is not None:
                # gate on the FIRST qid-carrying chunk — a beyond-RAM load
                # must not stream to completion before learning its query
                # groups cannot be honored
                raise NotImplementedError(
                    "streamed ingestion does not support qid/ranking data "
                    "yet (query groups need a global contiguity sort the "
                    "chunk pipeline cannot do); materialize the matrix for "
                    "ranking."
                )
            x = np.asarray(chunk["data"], np.float32)
            binning.validate_categorical_codes(x, cat_features, max_bin)
            t0 = time.perf_counter()
            with tracer.span(
                "data.sketch_chunk", rows=int(x.shape[0]),
                shard=len(res.sketches),
            ):
                sketch.update(x, weight=chunk.get("weight"))
            res.sketch_s += time.perf_counter() - t0
            for k in shard_cols:
                shard_cols[k].append(chunk.get(k))
            rows += x.shape[0]
            res.chunks += 1
        if rows != s.n_rows:
            raise ValueError(
                f"stream produced {rows} rows but declared {s.n_rows}"
            )
        res.sketches.append(sketch)
        res.shard_rows.append(rows)
        for k in cols:
            cols[k].append(shard_cols[k])
    res.n_rows = sum(res.shard_rows)
    fills = SHARD_COLUMN_FILLS  # _concat_shards parity, one table
    res.label = _concat_optional(
        cols["label"], res.shard_rows, fill=fills["label"]
    )
    res.weight = _concat_optional(
        cols["weight"], res.shard_rows, fill=fills["weight"]
    )
    res.base_margin = _concat_optional(
        cols["base_margin"], res.shard_rows, fill=fills["base_margin"]
    )
    res.lower = _concat_optional(
        cols["label_lower_bound"], res.shard_rows,
        fill=fills["label_lower_bound"],
    )
    res.upper = _concat_optional(
        cols["label_upper_bound"], res.shard_rows,
        fill=fills["label_upper_bound"],
    )
    res.wall_s = time.perf_counter() - wall0
    return res


# ---------------------------------------------------------------------------
# cuts merge (device, same collective shape as the materialized sketch)
# ---------------------------------------------------------------------------


def merged_cuts(
    engine,
    pass1: PassOneResult,
) -> Tuple[jnp.ndarray, jnp.ndarray, np.ndarray, np.ndarray]:
    """Merge per-shard sketches into global cuts on the mesh.

    Shard sketches fold deterministically (rank order, round-robin over the
    ``n_devices`` mesh slots), export to fixed-shape summaries, and merge on
    device through pmin/pmax + histogram/missing psums — the materialized
    sketch program's exact collective schedule. Returns (cuts_dev [F, B-1],
    has_missing_dev [F] bool, cuts_np, rank_error_bound [F]).
    """
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    cat_features = engine._cat_features
    n_dev = engine.n_devices
    num_features = pass1.n_features
    with tracer.span("data.cuts_merge", world=n_dev) as span_attrs:
        groups: List[Optional[StreamSketch]] = [None] * n_dev
        for i, sk in enumerate(pass1.sketches):
            d = i % n_dev
            groups[d] = sk if groups[d] is None else groups[d].merge(sk)
        # export shape: tight power-of-two over the fullest group's live
        # items, capped by an F-scaled ceiling — the stacked [D, F, export]
        # summaries are the merge program's memory, so shipping mostly-inert
        # padding (or summaries far finer than the SKETCH_BINS grid they
        # rasterize onto) costs real RSS at wide F for no cut accuracy
        items_max = max(
            (g.item_count() for g in groups if g is not None), default=1
        )
        export_cap = min(
            export_summary_ceiling(num_features),
            max(256, 1 << (items_max - 1).bit_length()),
        )
        mns, mxs, valss, wtss, missws = [], [], [], [], []
        err = np.zeros(num_features, np.float64)
        for g in groups:
            if g is None:
                # inert empty summary, bitwise what an empty sketch exports
                # — without allocating its full [F, cap] level buffers
                mns.append(np.full(num_features, np.inf, np.float32))
                mxs.append(np.full(num_features, -np.inf, np.float32))
                valss.append(
                    np.full((num_features, export_cap), np.inf, np.float32)
                )
                wtss.append(
                    np.zeros((num_features, export_cap), np.float32)
                )
                missws.append(np.zeros(num_features, np.float32))
                continue
            vals, wts, g_err = g.export(export_cap)
            err += g_err
            mns.append(g.min)
            mxs.append(g.max)
            valss.append(vals)
            wtss.append(wts)
            missws.append(g.missing_weight.astype(np.float32))
        rows = NamedSharding(engine.mesh, P(AXIS_ACTORS))
        mn_dev = jax.device_put(np.stack(mns), rows)
        mx_dev = jax.device_put(np.stack(mxs), rows)
        vals_dev = jax.device_put(np.stack(valss), rows)
        wts_dev = jax.device_put(np.stack(wtss), rows)
        miss_dev = jax.device_put(np.stack(missws), rows)

        def fn(mn, mx, vals, wts, missw):
            mn = jax.lax.pmin(mn[0], AXIS_ACTORS)
            mx = jax.lax.pmax(mx[0], AXIS_ACTORS)
            hist = binning.sketch_histogram_items(vals[0], wts[0], mn, mx)
            hist = jax.lax.psum(hist, AXIS_ACTORS)
            cuts = binning.cuts_from_sketch(mn, mx, hist, max_bin)
            if cat_features:
                from xgboost_ray_tpu.ops.grow import cat_mask_const

                cat_mask = cat_mask_const(cat_features, num_features)
                code_cuts = jnp.arange(max_bin - 1, dtype=cuts.dtype) + 0.5
                cuts = jnp.where(cat_mask[:, None], code_cuts[None, :], cuts)
            miss = jax.lax.psum(missw[0], AXIS_ACTORS)
            return cuts, miss > 0

        mapped = shard_map(
            fn,
            mesh=engine.mesh,
            in_specs=(
                P(AXIS_ACTORS), P(AXIS_ACTORS), P(AXIS_ACTORS),
                P(AXIS_ACTORS), P(AXIS_ACTORS),
            ),
            out_specs=(P(), P()),
        )
        jit_fn = progreg.register_jit(
            "engine.sketch_cuts",
            mapped,
            example_args=(mn_dev, mx_dev, vals_dev, wts_dev, miss_dev),
            meta=engine._program_meta(),
        )
        cuts_dev, has_missing = jit_fn(
            mn_dev, mx_dev, vals_dev, wts_dev, miss_dev
        )
        # the pipeline's ONE documented device->host read: pass 2 bins on
        # the host against these cuts
        cuts_np = np.asarray(cuts_dev)
        span_attrs["rank_error_bound_max"] = float(err.max(initial=0.0))
    return cuts_dev, has_missing, cuts_np, err


# ---------------------------------------------------------------------------
# pass 2: bin on host, double-buffered upload, on-device assembly
# ---------------------------------------------------------------------------


def _mesh_block_devices(engine) -> List[Tuple[Any, List[Any]]]:
    """Per row-block (primary device, replica devices): 1D meshes have no
    replicas; a 2D row x feature mesh replicates each row block over the
    feature axis."""
    dev = np.asarray(engine.mesh.devices)
    if dev.ndim == 1:
        return [(d, []) for d in dev.tolist()]
    return [(row[0], list(row[1:])) for row in dev.tolist()]


def _upload_blocks(
    engine,
    rows_iter,
    num_features: int,
    prefetch: int,
) -> Tuple[jnp.ndarray, Dict[str, float]]:
    """Shared block assembly of the streamed data plane: consume binned
    ``[k, F]`` row batches arriving in GLOBAL row order, fill the per-actor
    ``bin_dtype`` block buffers, upload completed blocks double-buffered,
    and assemble the [pad_to, F] row-sharded device matrix.

    Rows arrive in global row order, so exactly ONE per-actor block buffer
    is being filled at any time; a completed block hands off to the
    background uploader (one H2D transfer per device block — the device
    holds exactly the final binned bytes, no concat/update churn) while the
    next batch is produced on the main thread. Peak host memory:
    O(batch + prefetch·block_bytes). Tail padding rows bin to the missing
    bucket — exactly where the materialized path's NaN-padded rows land, so
    a streamed matrix is indistinguishable downstream.

    Consumed by :func:`bin_upload_pass` (batches = freshly binned chunks)
    and :func:`reuse_bin_pass` (batches = donor fetches + re-binned chunks
    of the one replacement shard).
    """
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    dtype = binning.bin_dtype(max_bin)
    pad_to = engine.pad_to
    block = pad_to // engine.n_devices
    block_devices = _mesh_block_devices(engine)
    uploader = DoubleBufferedUploader(depth=prefetch, tracer=tracer)
    cursor = 0
    buf: Optional[np.ndarray] = None  # the block being filled

    def submit_rows(rows: np.ndarray) -> None:
        nonlocal cursor, buf
        pos = 0
        while pos < rows.shape[0]:
            b = cursor // block
            off = cursor - b * block
            if buf is None:
                buf = np.full((block, num_features), max_bin, dtype)
            take = min(block - off, rows.shape[0] - pos)
            buf[off : off + take] = rows[pos : pos + take]
            pos += take
            cursor += take
            if off + take == block:
                primary, replicas = block_devices[b]
                uploader.submit((b, 0), buf, primary)
                for ci, rdev in enumerate(replicas):
                    uploader.submit((b, ci + 1), buf, rdev)
                buf = None

    try:
        for rows in rows_iter:
            submit_rows(np.asarray(rows, dtype))
        if cursor < pad_to:
            # padding tail: the partially-filled block buffer already holds
            # the missing bucket in its unwritten rows; flush block by block
            while cursor < pad_to:
                b = cursor // block
                take = block * (b + 1) - cursor
                if buf is None:
                    buf = np.full((block, num_features), max_bin, dtype)
                cursor += take
                primary, replicas = block_devices[b]
                uploader.submit((b, 0), buf, primary)
                for ci, rdev in enumerate(replicas):
                    uploader.submit((b, ci + 1), buf, rdev)
                buf = None
        results = uploader.drain()
    finally:
        uploader.close()

    sharding = engine._row_sharding
    shape = (pad_to, num_features)
    per_device = {}
    for b, (primary, replicas) in enumerate(block_devices):
        for ci, dev in enumerate([primary] + replicas):
            per_device[dev] = results[(b, ci)]
    arrays = [
        per_device[d]
        for d, _idx in sharding.addressable_devices_indices_map(shape).items()
    ]
    bins_global = jax.make_array_from_single_device_arrays(
        shape, sharding, arrays
    )
    return bins_global, dict(uploader.stats())


def bin_upload_pass(
    engine,
    streams: Sequence[ShardStream],
    cuts_np: np.ndarray,
    sketch_bytes: int = 0,
) -> Tuple[jnp.ndarray, Dict[str, float]]:
    """Pass 2: re-stream chunks, bin each on the host straight into the
    current device block's ``bin_dtype`` buffer, and assemble the device
    matrix through :func:`_upload_blocks` (one block buffer filling while
    the previous block's H2D transfer is in flight).

    Returns (bins_global, stats).
    """
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    dtype = binning.bin_dtype(max_bin)
    num_features = cuts_np.shape[0]
    block = engine.pad_to // engine.n_devices
    prefetch = streams[0].config.prefetch
    # the full budget check: now that the mesh layout is known, the
    # N-scaling term (per-actor block buffers alive at once) is included
    streams[0].config.validate_budget(
        sum(s.n_rows for s in streams), num_features,
        max(s.chunk_rows for s in streams), sketch_bytes,
        block_rows=block, bin_itemsize=np.dtype(dtype).itemsize,
    )
    wall0 = time.perf_counter()
    bin_state = {"bin_s": 0.0}

    def binned_chunks():
        for si, s in enumerate(streams):
            for chunk in s.chunks():
                x = np.asarray(chunk["data"], np.float32)
                t0 = time.perf_counter()
                with tracer.span(
                    "data.bin_chunk", rows=int(x.shape[0]), shard=si
                ):
                    bins_chunk = binning.bin_matrix_np(x, cuts_np, max_bin)
                bin_state["bin_s"] += time.perf_counter() - t0
                yield bins_chunk

    bins_global, stats = _upload_blocks(
        engine, binned_chunks(), num_features, prefetch
    )
    stats.update({
        "bin_s": bin_state["bin_s"],
        "pass2_wall_s": time.perf_counter() - wall0,
    })
    return bins_global, stats


# ---------------------------------------------------------------------------
# elastic continuation: seed a new world's binned matrix from a donor engine
# (zero re-sketch, zero re-stream of surviving shards)
# ---------------------------------------------------------------------------


def plan_stream_reuse(
    streams: Sequence[ShardStream], donor, max_bin: Optional[int] = None
) -> Optional[List[Tuple]]:
    """Map each of this load's shard streams onto ``donor``'s retained
    binned rows (an elastic shrink/grow of a streamed world).

    Returns a per-shard plan — ``("donor", lo, hi)`` for a shard whose
    binned rows (and small columns) live in the donor engine at donor-global
    rows [lo, hi), ``("stream", shard_stream)`` for a shard the donor never
    streamed (a grow-back onto a NEW replacement actor: that one shard
    re-streams and bins against the donor's FROZEN cuts) — or ``None`` when
    the donor cannot seed this load at all (not streamed, different
    feature count / binning, or no shard overlap), in which case the
    caller falls through to the full sketch+bin pipeline.

    Shard identity is the stream fingerprint (deterministic in source,
    rank window, and chunking — the same identity the driver's engine
    cache keys on), so a matching shard's binned rows are bitwise the rows
    a re-stream would produce under the donor's cuts.
    """
    if donor is None or not getattr(donor, "_streamed", False):
        return None
    fps = getattr(donor, "_stream_shard_fps", None)
    shard_rows = getattr(donor, "_stream_shard_rows", None)
    cuts_np = getattr(donor, "_stream_cuts_np", None)
    if not fps or not shard_rows or cuts_np is None:
        return None
    if any(s.n_features != donor.n_features for s in streams):
        return None
    if max_bin is not None and int(donor.params.max_bin) != int(max_bin):
        # frozen cuts are only valid at the binning they were sketched for
        # (unreachable from the elastic driver — params are fixed within a
        # run — but a direct TpuEngine(stream_donor=) caller could differ)
        return None
    offsets = np.concatenate([[0], np.cumsum(shard_rows)])
    by_fp = {fp: i for i, fp in enumerate(fps)}
    plan: List[Tuple] = []
    reused = 0
    for s in streams:
        i = by_fp.get(s.fingerprint())
        if i is None:
            plan.append(("stream", s))
        else:
            plan.append(("donor", int(offsets[i]), int(offsets[i + 1])))
            reused += 1
    if reused == 0:
        return None
    return plan


def prevalidate_reuse_budget(
    streams: Sequence[ShardStream],
    plan: Sequence[Tuple],
    block_rows: int,
    bin_itemsize: int,
) -> None:
    """Budget fail-fast for the reuse path, callable BEFORE any byte of a
    re-streamed replacement shard moves: the re-stream charges the same
    chunk+binned+block model as the original ingest, with the donor-fetch
    slice (one block of already-binned rows) standing in for the sketch
    term. Zero-restream plans (a pure shrink) still validate the block
    buffers — the uploader keeps them alive either way."""
    if not streams:
        return
    n_features = streams[0].n_features
    fetch_bytes = block_rows * n_features * bin_itemsize
    n_rows = sum(s.n_rows for s in streams)
    restreamed = [s for s, e in zip(streams, plan) if e[0] == "stream"]
    for s in streams:
        chunk = s.chunk_rows if s in restreamed else min(
            s.chunk_rows, block_rows
        )
        s.config.validate_budget(
            n_rows, n_features, chunk, fetch_bytes,
            block_rows=block_rows, bin_itemsize=bin_itemsize,
        )


def reuse_columns_pass(
    streams: Sequence[ShardStream],
    plan: Sequence[Tuple],
    donor,
    max_bin: int,
    cat_features: Sequence[int] = (),
) -> PassOneResult:
    """The reuse path's stand-in for :func:`sketch_pass`: small per-row
    columns come from donor slices for reused shards, and from ONE chunk
    iteration for re-streamed shards (no sketch is built — cuts are the
    donor's frozen ones, which is the whole point). The re-streamed
    shards' data chunks are read again by :func:`reuse_bin_pass` — a
    deliberate tradeoff: binning here would have to buffer the whole
    shard's binned rows on the host until the mesh layout exists (the
    columns feed the engine's row layout BEFORE the bin assembly runs),
    breaking the O(chunk + block) memory contract, so the one replacement
    shard pays the same two-read cost the original ingest pays per shard
    and host memory stays bounded."""
    tracer = obs.get_tracer()
    res = PassOneResult()
    res.n_features = streams[0].n_features
    binning.validate_feature_types_count(cat_features, res.n_features)
    wall0 = time.perf_counter()
    col_keys = ("label", "weight", "base_margin",
                "label_lower_bound", "label_upper_bound")
    donor_cols = getattr(donor, "_stream_cols", None) or {}
    # per-column, per-shard chunk lists in _concat_optional's shape: a
    # donor-sourced shard contributes its slice as one "chunk", so the
    # merge below rides the SAME fill/concat contract sketch_pass uses
    cols: Dict[str, List[List[Optional[np.ndarray]]]] = {
        k: [] for k in col_keys
    }
    for s, entry in zip(streams, plan):
        if entry[0] == "donor":
            _, lo, hi = entry
            for k in col_keys:
                col = donor_cols.get(k)
                cols[k].append([None if col is None else col[lo:hi]])
            res.shard_rows.append(hi - lo)
            continue
        shard_cols: Dict[str, List[Optional[np.ndarray]]] = {
            k: [] for k in col_keys
        }
        rows = 0
        for chunk in s.chunks():
            if chunk.get("qid") is not None:
                raise NotImplementedError(
                    "streamed ingestion does not support qid/ranking data"
                )
            x = np.asarray(chunk["data"], np.float32)
            binning.validate_categorical_codes(x, cat_features, max_bin)
            for k in col_keys:
                shard_cols[k].append(chunk.get(k))
            rows += x.shape[0]
            res.chunks += 1
        if rows != s.n_rows:
            raise ValueError(
                f"stream produced {rows} rows but declared {s.n_rows}"
            )
        res.shard_rows.append(rows)
        for k in col_keys:
            cols[k].append(shard_cols[k])
    res.n_rows = sum(res.shard_rows)
    fills = SHARD_COLUMN_FILLS
    res.label = _concat_optional(
        cols["label"], res.shard_rows, fill=fills["label"]
    )
    res.weight = _concat_optional(
        cols["weight"], res.shard_rows, fill=fills["weight"]
    )
    res.base_margin = _concat_optional(
        cols["base_margin"], res.shard_rows, fill=fills["base_margin"]
    )
    res.lower = _concat_optional(
        cols["label_lower_bound"], res.shard_rows,
        fill=fills["label_lower_bound"],
    )
    res.upper = _concat_optional(
        cols["label_upper_bound"], res.shard_rows,
        fill=fills["label_upper_bound"],
    )
    res.wall_s = time.perf_counter() - wall0
    tracer.event(
        "data.bin_reuse",
        attrs={
            "rows": int(res.n_rows),
            "reused_shards": sum(1 for e in plan if e[0] == "donor"),
            "restreamed_shards": sum(1 for e in plan if e[0] == "stream"),
        },
    )
    return res


def reuse_bin_pass(
    engine,
    streams: Sequence[ShardStream],
    plan: Sequence[Tuple],
    donor,
    cuts_np: np.ndarray,
) -> Tuple[jnp.ndarray, Dict[str, float]]:
    """Assemble the new world's [pad_to, F] binned device matrix without
    re-sketching and without re-streaming surviving shards.

    Donor-resident shards are fetched from the donor's DEVICE binned
    matrix in block-sized slices (already-binned bytes — no raw f32 ever
    exists, and peak host stays O(block)); a shard the donor never held
    (grow-back onto a new replacement actor) re-streams and bins against
    the donor's frozen cuts, prevalidated against the budget model before
    its first byte streams. Everything rides the same double-buffered
    uploader as the original ingest."""
    tracer = obs.get_tracer()
    max_bin = engine.params.max_bin
    dtype = binning.bin_dtype(max_bin)
    num_features = int(cuts_np.shape[0])
    block = engine.pad_to // engine.n_devices
    prefetch = streams[0].config.prefetch
    itemsize = np.dtype(dtype).itemsize
    # defensive re-check of the engine's up-front reuse prevalidation (the
    # mesh layout is authoritative here)
    prevalidate_reuse_budget(
        streams, plan, block_rows=block, bin_itemsize=itemsize
    )
    wall0 = time.perf_counter()
    state = {"bin_s": 0.0, "reused_rows": 0, "restreamed_rows": 0}
    donor_bins = donor.bins
    donor_f_real = donor.n_features  # donor tiles may be feature-padded

    def batches():
        for si, (s, entry) in enumerate(zip(streams, plan)):
            if entry[0] == "donor":
                _, lo, hi = entry
                for a in range(lo, hi, block):
                    b = min(a + block, hi)
                    with tracer.span(
                        "data.bin_reuse", rows=int(b - a), shard=si
                    ):
                        # device gather + one host read of binned bytes;
                        # slice away feature padding when the donor ran a
                        # 2D (feature-sharded) mesh
                        rows = np.asarray(donor_bins[a:b])[:, :donor_f_real]
                    state["reused_rows"] += b - a
                    yield rows
                continue
            for chunk in s.chunks():
                x = np.asarray(chunk["data"], np.float32)
                t0 = time.perf_counter()
                with tracer.span(
                    "data.bin_chunk", rows=int(x.shape[0]), shard=si
                ):
                    bins_chunk = binning.bin_matrix_np(x, cuts_np, max_bin)
                state["bin_s"] += time.perf_counter() - t0
                state["restreamed_rows"] += x.shape[0]
                yield bins_chunk

    bins_global, stats = _upload_blocks(
        engine, batches(), num_features, prefetch
    )
    stats.update({
        "bin_s": state["bin_s"],
        "pass2_wall_s": time.perf_counter() - wall0,
        "reused_rows": state["reused_rows"],
        "restreamed_rows": state["restreamed_rows"],
        "reused_shards": sum(1 for e in plan if e[0] == "donor"),
        "restreamed_shards": sum(1 for e in plan if e[0] == "stream"),
    })
    return bins_global, stats
