"""Chunked shard readers: the shard handle streamed ingestion consumes.

A streamed shard dict carries ``{"stream": ShardStream}`` instead of a raw
``{"data": ndarray}``; the engine's two-pass pipeline iterates
:meth:`ShardStream.chunks` twice (sketch pass, bin pass). Chunk sources:

* in-memory numpy arrays / DataFrames (``array_shard_stream`` /
  ``RayStreamingDMatrix`` central loading): chunks are row slices of data
  the caller already holds — streaming avoids the engine-side raw-f32
  device copy and full-shard sketch materialization, it does not copy the
  caller's array;
* ``.npy`` files: chunks are raw ``offset + count`` reads (no mmap, so no
  page-cache residue inflating RSS) — the numpy file reader of the budget
  tests;
* CSV files: ``pandas.read_csv(chunksize=...)``;
* Parquet files: ``pyarrow.ParquetFile.iter_batches`` (loudly gated when
  pyarrow is unavailable — a whole-file read would silently break the
  O(chunk) memory contract).

Every chunk is delivered as the same field dict the materialized loaders
produce (``data``/``label``/``weight``/``base_margin``/bounds), restricted
to this chunk's rows.
"""

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from xgboost_ray_tpu import faults

_MB = 1024 * 1024

#: fraction of the host budget the raw f32 chunk may occupy; the remainder
#: covers the sketch buffers, the binned chunk and the in-flight upload copy
_CHUNK_BUDGET_FRACTION = 0.25

_FIELD_KEYS = (
    "data", "label", "weight", "base_margin",
    "label_lower_bound", "label_upper_bound", "qid",
)


class StreamConfig:
    """Resolved streaming knobs (explicit args win over ``RXGB_STREAM_*``)."""

    def __init__(
        self,
        chunk_rows: Optional[int] = None,
        budget_mb: Optional[float] = None,
        sketch_capacity: Optional[int] = None,
        prefetch: Optional[int] = None,
    ):
        def _env(name, cast):
            raw = os.environ.get(name, "").strip()
            return cast(raw) if raw else None

        self.chunk_rows = chunk_rows if chunk_rows is not None else _env(
            "RXGB_STREAM_CHUNK_ROWS", int
        )
        self.budget_mb = budget_mb if budget_mb is not None else _env(
            "RXGB_STREAM_BUDGET_MB", float
        )
        self.sketch_capacity = (
            sketch_capacity if sketch_capacity is not None
            else _env("RXGB_STREAM_SKETCH_CAP", int)
        )
        if prefetch is None:
            prefetch = _env("RXGB_STREAM_PREFETCH", int)
        self.prefetch = 2 if prefetch is None else prefetch
        if self.chunk_rows is not None and self.chunk_rows < 1:
            raise ValueError("chunk_rows must be >= 1")
        if self.prefetch < 1:
            raise ValueError("prefetch must be >= 1")

    def resolve_chunk_rows(self, n_rows: int, n_features: int) -> int:
        """Rows per chunk: explicit, else derived from the budget (the
        row-scaled ingest terms take at most _CHUNK_BUDGET_FRACTION of
        it), else the whole shard (single chunk == the materialized fast
        path)."""
        if self.chunk_rows is not None:
            rows = self.chunk_rows
        elif self.budget_mb is not None:
            # the SAME per-row cost model validate_budget charges (f32
            # chunk + binned copy at a conservative 2-byte bin_dtype +
            # binning transients), with the fraction leaving room for the
            # sketch/block terms. No efficiency floor: inflating a tiny
            # budget's derived chunk would hand validate_budget a config
            # to reject over a knob the user never set.
            per_row = max(1, n_features) * (4 + 2 + 4 * 8)
            rows = int(self.budget_mb * _MB * _CHUNK_BUDGET_FRACTION / per_row)
        else:
            rows = max(n_rows, 1)
        return max(1, min(rows, max(n_rows, 1)))

    def resolve_sketch_capacity(self, n_features: int) -> int:
        """Per-level sketch buffer capacity: explicit (validated like
        StreamSketch's own constructor — silently rewriting a user knob
        would run a capacity they never configured), else sized down for
        very wide matrices so the sketch term of the memory model stays
        modest (the knob table in README documents the scaling)."""
        if self.sketch_capacity is not None:
            cap = int(self.sketch_capacity)
            if cap < 8 or cap % 2:
                raise ValueError(
                    f"sketch_capacity must be even and >= 8; got {cap}"
                )
            return cap
        return 2048 if n_features <= 512 else 512

    def validate_budget(self, n_rows: int, n_features: int,
                        chunk_rows: int, sketch_bytes: int,
                        block_rows: int = 0, bin_itemsize: int = 1,
                        merge_bytes: int = 0) -> None:
        """Fail fast when the configured streaming cannot fit the budget.

        Terms: the raw f32 chunk, its binned copy, the sketch buffers, and
        — when the caller knows the mesh layout (``block_rows`` > 0) — the
        per-actor bin_dtype block buffers the upload pipeline keeps alive
        (the one being filled plus up to ``prefetch`` queued/in-flight)
        and the cuts merge's stacked export summaries (``merge_bytes``);
        those are the terms that scale with N/world/F, so omitting them
        would pass configs that blow the budget after pass 1 already
        streamed the dataset.
        """
        if self.budget_mb is None:
            return
        from xgboost_ray_tpu.ops.binning import _BIN_BLOCK_ROWS

        chunk_bytes = chunk_rows * n_features * 4
        binned = chunk_rows * n_features * bin_itemsize
        blocks = (self.prefetch + 1) * block_rows * n_features * bin_itemsize
        # bin_matrix_np's flat-searchsorted transients: ~4 concurrent
        # int64-width row-block buffers (keys, offset keys, searchsorted
        # output, pre-cast bins) — the term that bites at wide F
        bin_transient = 4 * min(chunk_rows, _BIN_BLOCK_ROWS) * n_features * 8
        est = (chunk_bytes + binned + sketch_bytes + blocks + bin_transient
               + merge_bytes)
        budget = self.budget_mb * _MB
        if est > budget:
            raise ValueError(
                f"RXGB_STREAM_BUDGET_MB={self.budget_mb:g} cannot hold the "
                f"configured streaming: chunk({chunk_bytes}B) + binned chunk"
                f"({binned}B) + sketch({sketch_bytes}B) + block buffers"
                f"({blocks}B) + binning transients({bin_transient}B) + "
                f"cuts-merge summaries({merge_bytes}B) = {est}B. Lower "
                f"RXGB_STREAM_CHUNK_ROWS / RXGB_STREAM_SKETCH_CAP / "
                f"RXGB_STREAM_PREFETCH (or use more actors to shrink the "
                f"per-actor block), or raise the budget."
            )


class ShardStream:
    """One rank's chunked data source.

    ``chunk_fn(lo, hi)`` returns the field dict for rows [lo, hi) of this
    shard; ``n_rows``/``n_features`` are known up front (numpy shapes,
    parquet metadata, a one-off CSV line count) so the engine can lay out
    the global padded row space before any feature bytes stream.
    """

    def __init__(
        self,
        n_rows: int,
        n_features: int,
        chunk_fn: Callable[[int, int], Dict[str, Optional[np.ndarray]]],
        config: Optional[StreamConfig] = None,
        source_token: Any = None,
    ):
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self._chunk_fn = chunk_fn
        self.config = config or StreamConfig()
        self.chunk_rows = self.config.resolve_chunk_rows(self.n_rows, self.n_features)
        self.sketch_capacity = self.config.resolve_sketch_capacity(self.n_features)
        self.n_chunks = max(1, -(-self.n_rows // self.chunk_rows))
        self.source_token = source_token

    def chunks(self) -> Iterator[Dict[str, Optional[np.ndarray]]]:
        """Yield field dicts chunk by chunk (re-iterable: each call restarts
        from row 0 — the two-pass pipeline reads the stream twice)."""
        for lo in range(0, self.n_rows, self.chunk_rows):
            hi = min(lo + self.chunk_rows, self.n_rows)
            # chaos site: a scheduled raise/delay here models a failing or
            # straggling chunk source (disk, object store) at an exact,
            # reproducible chunk index — the streaming plane's analog of
            # actor.load_shard
            faults.fire(
                "stream.read_chunk",
                chunk=lo // self.chunk_rows,
                rows=hi - lo,
            )
            fields = self._chunk_fn(lo, hi)
            data = fields.get("data")
            if data is None or data.shape[0] != hi - lo:
                got = None if data is None else data.shape
                raise ValueError(
                    f"chunk reader returned {got} for rows [{lo}, {hi}) — "
                    f"row count drifted from the declared n_rows={self.n_rows}"
                )
            yield fields

    def fingerprint(self) -> tuple:
        """Cheap identity for the driver's engine cache (mirrors
        ``shard_layout_fingerprint`` semantics: matching fingerprints mean
        matching rows for deterministic loaders)."""
        return (
            "stream", self.n_rows, self.n_features, self.chunk_rows,
            repr(self.source_token),
        )


# ---------------------------------------------------------------------------
# shard-dict plumbing (what the engine and driver key off)
# ---------------------------------------------------------------------------


def is_streamed_shards(shards: Sequence[Dict[str, Any]]) -> bool:
    return any(isinstance(sh.get("stream"), ShardStream) for sh in shards)


def shard_streams(shards: Sequence[Dict[str, Any]]) -> Optional[List[ShardStream]]:
    """The per-shard streams, or None when no shard is streamed. Mixing
    streamed and materialized shards in one matrix is rejected loudly —
    per-rank loaders are uniform, so a mix means a wiring bug."""
    streamed = [sh for sh in shards if isinstance(sh.get("stream"), ShardStream)]
    if not streamed:
        return None
    if len(streamed) != len(shards):
        raise ValueError(
            f"{len(streamed)}/{len(shards)} shards are streamed: a matrix "
            f"must be entirely streamed or entirely materialized."
        )
    return [sh["stream"] for sh in shards]


def materialize_shard(shard: Dict[str, Any]) -> Dict[str, Optional[np.ndarray]]:
    """Collapse a single-chunk streamed shard into the materialized field
    dict — the degrade path that keeps a stream that fits in one chunk on
    the EXACT pre-streaming engine program (bitwise parity by construction)."""
    stream = shard["stream"]
    fields: Dict[str, List[np.ndarray]] = {}
    present: Dict[str, bool] = {}
    for chunk in stream.chunks():
        for key in _FIELD_KEYS:
            val = chunk.get(key)
            present[key] = present.get(key, False) or val is not None
            fields.setdefault(key, []).append(val)
    out: Dict[str, Optional[np.ndarray]] = {}
    for key in _FIELD_KEYS:
        if not present.get(key):
            out[key] = None
        else:
            parts = fields[key]
            if any(p is None for p in parts):
                raise ValueError(
                    f"field {key!r} present in some chunks but not others"
                )
            out[key] = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
    return out


# ---------------------------------------------------------------------------
# in-memory (numpy / pre-split fields) chunk source
# ---------------------------------------------------------------------------


def fields_shard_stream(
    fields: Dict[str, Optional[np.ndarray]],
    indices: Optional[np.ndarray] = None,
    config: Optional[StreamConfig] = None,
    source_token: Any = None,
) -> ShardStream:
    """Stream over already-split field arrays (the central-loading path):
    chunks are row slices of ``fields['data']`` restricted to ``indices``."""
    data = fields["data"]
    idx = None if indices is None else np.asarray(indices)
    n = data.shape[0] if idx is None else idx.shape[0]

    def chunk_fn(lo, hi):
        rows = slice(lo, hi) if idx is None else idx[lo:hi]
        return {
            k: (None if v is None else np.asarray(v)[rows])
            for k, v in fields.items() if k in _FIELD_KEYS
        }

    return ShardStream(
        n, data.shape[1], chunk_fn, config=config,
        source_token=source_token if source_token is not None
        else ("array", id(data), n),
    )


def array_shard_stream(
    x: np.ndarray,
    label: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    base_margin: Optional[np.ndarray] = None,
    label_lower_bound: Optional[np.ndarray] = None,
    label_upper_bound: Optional[np.ndarray] = None,
    chunk_rows: Optional[int] = None,
    config: Optional[StreamConfig] = None,
) -> Dict[str, Any]:
    """Wrap in-memory arrays as ONE streamed shard dict (the test/bench
    entry point for driving the engine's streamed branch directly)."""
    if config is None:
        config = StreamConfig(chunk_rows=chunk_rows)
    elif chunk_rows is not None:
        raise ValueError("pass chunk_rows inside config, not alongside it")
    fields = {
        "data": np.asarray(x),
        "label": label,
        "weight": weight,
        "base_margin": base_margin,
        "label_lower_bound": label_lower_bound,
        "label_upper_bound": label_upper_bound,
        "qid": None,
    }
    return {"stream": fields_shard_stream(fields, config=config)}


# ---------------------------------------------------------------------------
# file chunk sources
# ---------------------------------------------------------------------------


def _npy_header(path: str) -> Tuple[np.dtype, Tuple[int, ...], int]:
    """(dtype, shape, data offset) of a .npy file, without mapping it
    (public numpy.lib.format readers only — no private-API dependence)."""
    with open(path, "rb") as f:
        version = np.lib.format.read_magic(f)
        if version >= (2, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
        else:
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
        if fortran:
            raise ValueError(f"{path}: Fortran-order .npy is not chunk-readable")
        return dtype, shape, f.tell()


def npy_shard_stream(
    data_path: str,
    label_path: Optional[str] = None,
    weight_path: Optional[str] = None,
    config: Optional[StreamConfig] = None,
    row_range: Optional[Tuple[int, int]] = None,
) -> ShardStream:
    """Stream a [N, F] .npy feature file (plus optional [N] label/weight
    .npy files) via raw offset reads — touched bytes stay O(chunk).
    ``row_range`` restricts the stream to a contiguous [start, stop) row
    window (BATCH sharding of one file across ranks)."""
    dtype, shape, offset = _npy_header(data_path)
    if len(shape) != 2:
        raise ValueError(f"{data_path}: expected a 2-D [N, F] array, got {shape}")
    total_rows, num_features = shape
    n = total_rows
    start = 0
    if row_range is not None:
        start, stop = int(row_range[0]), int(row_range[1])
        if not 0 <= start <= stop <= total_rows:
            raise ValueError(f"row_range {row_range} outside [0, {total_rows}]")
        n = stop - start
    row_bytes = dtype.itemsize * num_features
    sides = {}
    for key, path in (("label", label_path), ("weight", weight_path)):
        if path is None:
            continue
        sdt, sshape, soff = _npy_header(path)
        if sshape[0] != total_rows:
            raise ValueError(
                f"{path}: row count {sshape[0]} != data rows {total_rows}"
            )
        width = int(np.prod(sshape[1:], dtype=np.int64)) or 1
        if width != 1:
            # a ravel()ed [N, k] side column would flow downstream as a
            # k*N-length array and die far from the cause (or silently
            # misalign) — reject the shape at header read
            raise ValueError(
                f"{path}: {key} side file must be 1-D [N] (or [N, 1]); "
                f"got shape {tuple(sshape)}"
            )
        sides[key] = (path, sdt, soff, 1)

    def read_rows(path, dt, off, width, lo, hi):
        count = (hi - lo) * width
        arr = np.fromfile(path, dtype=dt, count=count,
                          offset=off + lo * dt.itemsize * width)
        return arr.reshape(hi - lo, width) if width > 1 else arr

    def chunk_fn(lo, hi):
        lo, hi = lo + start, hi + start
        out: Dict[str, Optional[np.ndarray]] = {
            "data": np.fromfile(
                data_path, dtype=dtype, count=(hi - lo) * num_features,
                offset=offset + lo * row_bytes,
            ).reshape(hi - lo, num_features).astype(np.float32, copy=False)
        }
        for key, (path, sdt, soff, width) in sides.items():
            out[key] = read_rows(path, sdt, soff, width, lo, hi).astype(
                np.float32, copy=False
            ).ravel()
        return out

    return ShardStream(
        n, num_features, chunk_fn, config=config,
        source_token=("npy", os.path.abspath(data_path), label_path, start),
    )


def file_shard_stream(
    files: Sequence[str],
    split_fn: Callable[[Any], Dict[str, Optional[np.ndarray]]],
    filetype: str,
    config: Optional[StreamConfig] = None,
    read_kwargs: Optional[Dict[str, Any]] = None,
) -> ShardStream:
    """Stream one rank's CSV/Parquet file list. ``split_fn`` maps each chunk
    DataFrame through the matrix loader's column extraction (label/weight
    columns by name), so streamed file shards keep the exact materialized
    field semantics. Row counts come from parquet metadata / a one-off CSV
    newline count; per-file chunk iteration then honors ``chunk_rows``."""
    import pandas as pd

    files = list(files)
    kwargs = dict(read_kwargs or {})
    if filetype == "parquet" and kwargs:
        # the materialized path forwards these to pd.read_parquet; the
        # chunked pyarrow iter_batches path cannot honor arbitrary pandas
        # kwargs — silently ignoring them would train on different columns
        raise NotImplementedError(
            f"streamed parquet ingestion does not support read kwargs "
            f"{sorted(kwargs)}; drop them (use `ignore=` for column "
            f"exclusion) or materialize the matrix."
        )
    reserved = {"chunksize", "nrows", "iterator", "usecols"} & set(kwargs)
    if filetype == "csv" and reserved:
        # these collide with the chunk iteration / counting parse; the
        # materialized path accepts them, so fail loudly instead of
        # crashing mid-count or silently double-chunking
        raise NotImplementedError(
            f"streamed CSV ingestion does not support read kwargs "
            f"{sorted(reserved)} (they collide with the chunk iterator); "
            f"drop them or materialize the matrix."
        )
    if filetype == "parquet":
        try:
            import pyarrow.parquet as pq
        except ImportError as exc:  # pragma: no cover - env-dependent
            raise NotImplementedError(
                "streamed parquet ingestion requires pyarrow "
                "(ParquetFile.iter_batches); a pandas whole-file read would "
                "break the O(chunk) memory contract. Install pyarrow or "
                "convert to .npy/CSV."
            ) from exc
        counts = [pq.ParquetFile(f).metadata.num_rows for f in files]
    elif filetype == "csv":
        def count_rows(path):
            # a real (single-column) parse, not a raw newline count: files
            # without a trailing newline and quoted embedded newlines must
            # count exactly, or the stream silently drops/overruns rows
            rows = 0
            for chunk in pd.read_csv(path, usecols=[0], chunksize=1 << 18,
                                     **kwargs):
                rows += len(chunk)
            return rows

        counts = [count_rows(f) for f in files]
    else:
        raise ValueError(f"unsupported streamed filetype {filetype!r}")

    n = int(sum(counts))
    if filetype == "csv":
        first_frame = pd.read_csv(files[0], nrows=8, **kwargs)
    else:
        import pyarrow.parquet as pq

        first_frame = next(
            pq.ParquetFile(files[0]).iter_batches(batch_size=8)
        ).to_pandas()
    num_features = split_fn(first_frame)["data"].shape[1]
    del first_frame

    def iter_frames(chunk_rows):
        if filetype == "csv":
            for path in files:
                for df in pd.read_csv(path, chunksize=chunk_rows, **kwargs):
                    yield df
        else:
            import pyarrow.parquet as pq

            for path in files:
                pf = pq.ParquetFile(path)
                for batch in pf.iter_batches(batch_size=chunk_rows):
                    yield batch.to_pandas()

    class _FileChunks:
        """Sequential-window adapter: chunk_fn(lo, hi) calls must arrive in
        order from row 0 (the pipeline's contract); each fresh lo==0 call
        restarts the file iteration. File boundaries rarely align with the
        global chunk grid, so a leftover frame tail carries to the next
        window (still O(chunk) resident)."""

        def __init__(self):
            self._iter = None
            self._pos = 0
            self._tail = None  # leftover rows from the previous window

        def __call__(self, lo, hi):
            if lo == 0 or self._iter is None:
                self._iter = iter_frames(max(hi - lo, 1))
                self._pos = 0
                self._tail = None
            if lo != self._pos:
                raise ValueError(
                    f"streamed file chunks must be read sequentially "
                    f"(asked for {lo}, at {self._pos})"
                )
            need = hi - lo
            rows: List[Any] = []
            have = 0
            if self._tail is not None and len(self._tail):
                rows.append(self._tail)
                have = len(self._tail)
                self._tail = None
            while have < need:
                df = next(self._iter)
                rows.append(df)
                have += len(df)
            frame = rows[0] if len(rows) == 1 else pd.concat(rows, ignore_index=True)
            if have > need:
                self._tail = frame.iloc[need:]
                frame = frame.iloc[:need]
            self._pos = hi
            return split_fn(frame)

    return ShardStream(
        n, num_features, _FileChunks(), config=config,
        source_token=(filetype, tuple(os.path.abspath(f) for f in files)),
    )
