"""Double-buffered host→device uploader for streamed ingestion.

One background worker drains a bounded queue of (key, host array, device)
transfers so chunk binning on the main thread overlaps the H2D copy of the
previous chunk. ``depth`` bounds the host copies alive at once: the chunk
being binned plus ``depth`` queued/in-flight uploads — depth=2 is classic
double buffering, and ``submit`` blocking on a full queue is the
backpressure that keeps peak host memory O(chunk).

Every transfer is recorded as a fenced ``data.h2d`` span on the tracer the
uploader was constructed with (captured on the TRAINING thread — the worker
must not fall back to the process-default tracer and lose the spans from
the run's timeline).

Concurrency: every shared attribute is guarded by ``self._cond``'s lock
(rxgblint LOCK001 enforces this statically; the rxgbrace
``stream_upload_double_buffer`` scenario explores the schedule space).
"""

import collections
import threading
import time
from typing import Any, Callable, Dict, Optional

from xgboost_ray_tpu import faults


def _device_transfer(array, device):
    """Default transfer: committed device_put, fenced so the recorded span
    covers the actual copy (module-level indirection so tests and the race
    scenario can stub the jax dependency)."""
    import jax

    out = array if device is None else jax.device_put(array, device)
    return getattr(out, "block_until_ready", lambda: out)()


class DoubleBufferedUploader:
    """Bounded-queue background H2D uploader (see module docstring)."""

    def __init__(
        self,
        depth: int = 2,
        transfer: Optional[Callable[[Any, Any], Any]] = None,
        tracer=None,
    ):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.depth = int(depth)
        self._transfer = transfer or _device_transfer
        self._tracer = tracer
        self._cond = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._results: Dict[Any, Any] = {}
        self._inflight = 0
        self._submitted = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._transfer_s = 0.0
        self._bytes = 0
        self._thread = threading.Thread(
            target=self._worker, name="rxgb-stream-h2d"
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, key, array, device) -> None:
        """Queue one transfer; blocks while ``depth`` uploads are already
        queued or in flight (the double-buffer backpressure)."""
        # chaos site: fired on the SUBMITTING (training) thread, before the
        # hand-off (and before the lock — a plan-injected delay must model a
        # stalled H2D pipe, not wedge the worker out of the condition), so
        # an injected raise surfaces exactly where a real upload failure
        # does (drain() re-raises worker errors there too). The k-th
        # occurrence IS the k-th submitted transfer.
        faults.fire(
            "stream.h2d_upload",
            bytes=int(getattr(array, "nbytes", 0)),
        )
        with self._cond:
            while (
                len(self._pending) + self._inflight >= self.depth
                and self._error is None
                and not self._closed
            ):
                self._cond.wait()
            if self._error is not None:
                raise RuntimeError("uploader failed") from self._error
            if self._closed:
                raise RuntimeError("uploader is closed")
            self._pending.append((key, array, device))
            self._submitted += 1
            self._cond.notify_all()

    def drain(self) -> Dict[Any, Any]:
        """Wait for every queued transfer; returns {key: device array}.
        Re-raises the first worker error."""
        with self._cond:
            while (self._pending or self._inflight) and self._error is None:
                self._cond.wait()
            if self._error is not None:
                raise RuntimeError("uploader failed") from self._error
            return dict(self._results)

    def close(self) -> None:
        """Drain-free shutdown: stop the worker and join it. Safe to call
        multiple times; pending transfers are abandoned."""
        with self._cond:
            self._closed = True
            self._pending.clear()
            self._cond.notify_all()
        self._thread.join()

    def stats(self) -> Dict[str, float]:
        with self._cond:
            return {
                "transfers": len(self._results),
                "submitted": self._submitted,
                "transfer_s": self._transfer_s,
                "bytes": float(self._bytes),
            }

    # -- worker side ---------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                key, array, device = self._pending.popleft()
                self._inflight += 1
                self._cond.notify_all()
            ts = time.time()
            t0 = time.perf_counter()
            try:
                out = self._transfer(array, device)
                dur = time.perf_counter() - t0
                nbytes = int(getattr(array, "nbytes", 0))
                if self._tracer is not None:
                    self._tracer.add_span(
                        "data.h2d", ts, dur,
                        attrs={"bytes": nbytes, "device": str(device)},
                    )
                with self._cond:
                    self._results[key] = out
                    self._transfer_s += dur
                    self._bytes += nbytes
                    self._inflight -= 1
                    self._cond.notify_all()
            except BaseException as exc:  # noqa: BLE001 - surfaced at drain()
                with self._cond:
                    self._error = exc
                    self._inflight -= 1
                    self._cond.notify_all()
                return
