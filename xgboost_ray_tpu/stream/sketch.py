"""Mergeable deterministic weight-aware quantile sketch (KLL-style).

The streaming counterpart of the in-memory histogram-CDF sketch in
``ops/binning.py``: where that sketch needs the full shard on device (global
min/max before the fine histogram), this one ingests a row stream chunk by
chunk on the host in O(capacity · levels) memory per feature and merges
associatively — across chunks (trivially: the state is a function of the row
prefix only, so ANY chunking of the same rows yields the bitwise-same
summary) and across actors (explicit :meth:`StreamSketch.merge`, driver
merges in rank order).

Structure (vectorized over features; all buffers are ``[F, capacity]``):

* level buffers of (value, weight) items; rows insert at level 0;
* a full level is *compacted*: items sorted by value (stable), then
  ``capacity/2`` equi-weight representatives are selected at deterministic
  targets ``(j + offset) * T / S`` (offset alternates 0.25/0.75 per
  compaction so consecutive rank perturbations cancel in practice), each
  carrying weight ``T / S``; survivors push into the next level;
* missing values (NaN) enter as ``(+inf, 0)`` placeholders so every row
  advances the shared fill counter (keeping the state fully vectorized);
  their real weight is tracked per feature in ``missing_weight``.

Rank-error certificate
----------------------
One compaction replaces the buffer's cumulative-weight function by a step
function with steps of ``T/S``, perturbing any rank query by at most
``T/S``. Every performed compaction adds its ``T/S`` to ``_err`` — so at
readout, for every value v, ``|C_sketch(v) - C_true(v)| <= _err[f]``, and a
quantile read off the summary is within ``rank_error_bound()`` (the
certificate plus one item weight of readout resolution) of the true rank.
The bound is computed from the compactions that actually happened, not a
worst-case formula, and is pinned against exact quantiles by
``tests/test_streaming.py``.
"""

from typing import List, Optional, Tuple

import numpy as np

#: default per-level buffer capacity (items per feature per level); the
#: certificate scales as O(levels · N / capacity) worst case, far better in
#: practice thanks to the alternating compaction offsets
DEFAULT_CAPACITY = 2048

#: level count ceiling: compacting the top level re-inserts survivors into
#: itself, bounding memory at O(MAX_LEVELS · capacity · F) while the error
#: certificate keeps accounting for every extra compaction honestly
MAX_LEVELS = 12

#: exported summary size (items per feature) for the fixed-shape device
#: merge; a fuller sketch equi-weight-compacts down to this on export
DEFAULT_EXPORT_CAPACITY = 4096


class _Level:
    """One level's (value, weight) buffer, [F, capacity]."""

    __slots__ = ("vals", "wts", "n", "compactions")

    def __init__(self, n_features: int, capacity: int):
        self.vals = np.full((n_features, capacity), np.inf, np.float32)
        self.wts = np.zeros((n_features, capacity), np.float64)
        self.n = 0  # filled item count (shared across features)
        self.compactions = 0  # drives the alternating selection offset


def _flat_searchsorted(z: np.ndarray, targets: np.ndarray) -> np.ndarray:
    """Per-feature searchsorted in ONE flat call.

    ``z`` [F, m] is per-feature non-decreasing with values in [0, 1];
    ``targets`` [F, k] likewise in (0, 1). Keys offset each feature by 2·f
    (z stays within [0, 1] ⊂ [0, 2), so feature blocks never interleave).
    Returns per-feature left-insertion indices [F, k] in [0, m].
    """
    num_features, m = z.shape
    base = (np.arange(num_features, dtype=np.float64) * 2.0)[:, None]
    idx = np.searchsorted(
        (base + z).ravel(), (base + targets).ravel(), side="left"
    ).reshape(targets.shape)
    return idx - np.arange(num_features, dtype=np.int64)[:, None] * m


class StreamSketch:
    """Deterministic mergeable per-feature quantile sketch."""

    def __init__(
        self,
        n_features: int,
        capacity: Optional[int] = None,
        export_capacity: Optional[int] = None,
    ):
        if n_features < 1:
            raise ValueError("n_features must be >= 1")
        cap = int(capacity or DEFAULT_CAPACITY)
        if cap < 8 or cap % 2:
            raise ValueError(f"capacity must be even and >= 8; got {cap}")
        self.n_features = int(n_features)
        self.capacity = cap
        self.export_capacity = int(export_capacity or DEFAULT_EXPORT_CAPACITY)
        self.levels: List[_Level] = [_Level(self.n_features, cap)]
        self.min = np.full(n_features, np.inf, np.float32)
        self.max = np.full(n_features, -np.inf, np.float32)
        self.total_weight = np.zeros(n_features, np.float64)  # finite rows
        self.missing_weight = np.zeros(n_features, np.float64)
        self.n_rows = 0
        self._err = np.zeros(n_features, np.float64)

    # -- ingestion -----------------------------------------------------------

    def update(self, x: np.ndarray, weight: Optional[np.ndarray] = None) -> None:
        """Insert one chunk of rows. ``x`` [n, F] float; ``weight`` [n] or
        None (unit weights). Rows insert in order — chunk boundaries leave
        no trace in the state, which is what makes any chunking of the same
        row stream produce the bitwise-identical sketch."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[1] != self.n_features:
            raise ValueError(
                f"expected [n, {self.n_features}] chunk, got {x.shape}"
            )
        n = x.shape[0]
        if n == 0:
            return
        if weight is None:
            w = np.ones(n, np.float64)
        else:
            w = np.asarray(weight, np.float64).ravel()
            if w.shape[0] != n:
                raise ValueError("weight length does not match chunk rows")
            if (w < 0).any():
                raise ValueError("sketch weights must be non-negative")
        nan = np.isnan(x)
        finite_w = np.where(nan, 0.0, w[:, None])  # [n, F]
        self.total_weight += finite_w.sum(axis=0)
        self.missing_weight += (np.where(nan, w[:, None], 0.0)).sum(axis=0)
        with np.errstate(invalid="ignore"):
            self.min = np.fmin(self.min, np.min(np.where(nan, np.inf, x), axis=0))
            self.max = np.fmax(self.max, np.max(np.where(nan, -np.inf, x), axis=0))
        self.n_rows += n

        vals = np.where(nan, np.float32(np.inf), x)  # [n, F]
        lvl0 = self.levels[0]
        pos = 0
        while pos < n:
            take = min(self.capacity - lvl0.n, n - pos)
            sl = slice(pos, pos + take)
            lvl0.vals[:, lvl0.n : lvl0.n + take] = vals[sl].T
            lvl0.wts[:, lvl0.n : lvl0.n + take] = finite_w[sl].T
            lvl0.n += take
            pos += take
            if lvl0.n == self.capacity:
                self._compact(0)

    # -- compaction ----------------------------------------------------------

    def _select_equiweight(
        self,
        vals: np.ndarray,
        wts: np.ndarray,
        n_out: int,
        offset: float,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Equi-weight representatives of sorted-by-value item buffers.

        Returns (values [F, n_out] f32, weights [F, n_out] f64, per-feature
        rank-error contribution [F] f64). Features with zero total weight
        (everything missing so far) yield (+inf, 0) placeholders.
        """
        order = np.argsort(vals, axis=1, kind="stable")
        sv = np.take_along_axis(vals, order, axis=1)
        sw = np.take_along_axis(wts, order, axis=1)
        cw = np.cumsum(sw, axis=1)
        total = cw[:, -1]
        has_mass = total > 0
        safe_total = np.where(has_mass, total, 1.0)
        z = cw / safe_total[:, None]
        targets = (np.arange(n_out, dtype=np.float64) + offset)[None, :] / n_out
        idx = np.clip(_flat_searchsorted(z, np.broadcast_to(
            targets, (vals.shape[0], n_out)
        )), 0, vals.shape[1] - 1)
        out_vals = np.take_along_axis(sv, idx, axis=1)
        out_w = np.broadcast_to((total / n_out)[:, None], out_vals.shape)
        out_vals = np.where(has_mass[:, None], out_vals, np.float32(np.inf))
        out_w = np.where(has_mass[:, None], out_w, 0.0)
        err = np.where(has_mass, total / n_out, 0.0)
        return out_vals.astype(np.float32), out_w, err

    def _compact(self, level: int) -> None:
        lvl = self.levels[level]
        half = self.capacity // 2
        offset = 0.25 if lvl.compactions % 2 == 0 else 0.75
        lvl.compactions += 1
        out_vals, out_w, err = self._select_equiweight(
            lvl.vals[:, : lvl.n], lvl.wts[:, : lvl.n], half, offset
        )
        self._err += err
        lvl.vals[:] = np.inf
        lvl.wts[:] = 0.0
        lvl.n = 0
        # promote survivors; the top level compacts into itself (bounded
        # memory, honestly accounted error)
        dest_idx = level + 1
        if dest_idx >= MAX_LEVELS:
            dest_idx = level
        if dest_idx == len(self.levels):
            self.levels.append(_Level(self.n_features, self.capacity))
        self._insert_items(dest_idx, out_vals, out_w)

    def _insert_items(self, level: int, vals: np.ndarray, wts: np.ndarray) -> None:
        """Append pre-weighted items into ``level`` (in column order),
        compacting on fill."""
        lvl = self.levels[level]
        m = vals.shape[1]
        pos = 0
        while pos < m:
            take = min(self.capacity - lvl.n, m - pos)
            lvl.vals[:, lvl.n : lvl.n + take] = vals[:, pos : pos + take]
            lvl.wts[:, lvl.n : lvl.n + take] = wts[:, pos : pos + take]
            lvl.n += take
            pos += take
            if lvl.n == self.capacity:
                self._compact(level)

    # -- merge ---------------------------------------------------------------

    def merge(self, other: "StreamSketch") -> "StreamSketch":
        """Fold ``other`` into this sketch (in place; returns self).

        Level-aligned item insertion: deterministic given the two operands'
        states, so a fixed merge order (the driver merges in rank order)
        yields a fully deterministic result. Error certificates add."""
        if other.n_features != self.n_features:
            raise ValueError("cannot merge sketches over different feature counts")
        if other.capacity != self.capacity:
            raise ValueError("cannot merge sketches with different capacities")
        self.min = np.fmin(self.min, other.min)
        self.max = np.fmax(self.max, other.max)
        self.total_weight += other.total_weight
        self.missing_weight += other.missing_weight
        self.n_rows += other.n_rows
        self._err += other._err
        for li, lvl in enumerate(other.levels):
            if lvl.n:
                dest = min(li, MAX_LEVELS - 1)
                while dest >= len(self.levels):
                    self.levels.append(_Level(self.n_features, self.capacity))
                self._insert_items(dest, lvl.vals[:, : lvl.n], lvl.wts[:, : lvl.n])
        return self

    # -- readout -------------------------------------------------------------

    def item_count(self) -> int:
        """Live summary items per feature (drives the export shape)."""
        return max(1, sum(lvl.n for lvl in self.levels))

    def _all_items(self) -> Tuple[np.ndarray, np.ndarray]:
        """Every live item, level order: ([F, m] values, [F, m] weights)."""
        parts_v = [lvl.vals[:, : lvl.n] for lvl in self.levels if lvl.n]
        parts_w = [lvl.wts[:, : lvl.n] for lvl in self.levels if lvl.n]
        if not parts_v:
            return (
                np.full((self.n_features, 1), np.inf, np.float32),
                np.zeros((self.n_features, 1), np.float64),
            )
        return np.concatenate(parts_v, axis=1), np.concatenate(parts_w, axis=1)

    def export(
        self, capacity: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Fixed-shape summary for the device merge: (values [F, cap] f32,
        weights [F, cap] f32, rank-error bound [F] f64 including any export
        compaction). Unused slots hold (+inf, 0) — weightless, so they are
        inert under the rasterizing scatter-add."""
        cap = int(capacity or self.export_capacity)
        vals, wts = self._all_items()
        err = self._err.copy()
        if vals.shape[1] > cap:
            vals, wts, extra = self._select_equiweight(vals, wts, cap, 0.5)
            err += extra
        pad = cap - vals.shape[1]
        if pad:
            vals = np.concatenate(
                [vals, np.full((self.n_features, pad), np.inf, np.float32)], axis=1
            )
            wts = np.concatenate(
                [wts, np.zeros((self.n_features, pad), np.float64)], axis=1
            )
        return vals.astype(np.float32), wts.astype(np.float32), err

    def quantiles(self, qs: np.ndarray) -> np.ndarray:
        """Estimated per-feature quantile values [F, len(qs)] over the
        non-missing mass (host readout; the training cuts instead go through
        the device rasterized merge for schedule parity)."""
        qs = np.asarray(qs, np.float64).ravel()
        vals, wts = self._all_items()
        order = np.argsort(vals, axis=1, kind="stable")
        sv = np.take_along_axis(vals, order, axis=1)
        sw = np.take_along_axis(wts, order, axis=1)
        cw = np.cumsum(sw, axis=1)
        total = cw[:, -1]
        has_mass = total > 0
        z = cw / np.where(has_mass, total, 1.0)[:, None]
        idx = np.clip(
            _flat_searchsorted(z, np.broadcast_to(qs[None, :], (self.n_features, qs.size))),
            0, sv.shape[1] - 1,
        )
        out = np.take_along_axis(sv, idx, axis=1)
        return np.where(has_mass[:, None], out, np.float32(0.0)).astype(np.float32)

    def rank_error_bound(self) -> np.ndarray:
        """Per-feature certified rank-error bound (absolute weight units) of
        a quantile read off this sketch: the accumulated compaction
        certificate plus one item weight of readout resolution."""
        _, wts = self._all_items()
        return self._err + wts.max(axis=1)

    def memory_bytes(self) -> int:
        """Current buffer footprint (the ``sketch`` term of the streaming
        memory model)."""
        return sum(lvl.vals.nbytes + lvl.wts.nbytes for lvl in self.levels)

    @staticmethod
    def level_nbytes(n_features: int, capacity: int) -> int:
        """Bytes of ONE level's buffers (f32 values + f64 weights) — the
        closed form of a fresh sketch's :meth:`memory_bytes`, for budget
        estimates that must not themselves allocate sketch-sized arrays."""
        return n_features * capacity * (4 + 8)
