"""Out-of-core streaming data plane: train from data that never fully
materializes as a raw float32 matrix.

The reference's L4 layer (``matrix.py`` + 10 pluggable data sources) exists
so beyond-RAM data can stream in shards; this package is the TPU-native
equivalent:

* :mod:`xgboost_ray_tpu.stream.sketch` — a mergeable, deterministic,
  weight-aware KLL-style per-feature quantile sketch updated chunk by chunk
  on the host. Same rows in the same order produce the bitwise-same summary
  for ANY chunking, and every compaction's rank perturbation is accumulated
  into a runtime error certificate.
* :mod:`xgboost_ray_tpu.stream.reader` — chunked readers
  (numpy arrays, ``.npy`` files, CSV, Parquet) wrapped as ``ShardStream``
  objects: the shard handle the engine ingests instead of a raw array.
* :mod:`xgboost_ray_tpu.stream.upload` — the double-buffered host→device
  uploader: chunk binning on the host overlaps the H2D transfer of the
  previous chunk.
* :mod:`xgboost_ray_tpu.stream.ingest` — the two-pass sketch→bin pipeline
  the engine drives: pass 1 streams chunks through the sketch (and collects
  the small per-row columns), the per-actor summaries merge on device
  through the SAME pmin/pmax/psum collective shape as the materialized
  sketch (``engine.sketch_cuts``), and pass 2 bins each chunk straight into
  the per-actor ``bin_dtype`` buffer with overlapped upload. Peak host
  memory is O(chunk + sketch), never O(N·F) float32.

Environment knobs (all overridable per-matrix via ``RayStreamingDMatrix``
arguments): ``RXGB_STREAM_CHUNK_ROWS`` (rows per ingest chunk),
``RXGB_STREAM_BUDGET_MB`` (host-memory budget the chunk size is derived
from and validated against), ``RXGB_STREAM_SKETCH_CAP`` (per-level sketch
buffer capacity), ``RXGB_STREAM_PREFETCH`` (upload queue depth; 2 = double
buffering).
"""

from xgboost_ray_tpu.stream.reader import (
    ShardStream,
    StreamConfig,
    array_shard_stream,
    is_streamed_shards,
    materialize_shard,
    shard_streams,
)
from xgboost_ray_tpu.stream.sketch import StreamSketch

__all__ = [
    "ShardStream",
    "StreamConfig",
    "StreamSketch",
    "array_shard_stream",
    "is_streamed_shards",
    "materialize_shard",
    "shard_streams",
]
