"""Per-process bootstrap spawned by ``launcher.launch_distributed``.

Order matters: the hermeticity trick (drop non-CPU PJRT factories when
``RXGB_FORCE_CPU_MESH`` is set — same as tests/conftest.py) must run before
ANY jax-touching import, including the unpickle of the worker fn's module;
then the process joins the ``jax.distributed`` world and runs the fn.

Usage (internal): python -m xgboost_ray_tpu._launcher_worker <payload> <result>
"""

import os
import pickle
import sys


def main() -> int:
    payload_path, result_path = sys.argv[1], sys.argv[2]

    if os.environ.get("RXGB_FORCE_CPU_MESH"):
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        for _name in list(_xb._backend_factories):
            if _name not in ("cpu",):
                _xb._backend_factories.pop(_name, None)

    with open(payload_path, "rb") as f:
        payload = pickle.load(f)
    ctx = payload["ctx"]
    if hasattr(ctx, "heartbeat"):
        # first touch BEFORE the slow imports: the watchdog's stall clock
        # should start at bootstrap, not at spawn + interpreter startup
        ctx.heartbeat()

    # chaos hook (kill/hang/straggle this process, RXGB_FAULT_PLAN env).
    # A plain package import is correct here: unpickling ctx above already
    # imported xgboost_ray_tpu.launcher (LaunchContext's defining module)
    # and with it the whole package — importing jax modules does not
    # initialize a backend, so jax.distributed.initialize below still runs
    # first. Using the package's own faults instance keeps ONE plan/counter
    # state per process (a standalone copy would double-parse the env plan).
    from xgboost_ray_tpu import faults

    faults.fire(
        "launcher.worker", process_id=ctx.process_id, attempt=ctx.attempt
    )

    fn, args = pickle.loads(payload["fn_args"])

    import jax

    jax.distributed.initialize(
        coordinator_address=ctx.coordinator_address,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
    )
    if hasattr(ctx, "heartbeat"):
        # first post-join liveness touch; worker fns take over per round
        ctx.heartbeat()

    result = fn(ctx, *args)

    tmp = f"{result_path}.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, result_path)
    try:
        # orderly disconnect; the result file is already committed, so a
        # teardown-time error must not fail the worker
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
