"""Driver-level multi-process launcher with automatic restart-from-checkpoint.

The SPMD failure model (SURVEY §5.8): when any process of a
``jax.distributed`` world dies, the coordination service TERMINATES the
survivors with a fatal diagnostic — there is no Python exception to catch
mid-collective, so recovery must live ABOVE the world, at the driver level.
The reference solves the same problem with its retry loop
(``xgboost_ray/main.py:1606-1713``): detect dead actors, re-create them, and
restart training from the last checkpoint. ``launch_distributed`` is that
loop for real process worlds: it spawns the per-process workers, watches for
any death, tears the attempt down, and respawns the whole world — the
workers resume from the newest checkpoint via ``load_round_checkpoint``.

Single-host (or the CPU-mesh rehearsal), one launcher supervises the whole
world. On a multi-host pod, run one launcher per host with
``local_process_ids`` set to that host's process ids and a fixed
``coordinator_address``: a death anywhere kills every process (the
coordination service guarantees it), so every host's launcher observes its
local children die and independently respawns them — the world re-forms at
the same coordinator with the attempt counter advanced, and training resumes
from the shared checkpoint.

Worker functions must be module-level (pickled by reference into the spawned
interpreter) with signature ``fn(ctx, *args)``; see ``LaunchContext`` for
what they receive. The canonical training worker:

    def train_worker(ctx, data_path):
        booster, done = load_round_checkpoint(ctx.checkpoint_path)
        shards = ...  # THIS process's rows
        eng = TpuEngine(shards, params, num_actors=W, init_booster=booster)
        for i in range(total_rounds - done):
            eng.step(i)
            save_round_checkpoint(eng.get_booster(), ctx.checkpoint_path,
                                  done + i)
        return eng.get_booster().save_raw()
"""

import dataclasses
import logging
import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "LaunchContext",
    "LaunchResult",
    "ProcessFailure",
    "LaunchFailedError",
    "launch_distributed",
    "save_round_checkpoint",
    "load_round_checkpoint",
]


@dataclasses.dataclass(frozen=True)
class LaunchContext:
    """What every worker process receives as its first argument."""

    process_id: int
    num_processes: int
    coordinator_address: str
    attempt: int  # 0 on the first try, +1 per world restart
    checkpoint_path: Optional[str]


@dataclasses.dataclass(frozen=True)
class ProcessFailure:
    attempt: int
    process_id: int
    returncode: int
    log_tail: str
    # True when the LAUNCHER force-killed this process during teardown;
    # False when it died on its own (the injected fault, the coordination
    # service's survivor termination, or a surfaced Python exception)
    forced: bool = False


@dataclasses.dataclass
class LaunchResult:
    results: List[Any]  # worker_fn return value per LOCAL process
    restarts: int  # world restarts that were needed
    failures: List[ProcessFailure]  # every observed process death


class LaunchFailedError(RuntimeError):
    def __init__(self, message: str, failures: List[ProcessFailure]):
        super().__init__(message)
        self.failures = failures


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def save_round_checkpoint(booster, path: str, completed_round: int) -> None:
    """Atomically persist ``booster`` + the round it completed (the driver's
    rank-0 checkpoint role, reference ``main.py:612-626``). The MODEL rename
    is the single commit point — the ``.round`` marker is advisory (humans /
    monitoring) and never read back, so a death between the two renames
    cannot desynchronize resume arithmetic."""
    tmp = f"{path}.tmp"
    booster.save_model(tmp)
    os.replace(tmp, path)
    rtmp = f"{path}.round.tmp"
    with open(rtmp, "w") as f:
        f.write(str(int(completed_round)))
    os.replace(rtmp, f"{path}.round")


def load_round_checkpoint(path: Optional[str]) -> Tuple[Optional[Any], int]:
    """(booster, completed_rounds) from the newest checkpoint, or (None, 0)
    when none exists yet. ``completed_rounds`` comes from the atomically
    committed model itself (``num_boosted_rounds``), never the advisory
    ``.round`` file — a kill between the checkpoint's two renames must not
    make the resumed world recount."""
    if not path or not os.path.exists(path):
        return None, 0
    import json

    with open(path) as f:
        doc = json.load(f)
    # dispatch on the document's booster (gblinear checkpoints carry the
    # xgboost gblinear learner schema, trees our native format)
    name = doc.get("learner", {}).get("gradient_booster", {}).get("name")
    if name == "gblinear":
        from xgboost_ray_tpu.linear import RayLinearBooster

        booster = RayLinearBooster.import_xgboost_json(doc)
    else:
        from xgboost_ray_tpu.models.booster import RayXGBoostBooster

        booster = RayXGBoostBooster._from_dict(doc)
    return booster, booster.num_boosted_rounds()


def _tail(path: str, limit: int = 4000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def launch_distributed(
    worker_fn: Callable,
    num_processes: int,
    *,
    args: tuple = (),
    checkpoint_path: Optional[str] = None,
    max_restarts: int = 2,
    local_process_ids: Optional[Sequence[int]] = None,
    coordinator_address: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    timeout_s: float = 900.0,
    poll_interval: float = 0.25,
    survivor_grace_s: float = 150.0,
) -> LaunchResult:
    """Run ``worker_fn(ctx, *args)`` in a ``num_processes``-process
    ``jax.distributed`` world, restarting the WHOLE world from the latest
    checkpoint when any process dies (up to ``max_restarts`` times).

    ``worker_fn`` must be a module-level callable (pickled by reference).
    Each spawned process joins the world before the fn runs; the fn's return
    value is pickled back. ``env`` entries override the inherited
    environment (e.g. ``JAX_PLATFORMS``/``XLA_FLAGS`` for the CPU-mesh
    rehearsal, ``RXGB_FORCE_CPU_MESH=1`` for tunnel hermeticity).

    Single-host by default (spawns all ``num_processes`` locally with a
    fresh loopback coordinator per attempt). On a pod, pass this host's
    ``local_process_ids`` and the shared ``coordinator_address``.

    On a process death, survivors get ``survivor_grace_s`` to exit on their
    own (the coordination service terminates them — with default heartbeat
    settings detection takes up to ~100 s, so the grace must exceed it; a
    Python-level surfaced failure exits sooner) before being force-killed — so ``failures`` records
    whether each process surfaced the failure itself (``forced=False``) or
    had to be torn down (``forced=True``).
    """
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    local_ids = (
        list(local_process_ids)
        if local_process_ids is not None
        else list(range(num_processes))
    )
    if any(i < 0 or i >= num_processes for i in local_ids):
        raise ValueError(
            f"local_process_ids {local_ids} out of range for "
            f"num_processes={num_processes}"
        )
    # pickle-by-reference sanity check up front (spawned interpreters import
    # the fn's module; a lambda/closure would die remotely with a worse error)
    try:
        payload_fn = pickle.dumps((worker_fn, tuple(args)))
    except Exception as exc:
        raise ValueError(
            f"worker_fn/args must be picklable module-level objects "
            f"(got {exc})"
        ) from exc

    scratch = tempfile.mkdtemp(prefix="rxgb_launch_")
    fn_mod_dir = None
    mod = sys.modules.get(getattr(worker_fn, "__module__", ""), None)
    mod_file = getattr(mod, "__file__", None)
    if mod_file:
        fn_mod_dir = os.path.dirname(os.path.abspath(mod_file))

    failures: List[ProcessFailure] = []
    try:
        return _run_attempts(
            payload_fn, num_processes, local_ids, checkpoint_path,
            coordinator_address, env, fn_mod_dir, scratch, timeout_s,
            poll_interval, survivor_grace_s, max_restarts, failures,
        )
    finally:
        import shutil

        # failure log tails are already captured into the ProcessFailure
        # records (and into the raised error), so the scratch dir never
        # needs to outlive the call
        shutil.rmtree(scratch, ignore_errors=True)


def _run_attempts(
    payload_fn, num_processes, local_ids, checkpoint_path,
    coordinator_address, env, fn_mod_dir, scratch, timeout_s,
    poll_interval, survivor_grace_s, max_restarts, failures,
) -> LaunchResult:
    restarts = 0
    attempt = 0
    while True:
        coord = coordinator_address or f"127.0.0.1:{_free_port()}"
        procs: List[subprocess.Popen] = []
        paths = []
        for pid_ in local_ids:
            ctx = LaunchContext(
                process_id=pid_,
                num_processes=num_processes,
                coordinator_address=coord,
                attempt=attempt,
                checkpoint_path=checkpoint_path,
            )
            payload_path = os.path.join(scratch, f"a{attempt}_p{pid_}.pkl")
            result_path = os.path.join(scratch, f"a{attempt}_p{pid_}.result")
            log_path = os.path.join(scratch, f"a{attempt}_p{pid_}.log")
            with open(payload_path, "wb") as f:
                pickle.dump({"fn_args": payload_fn, "ctx": ctx}, f)
            child_env = dict(os.environ)
            if env:
                child_env.update(env)
            py_path = [p for p in (fn_mod_dir,) if p]
            py_path.append(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            if child_env.get("PYTHONPATH"):
                py_path.append(child_env["PYTHONPATH"])
            child_env["PYTHONPATH"] = os.pathsep.join(py_path)
            child_env.pop("PYTEST_CURRENT_TEST", None)
            log_f = open(log_path, "w")
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-u",
                        "-m",
                        "xgboost_ray_tpu._launcher_worker",
                        payload_path,
                        result_path,
                    ],
                    env=child_env,
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
            )
            log_f.close()
            paths.append((result_path, log_path, pid_))

        deadline = time.monotonic() + timeout_s
        attempt_failed = False
        timed_out = False
        while True:
            codes = [p.poll() for p in procs]
            if any(c is not None and c != 0 for c in codes):
                attempt_failed = True
                break
            if all(c == 0 for c in codes):
                break
            if time.monotonic() > deadline:
                attempt_failed = True
                timed_out = True
                break
            time.sleep(poll_interval)

        if attempt_failed:
            # give survivors the chance to exit on their own (coordination-
            # service termination / surfaced exception) so `forced` records
            # who actually surfaced the failure; hung worlds skip the grace
            if not timed_out and survivor_grace_s > 0:
                grace_end = time.monotonic() + survivor_grace_s
                while (any(p.poll() is None for p in procs)
                       and time.monotonic() < grace_end):
                    time.sleep(poll_interval)
            forced_ids = set()
            for p, (_, _, pid_) in zip(procs, paths):
                if p.poll() is None:
                    forced_ids.add(pid_)
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            for p, (_, log_path, pid_) in zip(procs, paths):
                rc = p.returncode if p.returncode is not None else -1
                if rc != 0:
                    failures.append(
                        ProcessFailure(
                            attempt, pid_, rc, _tail(log_path),
                            forced=pid_ in forced_ids,
                        )
                    )
            why = "timed out" if timed_out else "process death"
            if restarts >= max_restarts:
                raise LaunchFailedError(
                    f"distributed world failed ({why}) on attempt {attempt} "
                    f"and the restart budget ({max_restarts}) is exhausted. "
                    f"Last failure logs:\n"
                    + "\n".join(
                        f"--- process {f_.process_id} (rc={f_.returncode})\n"
                        f"{f_.log_tail[-1500:]}"
                        for f_ in failures[-len(local_ids):]
                    ),
                    failures,
                )
            restarts += 1
            attempt += 1
            logger.warning(
                "[RayXGBoost] distributed world died (%s, attempt %d); "
                "restarting from checkpoint %r (restart %d/%d).",
                why, attempt - 1, checkpoint_path, restarts, max_restarts,
            )
            continue

        results = []
        for result_path, log_path, pid_ in paths:
            try:
                with open(result_path, "rb") as f:
                    results.append(pickle.load(f))
            except OSError:
                results.append(None)
        return LaunchResult(
            results=results, restarts=restarts, failures=failures
        )
