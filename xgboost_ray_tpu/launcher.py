"""Driver-level multi-process launcher with automatic restart-from-checkpoint.

The SPMD failure model (SURVEY §5.8): when any process of a
``jax.distributed`` world dies, the coordination service TERMINATES the
survivors with a fatal diagnostic — there is no Python exception to catch
mid-collective, so recovery must live ABOVE the world, at the driver level.
The reference solves the same problem with its retry loop
(``xgboost_ray/main.py:1606-1713``): detect dead actors, re-create them, and
restart training from the last checkpoint. ``launch_distributed`` is that
loop for real process worlds: it spawns the per-process workers, watches for
any death, tears the attempt down, and respawns the whole world — the
workers resume from the newest checkpoint via ``load_round_checkpoint``.

Single-host (or the CPU-mesh rehearsal), one launcher supervises the whole
world. On a multi-host pod, run one launcher per host with
``local_process_ids`` set to that host's process ids and a fixed
``coordinator_address``: a death anywhere kills every process (the
coordination service guarantees it), so every host's launcher observes its
local children die and independently respawns them — the world re-forms at
the same coordinator with the attempt counter advanced, and training resumes
from the shared checkpoint.

Worker functions must be module-level (pickled by reference into the spawned
interpreter) with signature ``fn(ctx, *args)``; see ``LaunchContext`` for
what they receive. The canonical training worker:

    def train_worker(ctx, data_path):
        booster, done = load_round_checkpoint(ctx.checkpoint_path)
        shards = ...  # THIS process's rows
        eng = TpuEngine(shards, params, num_actors=W, init_booster=booster)
        with AsyncCheckpointWriter() as ckpt:  # commits off the round loop
            for i in range(total_rounds - done):
                eng.step(i)
                ckpt.submit(eng.get_booster(), ctx.checkpoint_path, done + i)
        return eng.get_booster().save_raw()
"""

import dataclasses
import glob
import hashlib
import logging
import os
import pickle
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from xgboost_ray_tpu import faults, obs
from xgboost_ray_tpu.util import restart_backoff_s

logger = logging.getLogger(__name__)

__all__ = [
    "LaunchContext",
    "LaunchResult",
    "ProcessFailure",
    "LaunchFailedError",
    "launch_distributed",
    "save_round_checkpoint",
    "load_round_checkpoint",
    "AsyncCheckpointWriter",
]


@dataclasses.dataclass(frozen=True)
class LaunchContext:
    """What every worker process receives as its first argument."""

    process_id: int
    num_processes: int
    coordinator_address: str
    attempt: int  # 0 on the first try, +1 per world restart
    checkpoint_path: Optional[str]
    # per-process liveness file for the launcher's hang watchdog; workers
    # call ``ctx.heartbeat()`` each round (cheap mtime touch)
    heartbeat_path: Optional[str] = None

    def heartbeat(self) -> None:
        """Touch this process's heartbeat file (no-op when the launcher did
        not arm the watchdog). Must never fail the worker."""
        if not self.heartbeat_path:
            return
        try:
            with open(self.heartbeat_path, "w") as f:
                f.write(str(time.time()))
        except OSError:  # pragma: no cover - liveness is best-effort
            pass


@dataclasses.dataclass(frozen=True)
class ProcessFailure:
    attempt: int
    process_id: int
    returncode: int
    log_tail: str
    # True when the LAUNCHER force-killed this process during teardown;
    # False when it died on its own (the injected fault, the coordination
    # service's survivor termination, or a surfaced Python exception)
    forced: bool = False
    # why this process went down: "crashed" (nonzero exit on its own),
    # "hung" (killed because the world's heartbeats stalled past
    # hang_timeout_s — world-level: a wedged collective stalls every
    # member), "slow" (the whole-world timeout_s expired), or "torn_down"
    # (healthy peer killed while the launcher tore a crashed world down)
    reason: str = "crashed"
    # fault-domain attribution (RXGB_FAULT_DOMAINS logical partition of the
    # process space, same layout as the elastic plane's); None = no
    # partition configured
    domain: Optional[int] = None


@dataclasses.dataclass
class LaunchResult:
    results: List[Any]  # worker_fn return value per LOCAL process
    restarts: int  # world restarts that were needed
    failures: List[ProcessFailure]  # every observed process death


class LaunchFailedError(RuntimeError):
    def __init__(self, message: str, failures: List[ProcessFailure]):
        super().__init__(message)
        self.failures = failures


def _process_domain(process_id: int, num_processes: int) -> Optional[int]:
    """Fault-domain of a launcher process under the ``RXGB_FAULT_DOMAINS``
    logical partition (the same contiguous layout the elastic plane uses),
    or None when no partition is configured — correlates cross-process
    failures ("both deaths were domain 1") in ProcessFailure records and
    the ``launcher.attempt_failed`` timeline event."""
    from xgboost_ray_tpu.domains import logical_domain_of

    raw = os.environ.get("RXGB_FAULT_DOMAINS", "")
    try:
        h = int(raw) if raw else 0
    except ValueError:
        h = 0
    if h <= 0 or num_processes <= 0:
        return None
    return logical_domain_of(process_id, num_processes, h)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _history_path(path: str, completed_round: int) -> str:
    return f"{path}.r{int(completed_round):06d}"


def _history_candidates(path: str) -> List[str]:
    """Retained history checkpoints for ``path``, newest round first."""
    pat = re.compile(re.escape(os.path.basename(path)) + r"\.r(\d{6})$")
    out = []
    for p in glob.glob(glob.escape(path) + ".r??????"):
        m = pat.match(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out, reverse=True)]


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Durably commit a rename by fsyncing the containing directory (a
    crash after ``os.replace`` but before the directory entry hits disk can
    otherwise resurrect the OLD file — or nothing). Best-effort: some
    filesystems refuse directory fds."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass
    finally:
        os.close(fd)


def save_round_checkpoint(
    booster, path: str, completed_round: int, keep_last: Optional[int] = None,
    fsync: bool = True,
) -> None:
    """Atomically persist ``booster`` + the round it completed (the driver's
    rank-0 checkpoint role, reference ``main.py:612-626``). The MODEL rename
    is the single commit point — the ``.round`` marker is advisory (humans /
    monitoring) and never read back, so a death between the two renames
    cannot desynchronize resume arithmetic.

    Durability: the temp file is fsynced BEFORE the atomic rename (and the
    directory entry after), so a host crash cannot leave a zero-length or
    partially-written "newest" checkpoint behind the committed name —
    ``fsync=False`` opts out for tests/tmpfs.

    Integrity + retention (the hardened resume path): every commit also
    writes a ``.sha256`` sidecar and retains the last ``keep_last``
    checkpoints as independent ``{path}.rNNNNNN`` copies (default
    ``RXGB_CHECKPOINT_KEEP``, 2; 0 disables retention) — so a corrupt or
    truncated newest checkpoint makes ``load_round_checkpoint`` fall back
    to the previous good one instead of killing the resume path.

    This runs serialization + write + fsync on the CALLING thread; round
    loops should submit through :class:`AsyncCheckpointWriter` so the write
    overlaps the next rounds instead of stalling them."""
    if keep_last is None:
        keep_last = int(os.environ.get("RXGB_CHECKPOINT_KEEP", "2"))
    tmp = f"{path}.tmp"
    booster.save_model(tmp)
    digest = _sha256_file(tmp)
    if fsync:
        _fsync_file(tmp)
    os.replace(tmp, path)
    stmp = f"{path}.sha256.tmp"
    with open(stmp, "w") as f:
        f.write(digest)
    os.replace(stmp, f"{path}.sha256")
    rtmp = f"{path}.round.tmp"
    with open(rtmp, "w") as f:
        f.write(str(int(completed_round)))
    os.replace(rtmp, f"{path}.round")
    if keep_last > 0:
        # independent COPY (not a hardlink): single-inode corruption of the
        # live file must not take the retained fallback down with it
        hist = _history_path(path, completed_round)
        shutil.copyfile(path, f"{hist}.tmp")
        os.replace(f"{hist}.tmp", hist)
        with open(f"{hist}.sha256.tmp", "w") as f:
            f.write(digest)
        os.replace(f"{hist}.sha256.tmp", f"{hist}.sha256")
        for stale in _history_candidates(path)[keep_last:]:
            for victim in (stale, f"{stale}.sha256"):
                try:
                    os.remove(victim)
                except OSError:
                    pass
    if fsync:
        _fsync_dir(os.path.dirname(path))
    obs.get_tracer().event(
        "checkpoint.commit", round=int(completed_round),
        attrs={"path": path, "bytes": os.path.getsize(path)},
    )
    # chaos hook LAST: a corrupt/truncate rule damages the COMMITTED newest
    # checkpoint (post-write disk corruption), which load must survive
    faults.fire_file("checkpoint.save", path, round=int(completed_round))


class AsyncCheckpointWriter:
    """Background checkpoint writes for the round loop.

    ``save_round_checkpoint`` serializes, writes and fsyncs on the calling
    thread — at production model sizes that stalls the boosting loop for the
    full commit. ``submit()`` hands the (immutable) booster snapshot to a
    background thread instead; ``wait()`` joins the in-flight write and
    re-raises its failure, and is invoked automatically by the next
    ``submit()`` — so at most one write is ever in flight, checkpoints
    commit strictly in round order, and a write error surfaces at the next
    round boundary instead of being dropped. Use as a context manager so
    the final write is joined (and its errors surfaced) before the worker
    returns::

        with AsyncCheckpointWriter() as ckpt:
            for i in range(rounds):
                eng.step(i)
                ckpt.submit(eng.get_booster(), path, done + i)
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None

    def submit(self, booster, path: str, completed_round: int,
               keep_last: Optional[int] = None, fsync: bool = True) -> None:
        """Queue one checkpoint commit; joins the previous one first."""
        self.wait()

        def _write():
            try:
                save_round_checkpoint(
                    booster, path, completed_round,
                    keep_last=keep_last, fsync=fsync,
                )
            except BaseException as exc:  # noqa: BLE001 - re-raised by wait()
                self._exc = exc

        self._thread = threading.Thread(
            target=_write, name="rxgb-ckpt-writer", daemon=True
        )
        self._thread.start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the in-flight write (if any); re-raise its failure. Returns
        True when nothing is left in flight.

        With ``timeout`` the join is BOUNDED: if the write is still running
        after that many seconds (a hung disk, an injected ``checkpoint.save``
        hang), the writer thread is left behind (it is a daemon, so it can
        never wedge interpreter exit), a loud error is logged, and False is
        returned — the caller knows the newest checkpoint is unconfirmed.
        The thread handle is kept, so a later unbounded ``wait()`` can still
        collect a slow-but-alive write."""
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                logger.error(
                    "[RayXGBoost] background checkpoint write still running "
                    "after %.1fs; abandoning the join (daemon thread '%s') — "
                    "the most recent checkpoint is NOT confirmed on disk.",
                    timeout if timeout is not None else -1.0, thread.name,
                )
                return False
            self._thread = None
        # read the outcome only once the thread is provably finished — a
        # timed-out join must not race the writer's error store
        exc, self._exc = self._exc, None
        if exc is not None:
            raise exc
        return True

    @staticmethod
    def _exit_join_timeout() -> Optional[float]:
        """Bounded-join budget for context-manager exit (driver shutdown):
        ``RXGB_CKPT_EXIT_JOIN_S`` seconds, default 60; <= 0 restores the
        unbounded pre-hardening join."""
        t = float(os.environ.get("RXGB_CKPT_EXIT_JOIN_S", "60"))
        return t if t > 0 else None

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # bounded on BOTH paths: a commit hung on dead storage must not
        # wedge driver exit (the write thread is a daemon; wait() already
        # logged loudly if it had to abandon the join)
        if exc_type is None:
            self.wait(timeout=self._exit_join_timeout())
        else:
            # don't mask the in-flight exception with a checkpoint error
            try:
                self.wait(timeout=self._exit_join_timeout())
            except BaseException as ckpt_exc:  # noqa: BLE001
                logger.warning(
                    "[RayXGBoost] background checkpoint write failed during "
                    "error teardown: %s", ckpt_exc,
                )
        return False


def _checkpoint_sha_ok(path: str) -> Optional[bool]:
    """True/False against the ``.sha256`` sidecar, None when there is no
    (readable) sidecar to check against."""
    sidecar = f"{path}.sha256"
    if not os.path.exists(sidecar):
        return None
    try:
        with open(sidecar) as f:
            expected = f.read().strip()
        if not expected:
            return None
        return _sha256_file(path) == expected
    except OSError:
        return None


def _parse_checkpoint(path: str) -> Optional[Any]:
    """Parse one checkpoint file; None when it is corrupt/truncated."""
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
        # dispatch on the document's booster (gblinear checkpoints carry the
        # xgboost gblinear learner schema, trees our native format)
        name = doc.get("learner", {}).get("gradient_booster", {}).get("name")
        if name == "gblinear":
            from xgboost_ray_tpu.linear import RayLinearBooster

            return RayLinearBooster.import_xgboost_json(doc)
        from xgboost_ray_tpu.models.booster import RayXGBoostBooster

        return RayXGBoostBooster._from_dict(doc)
    except Exception as exc:  # noqa: BLE001 - any parse failure -> fallback
        logger.warning(
            "[RayXGBoost] checkpoint %s is unreadable (%s: %s); treating "
            "as corrupt.", path, type(exc).__name__, exc,
        )
        return None


def load_round_checkpoint(path: Optional[str]) -> Tuple[Optional[Any], int]:
    """(booster, completed_rounds) from the newest GOOD checkpoint, or
    (None, 0) when none exists yet. ``completed_rounds`` comes from the
    atomically committed model itself (``num_boosted_rounds``), never the
    advisory ``.round`` file — a kill between the checkpoint's two renames
    must not make the resumed world recount.

    A corrupt/truncated/sha-mismatched newest checkpoint falls back to the
    newest retained ``{path}.rNNNNNN`` copy that validates (replaying the
    rounds in between) instead of crashing the resume path; only when every
    candidate is bad does the world restart from scratch — loudly."""
    if not path:
        return None, 0
    faults.fire("checkpoint.load", path=path)
    candidates = [path] + _history_candidates(path)
    existing = [c for c in candidates if os.path.exists(c)]
    sha_mismatched: List[str] = []
    for cand in existing:
        if _checkpoint_sha_ok(cand) is False:
            logger.warning(
                "[RayXGBoost] checkpoint %s fails its sha256 sidecar; "
                "treating as corrupt.", cand,
            )
            sha_mismatched.append(cand)
            continue
        booster = _parse_checkpoint(cand)
        if booster is not None:
            if cand != path:
                logger.warning(
                    "[RayXGBoost] newest checkpoint %s is corrupt; resuming "
                    "from retained fallback %s (%d rounds).",
                    path, cand, booster.num_boosted_rounds(),
                )
            obs.get_tracer().event(
                "checkpoint.load",
                attrs={"rounds": booster.num_boosted_rounds(),
                       "fallback": cand != path},
            )
            return booster, booster.num_boosted_rounds()
    # no candidate passed integrity. A sha mismatch can also be a STALE
    # sidecar (a kill between the model rename and the sidecar rename), so
    # before abandoning the run to round 0, accept the newest mismatched
    # candidate that still parses — a valid checkpoint beats none.
    for cand in sha_mismatched:
        booster = _parse_checkpoint(cand)
        if booster is not None:
            logger.warning(
                "[RayXGBoost] no checkpoint for %s passes integrity; "
                "resuming from sha-mismatched but parseable %s (%d rounds) "
                "— likely a torn sidecar write.",
                path, cand, booster.num_boosted_rounds(),
            )
            return booster, booster.num_boosted_rounds()
    if existing:
        logger.error(
            "[RayXGBoost] every checkpoint candidate for %s is corrupt "
            "(%d tried); restarting training from round 0.",
            path, len(existing),
        )
    return None, 0


def _tail(path: str, limit: int = 4000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def launch_distributed(
    worker_fn: Callable,
    num_processes: int,
    *,
    args: tuple = (),
    checkpoint_path: Optional[str] = None,
    max_restarts: int = 2,
    local_process_ids: Optional[Sequence[int]] = None,
    coordinator_address: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    timeout_s: float = 900.0,
    poll_interval: float = 0.25,
    survivor_grace_s: float = 150.0,
    hang_timeout_s: Optional[float] = None,
) -> LaunchResult:
    """Run ``worker_fn(ctx, *args)`` in a ``num_processes``-process
    ``jax.distributed`` world, restarting the WHOLE world from the latest
    checkpoint when any process dies (up to ``max_restarts`` times).

    ``worker_fn`` must be a module-level callable (pickled by reference).
    Each spawned process joins the world before the fn runs; the fn's return
    value is pickled back. ``env`` entries override the inherited
    environment (e.g. ``JAX_PLATFORMS``/``XLA_FLAGS`` for the CPU-mesh
    rehearsal, ``RXGB_FORCE_CPU_MESH=1`` for tunnel hermeticity).

    Single-host by default (spawns all ``num_processes`` locally with a
    fresh loopback coordinator per attempt). On a pod, pass this host's
    ``local_process_ids`` and the shared ``coordinator_address``.

    On a process death, survivors get ``survivor_grace_s`` to exit on their
    own (the coordination service terminates them — with default heartbeat
    settings detection takes up to ~100 s, so the grace must exceed it; a
    Python-level surfaced failure exits sooner) before being force-killed — so ``failures`` records
    whether each process surfaced the failure itself (``forced=False``) or
    had to be torn down (``forced=True``).

    ``hang_timeout_s`` arms the heartbeat watchdog: workers call
    ``ctx.heartbeat()`` each round, and a world whose heartbeats stall
    longer than this is flagged ``hung`` and restarted long before the
    global ``timeout_s`` — set it above the worst-case round (plus compile)
    time. ``failures[*].reason`` distinguishes ``hung`` / ``slow`` (global
    timeout) / ``crashed`` / ``torn_down``. Between attempts the launcher
    backs off exponentially with jitter (``RXGB_RESTART_BACKOFF_*``;
    base 0 disables) so a persistent fault cannot crash-loop storm.
    """
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    local_ids = (
        list(local_process_ids)
        if local_process_ids is not None
        else list(range(num_processes))
    )
    if any(i < 0 or i >= num_processes for i in local_ids):
        raise ValueError(
            f"local_process_ids {local_ids} out of range for "
            f"num_processes={num_processes}"
        )
    # pickle-by-reference sanity check up front (spawned interpreters import
    # the fn's module; a lambda/closure would die remotely with a worse error)
    try:
        payload_fn = pickle.dumps((worker_fn, tuple(args)))
    except Exception as exc:
        raise ValueError(
            f"worker_fn/args must be picklable module-level objects "
            f"(got {exc})"
        ) from exc

    scratch = tempfile.mkdtemp(prefix="rxgb_launch_")
    fn_mod_dir = None
    mod = sys.modules.get(getattr(worker_fn, "__module__", ""), None)
    mod_file = getattr(mod, "__file__", None)
    if mod_file:
        fn_mod_dir = os.path.dirname(os.path.abspath(mod_file))

    failures: List[ProcessFailure] = []
    try:
        return _run_attempts(
            payload_fn, num_processes, local_ids, checkpoint_path,
            coordinator_address, env, fn_mod_dir, scratch, timeout_s,
            poll_interval, survivor_grace_s, max_restarts, failures,
            hang_timeout_s,
        )
    finally:
        import shutil

        # failure log tails are already captured into the ProcessFailure
        # records (and into the raised error), so the scratch dir never
        # needs to outlive the call
        shutil.rmtree(scratch, ignore_errors=True)


def _run_attempts(
    payload_fn, num_processes, local_ids, checkpoint_path,
    coordinator_address, env, fn_mod_dir, scratch, timeout_s,
    poll_interval, survivor_grace_s, max_restarts, failures,
    hang_timeout_s=None,
) -> LaunchResult:
    restarts = 0
    attempt = 0
    consecutive_failures = 0
    # an attempt that ran at least this long before dying is an isolated
    # failure, not a crash loop — its restart rewinds the backoff escalation
    healthy_uptime_s = 2.0 * float(
        os.environ.get("RXGB_RESTART_BACKOFF_MAX_S", "30")
    )
    while True:
        coord = coordinator_address or f"127.0.0.1:{_free_port()}"
        procs: List[subprocess.Popen] = []
        paths = []
        spawned_at = time.time()
        attempt_started = time.monotonic()
        for pid_ in local_ids:
            heartbeat_path = os.path.join(scratch, f"a{attempt}_p{pid_}.hb")
            with open(heartbeat_path, "w") as f:
                # baseline: the hang clock starts at spawn, not first touch
                f.write(str(spawned_at))
            ctx = LaunchContext(
                process_id=pid_,
                num_processes=num_processes,
                coordinator_address=coord,
                attempt=attempt,
                checkpoint_path=checkpoint_path,
                heartbeat_path=heartbeat_path,
            )
            payload_path = os.path.join(scratch, f"a{attempt}_p{pid_}.pkl")
            result_path = os.path.join(scratch, f"a{attempt}_p{pid_}.result")
            log_path = os.path.join(scratch, f"a{attempt}_p{pid_}.log")
            with open(payload_path, "wb") as f:
                pickle.dump({"fn_args": payload_fn, "ctx": ctx}, f)
            child_env = dict(os.environ)
            if env:
                child_env.update(env)
            py_path = [p for p in (fn_mod_dir,) if p]
            py_path.append(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            if child_env.get("PYTHONPATH"):
                py_path.append(child_env["PYTHONPATH"])
            child_env["PYTHONPATH"] = os.pathsep.join(py_path)
            child_env.pop("PYTEST_CURRENT_TEST", None)
            log_f = open(log_path, "w")
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-u",
                        "-m",
                        "xgboost_ray_tpu._launcher_worker",
                        payload_path,
                        result_path,
                    ],
                    env=child_env,
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
            )
            log_f.close()
            paths.append((result_path, log_path, heartbeat_path, pid_))
        obs.get_tracer().event(
            "launcher.spawn",
            attrs={"attempt": attempt, "world": len(local_ids)},
        )

        deadline = time.monotonic() + timeout_s
        attempt_failed = False
        timed_out = False
        hung_ids = set()
        while True:
            codes = [p.poll() for p in procs]
            if any(c is not None and c != 0 for c in codes):
                attempt_failed = True
                break
            if all(c == 0 for c in codes):
                break
            if time.monotonic() > deadline:
                attempt_failed = True
                timed_out = True
                break
            if hang_timeout_s:
                now = time.time()
                for p, (_, _, hb_path, pid_) in zip(procs, paths):
                    if p.poll() is not None:
                        continue
                    try:
                        last = os.path.getmtime(hb_path)
                    except OSError:
                        last = spawned_at
                    if now - last > hang_timeout_s:
                        hung_ids.add(pid_)
                if hung_ids:
                    # a stalled world never trips the coordination service
                    # (nobody died) — flag it long before the global timeout
                    obs.get_tracer().event(
                        "launcher.hung",
                        attrs={
                            "attempt": attempt,
                            "ranks": sorted(hung_ids),
                            "heartbeat_age_s": round(
                                max(
                                    now - os.path.getmtime(hb)
                                    if os.path.exists(hb) else now - spawned_at
                                    for _, _, hb, _ in paths
                                ), 3,
                            ),
                        },
                    )
                    attempt_failed = True
                    break
            time.sleep(poll_interval)

        if attempt_failed:
            # give survivors the chance to exit on their own (coordination-
            # service termination / surfaced exception) so `forced` records
            # who actually surfaced the failure; hung/timed-out worlds skip
            # the grace (nobody is going to exit on their own)
            if not timed_out and not hung_ids and survivor_grace_s > 0:
                grace_end = time.monotonic() + survivor_grace_s
                while (any(p.poll() is None for p in procs)
                       and time.monotonic() < grace_end):
                    time.sleep(poll_interval)
            forced_ids = set()
            for p, (_, _, _, pid_) in zip(procs, paths):
                if p.poll() is None:
                    forced_ids.add(pid_)
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            for p, (_, log_path, _, pid_) in zip(procs, paths):
                rc = p.returncode if p.returncode is not None else -1
                if rc != 0:
                    # a heartbeat stall is detected at WORLD level (the
                    # first process to cross the threshold trips the
                    # teardown while its equally-stalled peers may be
                    # milliseconds short) — every process killed in a hang
                    # teardown was part of the stalled world
                    if hung_ids and pid_ in forced_ids:
                        reason = "hung"
                    elif timed_out:
                        reason = "slow"
                    elif pid_ in forced_ids:
                        reason = "torn_down"
                    else:
                        reason = "crashed"
                    failures.append(
                        ProcessFailure(
                            attempt, pid_, rc, _tail(log_path),
                            forced=pid_ in forced_ids,
                            reason=reason,
                            domain=_process_domain(pid_, num_processes),
                        )
                    )
            if hung_ids:
                why = f"heartbeats stalled > {hang_timeout_s}s"
            elif timed_out:
                why = "timed out"
            else:
                why = "process death"
            if restarts >= max_restarts:
                raise LaunchFailedError(
                    f"distributed world failed ({why}) on attempt {attempt} "
                    f"and the restart budget ({max_restarts}) is exhausted. "
                    f"Last failure logs:\n"
                    + "\n".join(
                        f"--- process {f_.process_id} (rc={f_.returncode})\n"
                        f"{f_.log_tail[-1500:]}"
                        for f_ in failures[-len(local_ids):]
                    ),
                    failures,
                )
            restarts += 1
            attempt += 1
            if time.monotonic() - attempt_started > healthy_uptime_s:
                consecutive_failures = 0
            consecutive_failures += 1
            backoff = restart_backoff_s(consecutive_failures - 1)
            logger.warning(
                "[RayXGBoost] distributed world died (%s, attempt %d); "
                "restarting from checkpoint %r (restart %d/%d, backoff "
                "%.2fs).",
                why, attempt - 1, checkpoint_path, restarts, max_restarts,
                backoff,
            )
            obs.get_tracer().event(
                "launcher.attempt_failed",
                attrs={"attempt": attempt - 1, "reason": why,
                       "restart": restarts, "backoff_s": round(backoff, 4),
                       "domains": sorted({
                           f_.domain for f_ in failures
                           if f_.attempt == attempt - 1
                           and f_.domain is not None
                       })},
            )
            if backoff > 0:
                time.sleep(backoff)
            continue

        results = []
        for result_path, log_path, _, pid_ in paths:
            try:
                with open(result_path, "rb") as f:
                    results.append(pickle.load(f))
            except (OSError, EOFError, pickle.UnpicklingError) as exc:
                # a zero-exit worker that left no (readable) result is a
                # broken contract, not a partial success — surface it with
                # the worker's log instead of silently returning None
                raise LaunchFailedError(
                    f"worker {pid_} exited 0 but its result file is "
                    f"missing/unreadable ({type(exc).__name__}: {exc}); "
                    f"refusing to return a partial world. Log tail:\n"
                    f"{_tail(log_path)}",
                    failures,
                )
        return LaunchResult(
            results=results, restarts=restarts, failures=failures
        )
