"""xgboost_ray_tpu: TPU-native distributed gradient-boosted-tree training.

A brand-new framework with the capabilities of ray-project/xgboost_ray,
re-designed for TPU: workers are slots of a ``jax.sharding.Mesh``, the
``gpu_hist`` CUDA tree method is replaced by a JAX/XLA/Pallas ``tpu_hist``
histogram learner over HBM-resident quantile-binned feature blocks, and the
Rabit TCP allreduce becomes ``jax.lax.psum`` over ICI/DCN.

Public API mirrors ``xgboost_ray/__init__.py:1-41``.
"""

import os as _os

# Respect an explicit JAX_PLATFORMS env override even when a PJRT plugin
# (e.g. a TPU tunnel) force-updated the jax config at interpreter startup —
# otherwise CPU-forced runs still initialize (and can hang on) the TPU client.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # pragma: no cover - config may be frozen post-init
        pass

from xgboost_ray_tpu.main import (
    RayParams,
    RayXGBoostActor,
    predict,
    train,
)
from xgboost_ray_tpu.matrix import (
    Data,
    RayDMatrix,
    RayDeviceQuantileDMatrix,
    RayQuantileDMatrix,
    RayShardingMode,
    RayStreamingDMatrix,
    combine_data,
)
from xgboost_ray_tpu.data_sources import RayFileType
from xgboost_ray_tpu.models.booster import Booster, RayXGBoostBooster
from xgboost_ray_tpu.callback import DistributedCallback, TrainingCallback
from xgboost_ray_tpu import faults, obs
from xgboost_ray_tpu.obs import recovery_time_s, validate_trace_records
from xgboost_ray_tpu.launcher import (
    AsyncCheckpointWriter,
    LaunchContext,
    LaunchResult,
    launch_distributed,
    load_round_checkpoint,
    save_round_checkpoint,
)

__version__ = "0.1.0"

__all__ = [
    "__version__",
    "RayParams",
    "RayDMatrix",
    "RayDeviceQuantileDMatrix",
    "RayQuantileDMatrix",
    "RayStreamingDMatrix",
    "RayFileType",
    "RayShardingMode",
    "Data",
    "combine_data",
    "train",
    "predict",
    "Booster",
    "RayXGBoostBooster",
    "RayXGBoostActor",
    "DistributedCallback",
    "TrainingCallback",
    "faults",
    "obs",
    "validate_trace_records",
    "recovery_time_s",
    "LaunchContext",
    "LaunchResult",
    "launch_distributed",
    "load_round_checkpoint",
    "save_round_checkpoint",
    "AsyncCheckpointWriter",
]

try:
    from xgboost_ray_tpu.sklearn import (
        RayXGBClassifier,
        RayXGBRanker,
        RayXGBRegressor,
        RayXGBRFClassifier,
        RayXGBRFRegressor,
    )

    __all__ += [
        "RayXGBClassifier",
        "RayXGBRegressor",
        "RayXGBRFClassifier",
        "RayXGBRFRegressor",
        "RayXGBRanker",
    ]
except ImportError:  # sklearn facade requires scikit-learn
    pass
