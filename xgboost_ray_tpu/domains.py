"""Fault domains: host-granular failure grouping for the elastic plane.

ROADMAP item 4 states the realistic production failure plainly: a lost HOST
is the unit of loss, not a lost actor. The placement layer already groups
mesh devices by ``process_index`` (``main._select_mesh_devices``'s SPREAD
strategy); this module keeps that structure alive at failure time as a
:class:`DomainMap` — a static rank -> domain-id assignment derived once per
training attempt — so the driver can coalesce a whole domain's
near-simultaneous deaths into ONE shrink, run the reintegration grace clock
per domain, and re-admit a replacement domain atomically.

Domain derivation (``derive_domain_map``), in priority order:

1. ``RXGB_FAULT_DOMAINS=H`` (``ENV.FAULT_DOMAINS``) — a logical partition of
   the rank space into ``H`` contiguous groups, so every domain behavior is
   exercised on the single-process CPU CI mesh.
2. A real multi-host mesh — each rank's domain is the ``process_index`` of
   the device backing it (ranks colocated on one host share a domain and
   die together when that host is lost).
3. Single process, no override — every rank is its own domain (an actor IS
   the failure unit on one host), which preserves the pre-domain per-rank
   elastic semantics exactly.

This module must stay import-light (no jax/numpy): ``faults`` resolves
``domain_kill`` targets through it and launcher workers import ``faults``
before any jax-touching import.
"""

import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["DomainMap", "DeathCoalescer", "derive_domain_map", "logical_domain_of"]


def logical_domain_of(rank: int, num_ranks: int, num_domains: int) -> int:
    """Contiguous H-way partition of ``num_ranks`` ranks (the
    ``RXGB_FAULT_DOMAINS=H`` layout, also used by the launcher to attribute
    process failures): rank ``r`` belongs to domain ``r * H // num_ranks``."""
    h = max(1, min(int(num_domains), int(num_ranks)))
    return int(rank) * h // int(num_ranks)


class DomainMap:
    """Immutable rank -> fault-domain assignment for one training attempt."""

    def __init__(self, assignment: Dict[int, int]):
        self._assignment = dict(assignment)
        self._ranks: Dict[int, Tuple[int, ...]] = {}
        for rank, dom in sorted(self._assignment.items()):
            self._ranks.setdefault(dom, ())
            self._ranks[dom] = self._ranks[dom] + (rank,)

    def domain_of(self, rank: int) -> int:
        return self._assignment[rank]

    def ranks_of(self, domain: int) -> Tuple[int, ...]:
        return self._ranks.get(domain, ())

    def domains(self) -> List[int]:
        return sorted(self._ranks)

    def domains_of(self, ranks: Sequence[int]) -> List[int]:
        return sorted({self._assignment[r] for r in ranks if r in self._assignment})

    @property
    def num_ranks(self) -> int:
        return len(self._assignment)

    @property
    def num_domains(self) -> int:
        return len(self._ranks)

    def __repr__(self) -> str:  # debugging / event payloads
        return f"DomainMap({self._assignment!r})"


def derive_domain_map(
    num_actors: int,
    devices: Optional[Sequence] = None,
    logical_domains: int = 0,
) -> DomainMap:
    """Build the rank -> domain assignment for a world of ``num_actors``.

    ``devices`` is the resolved mesh device list (rank ``r`` is backed by the
    ``r``-th contiguous slice); only each device's ``process_index`` attribute
    is consulted, so any object (including test fakes) works. See the module
    docstring for the three-tier derivation order.
    """
    n = int(num_actors)
    if logical_domains and int(logical_domains) > 0:
        return DomainMap(
            {r: logical_domain_of(r, n, int(logical_domains)) for r in range(n)}
        )
    if devices:
        procs = [getattr(d, "process_index", 0) for d in devices]
        if len(set(procs)) > 1:
            # rank r <-> its first backing device (devices are laid out in
            # rank-contiguous slices by the mesh builder)
            per = max(1, len(procs) // n)
            return DomainMap(
                {r: int(procs[min(r * per, len(procs) - 1)]) for r in range(n)}
            )
    return DomainMap({r: r for r in range(n)})


class DeathCoalescer:
    """Thread-safe mailbox folding near-simultaneous deaths into one shrink.

    Anything that learns of a rank death out-of-band of the driver's round
    loop (``RayXGBoostActor.kill`` from a chaos thread, a liveness probe, a
    future multi-host heartbeat monitor) ``note()``s the rank here; the
    driver's in-flight recovery drains the mailbox inside its coalescing
    window and blames every noted rank in the SAME shrink — one retrace for
    a whole lost host instead of N sequential shrink/recompile cycles.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Dict[int, Optional[int]] = {}

    def note(self, rank: int, domain: Optional[int] = None) -> None:
        """Record a dead rank (idempotent; first note's domain attribution
        wins). Never blocks the noting thread on driver-side work."""
        with self._lock:
            self._pending.setdefault(int(rank), domain)

    def drain(self) -> Dict[int, Optional[int]]:
        """Atomically take every noted death. A rank noted concurrently with
        a drain lands in exactly one batch — never both, never neither."""
        with self._lock:
            out = dict(self._pending)
            self._pending.clear()
            return out

    @property
    def pending(self) -> bool:
        with self._lock:
            return bool(self._pending)
