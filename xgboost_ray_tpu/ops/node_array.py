"""Breadth-first node-array forest layout: FIL-style level-synchronous walk.

The padded-heap layout (``grow.py``) stores each tree as its own
``[heap]`` vector and ``ops/predict.py`` walks it depth-first per tree
under a ``vmap`` — every level of the walk gathers from a *different*
region of every tree's private heap. The GPU inference analysis of
XGBoost's forests (arXiv:1806.11248, the layout RAPIDS FIL productized)
observes that batched tree traversal is memory-bound and wants the
opposite layout: **struct-of-arrays with all trees' level-k nodes
contiguous**, so one traversal step for the whole ensemble is a few wide
vectorized gathers from one contiguous slab instead of T strided
per-tree walks.

This module is that layout for our padded heaps. It is a *pure
permutation* of the heap — node ``(tree t, level k, slot p)`` lives at

    ``level_base(k) + t * 2**k + p``  with  ``level_base(k) = T * (2**k - 1)``

and corresponds to per-tree heap index ``2**k - 1 + p`` — so the walk
below performs the *same* elementwise routing arithmetic on the *same*
float values as ``predict.py``'s ``_walk_one_tree`` and stays **bitwise
identical** to it (pinned by ``tests/test_serve_pool.py``). Only the
six fields the raw-x walk reads are materialized (feature, split_bin,
threshold, default_left, is_leaf, value); the SHAP kernels need
``base_weight``/``cover`` path statistics that do not level-map, so
``contribs`` stays on the heap program (the serve layer routes it
there).
"""

import functools
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.grow import Tree, cat_mask_const as _cat_mask_const


class NodeForest(NamedTuple):
    """Breadth-first node-array ensemble: each field flat ``[T * heap]``,
    level-major (all trees' level-k nodes contiguous, ``2**k`` per tree)."""

    feature: jnp.ndarray       # int32  [T * heap]
    split_bin: jnp.ndarray     # int32  [T * heap]
    threshold: jnp.ndarray     # float32[T * heap]
    default_left: jnp.ndarray  # bool   [T * heap]
    is_leaf: jnp.ndarray       # bool   [T * heap]
    value: jnp.ndarray         # float32[T * heap]


def _level_base(k: int, num_trees: int) -> int:
    return num_trees * ((1 << k) - 1)


def forest_to_node_array(forest: Tree, max_depth: int) -> NodeForest:
    """Permute a stacked padded-heap forest (fields ``[T, heap]``) into the
    level-major node-array layout. Host-side numpy; called once per model
    at predictor construction."""
    feature = np.asarray(forest.feature)
    t, heap = feature.shape
    if heap != (1 << (max_depth + 1)) - 1:
        raise ValueError(
            f"heap width {heap} does not match max_depth {max_depth} "
            f"(expected {(1 << (max_depth + 1)) - 1})"
        )

    def permute(field, dtype):
        arr = np.asarray(field)
        # slab k is arr[:, 2^k-1 : 2^(k+1)-1] flattened tree-major: the
        # reshape(-1) of the [T, 2^k] slice lands (t, p) at t*2^k + p,
        # exactly the position formula the walk gathers with
        return np.concatenate([
            arr[:, (1 << k) - 1:(1 << (k + 1)) - 1].reshape(-1)
            for k in range(max_depth + 1)
        ]).astype(dtype, copy=False)

    return NodeForest(
        feature=permute(forest.feature, np.int32),
        split_bin=permute(forest.split_bin, np.int32),
        threshold=permute(forest.threshold, np.float32),
        default_left=permute(forest.default_left, bool),
        is_leaf=permute(forest.is_leaf, bool),
        value=permute(forest.value, np.float32),
    )


def _num_trees(na: NodeForest, max_depth: int) -> int:
    return int(na.value.shape[0]) // ((1 << (max_depth + 1)) - 1)


def _step_right_na(na, pos, xv, f, cat_mask):
    """``predict._step_right`` on node-array gathers: identical elementwise
    ops on identical values, so routing decisions are bitwise the same."""
    present_right = xv >= na.threshold[pos]
    if cat_mask is not None:
        code = jnp.round(xv).astype(jnp.int32)
        present_right = jnp.where(
            cat_mask[f], code != na.split_bin[pos], present_right
        )
    return jnp.where(jnp.isnan(xv), ~na.default_left[pos], present_right)


def _walk_levels(na: NodeForest, x: jnp.ndarray, max_depth: int, cat_mask):
    """Level-synchronous ensemble walk. x: [N, F] raw (may contain NaN).

    Returns ``(leaf_value [T, N], leaf_heap_idx [T, N])`` — the per-tree
    leaf value and per-tree heap index each row lands on, matching the
    depth-first walk exactly: a row freezes at its first leaf; a row that
    never meets a leaf reads the level-``max_depth`` node it reaches, just
    as ``_walk_one_tree`` returns ``value[idx]`` for its final ``idx``.
    """
    n = x.shape[0]
    t = _num_trees(na, max_depth)
    row = jnp.arange(n, dtype=jnp.int32)[None, :]      # [1, N]
    t_col = jnp.arange(t, dtype=jnp.int32)[:, None]    # [T, 1]
    p = jnp.zeros((t, n), jnp.int32)                   # slot within level
    done = jnp.zeros((t, n), bool)
    val = jnp.zeros((t, n), jnp.float32)
    hidx = jnp.zeros((t, n), jnp.int32)
    num_features = x.shape[1]
    for k in range(max_depth):
        pos = _level_base(k, t) + (t_col << k) + p     # [T, N] flat gather
        leaf_here = na.is_leaf[pos]
        newly = leaf_here & ~done
        val = jnp.where(newly, na.value[pos], val)
        hidx = jnp.where(newly, ((1 << k) - 1) + p, hidx)
        done = done | leaf_here
        f = jnp.clip(na.feature[pos], 0, num_features - 1)
        xv = x[row, f]                                  # [T, N] row gather
        go_right = _step_right_na(na, pos, xv, f, cat_mask)
        p = jnp.where(done, p, 2 * p + go_right.astype(jnp.int32))
    pos = _level_base(max_depth, t) + (t_col << max_depth) + p
    val = jnp.where(done, val, na.value[pos])
    hidx = jnp.where(done, hidx, ((1 << max_depth) - 1) + p)
    return val, hidx


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_margin_na(
    na: NodeForest,
    x: jnp.ndarray,            # [N, F] float32 raw features
    base_margin: jnp.ndarray,  # [N, K] starting margin
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,  # [T] per-tree scale (DART)
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Node-array twin of ``predict.predict_margin``: same leaf matrix,
    same accumulation tail, so the [N, K] margins are bitwise identical."""
    t = _num_trees(na, max_depth)
    cat_mask = _cat_mask_const(cat_features, x.shape[1])
    leaf, _ = _walk_levels(na, x, max_depth, cat_mask)  # [T, N]
    if tree_weights is not None:
        leaf = leaf * tree_weights[:, None]
    if ntree_limit:
        keep = jnp.arange(t) < ntree_limit
        leaf = jnp.where(keep[:, None], leaf, 0.0)
    if num_outputs == 1:
        margin = base_margin[:, 0] + leaf.sum(axis=0) / num_parallel_tree
        return margin[:, None]
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=leaf.dtype)  # [T, K]
    return base_margin + (leaf.T @ onehot) / num_parallel_tree


@functools.partial(jax.jit, static_argnames=("max_depth", "cat_features"))
def predict_leaf_index_na(
    na: NodeForest, x: jnp.ndarray, max_depth: int, cat_features: tuple = ()
) -> jnp.ndarray:
    """Node-array twin of ``predict.predict_leaf_index``: per-tree leaf
    heap index per row, [N, T] int32 — integer-identical by construction."""
    cat_mask = _cat_mask_const(cat_features, x.shape[1])
    _, hidx = _walk_levels(na, x, max_depth, cat_mask)
    return hidx.T.astype(jnp.int32)
