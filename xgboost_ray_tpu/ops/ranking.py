"""Learning-to-rank objectives (LambdaMART family) with query-group segments.

TPU-native replacement for xgboost's C++ rank objectives (``rank:pairwise``,
``rank:ndcg``, ``rank:map``), which the reference exercises through
``RayXGBRanker`` (``xgboost_ray/sklearn.py:921-1040``) with qid-sorted shards
(``xgboost_ray/matrix.py:70-102``).

Group structure is static-shaped: at data-load time the host builds a padded
gather map ``group_rows [n_groups, max_group]`` (row index or sentinel N for
padding). Per round, scores/labels are gathered into the padded layout, all
intra-group pairs are evaluated as dense [chunk, G, G] tensors (VPU-friendly,
no data-dependent shapes), and per-row grad/hess are scattered back. Groups
are processed in scan chunks to bound memory.
"""

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_ray_tpu.ops.objectives import Objective


def build_group_rows(qid: np.ndarray, max_group: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: qid [N] (sorted by qid) -> (group_rows [n_groups, G], group_ptr).

    group_rows holds row indices padded with N (sentinel). group_ptr is the
    [n_groups+1] offset array used by ranking metrics.
    """
    qid = np.asarray(qid)
    n = qid.shape[0]
    change = np.nonzero(np.diff(qid))[0] + 1
    ptr = np.concatenate([[0], change, [n]]).astype(np.int64)
    sizes = np.diff(ptr)
    g = int(sizes.max()) if sizes.size else 1
    if max_group:
        g = max(g, max_group)
    rows = np.full((ptr.size - 1, g), n, dtype=np.int32)
    for i in range(ptr.size - 1):
        rows[i, : sizes[i]] = np.arange(ptr[i], ptr[i + 1], dtype=np.int32)
    return rows, ptr


def _pairwise_lambdas(s, y, valid, use_ndcg_delta: bool):
    """One padded group chunk. s, y, valid: [C, G]. Returns g, h: [C, G]."""
    c, gsz = s.shape
    # pair masks: i beats j
    yi, yj = y[:, :, None], y[:, None, :]
    vi, vj = valid[:, :, None], valid[:, None, :]
    beats = (yi > yj) & vi & vj
    diff = s[:, :, None] - s[:, None, :]
    rho = jax.nn.sigmoid(-diff)  # P(mis-ordering gradient weight)

    if use_ndcg_delta:
        # |delta NDCG| for swapping i and j, based on current ranking.
        neg = jnp.where(valid, -s, jnp.inf)
        order = jnp.argsort(neg, axis=1)  # desc by score
        ranks = jnp.argsort(order, axis=1)  # rank of each item (0-based)
        inv_log = 1.0 / jnp.log2(2.0 + ranks.astype(jnp.float32))  # discount
        gain = jnp.exp2(jnp.where(valid, y, 0.0)) - 1.0
        # ideal DCG per group for normalization
        sorted_gain = jnp.sort(jnp.where(valid, gain, 0.0), axis=1)[:, ::-1]
        pos_disc = 1.0 / jnp.log2(2.0 + jnp.arange(gsz, dtype=jnp.float32))
        idcg = jnp.maximum((sorted_gain * pos_disc[None, :]).sum(axis=1), 1e-12)
        dgain = jnp.abs(gain[:, :, None] - gain[:, None, :])
        ddisc = jnp.abs(inv_log[:, :, None] - inv_log[:, None, :])
        delta = dgain * ddisc / idcg[:, None, None]
    else:
        delta = 1.0

    lam = jnp.where(beats, rho * delta, 0.0)  # [C, G, G] weight for (winner i, loser j)
    hess = jnp.where(beats, jnp.maximum(rho * (1.0 - rho), 1e-16) * delta, 0.0)
    # winner i: g_i -= lam_ij summed over j ; loser j: g_j += lam_ij summed over i
    g = -lam.sum(axis=2) + lam.sum(axis=1)
    h = hess.sum(axis=2) + hess.sum(axis=1)
    return g, h


def make_rank_grad_hess(name: str, group_chunk: int = 0) -> Callable:
    use_ndcg = name in ("rank:ndcg", "rank:map")

    def grad_hess(margin, label, weight, group_rows):
        """margin [N, 1], label [N], weight [N], group_rows [NG, G] -> g, h [N, 1]."""
        n = label.shape[0]
        ng, gsz = group_rows.shape
        if group_chunk:
            chunk = group_chunk
        else:
            # bound the [chunk, G, G] pair tensors to ~64M float32 elements
            # (MSLR-scale groups of ~1200 docs -> chunk ~44)
            chunk = int(np.clip(64_000_000 // max(gsz * gsz, 1), 1, 256))
        s_ext = jnp.concatenate([margin[:, 0], jnp.zeros((1,), margin.dtype)])
        y_ext = jnp.concatenate([label, jnp.zeros((1,), label.dtype)])
        valid = group_rows < n
        rows = jnp.minimum(group_rows, n)  # sentinel -> slot n

        n_chunks = -(-ng // chunk)
        pad = n_chunks * chunk - ng
        rows_p = jnp.pad(rows, ((0, pad), (0, 0)), constant_values=n)
        valid_p = jnp.pad(valid, ((0, pad), (0, 0)), constant_values=False)
        rows_c = rows_p.reshape(n_chunks, chunk, gsz)
        valid_c = valid_p.reshape(n_chunks, chunk, gsz)

        def chunk_step(acc, args):
            r, v = args
            s = s_ext[r]
            y = jnp.where(v, y_ext[r], 0.0)
            g, h = _pairwise_lambdas(s, y, v, use_ndcg)
            gacc, hacc = acc
            gacc = gacc.at[r.reshape(-1)].add(jnp.where(v, g, 0.0).reshape(-1))
            hacc = hacc.at[r.reshape(-1)].add(jnp.where(v, h, 0.0).reshape(-1))
            return (gacc, hacc), None

        g0 = jnp.zeros((n + 1,), jnp.float32)
        h0 = jnp.zeros((n + 1,), jnp.float32)
        (g, h), _ = jax.lax.scan(chunk_step, (g0, h0), (rows_c, valid_c))
        g = g[:n] * weight
        h = jnp.maximum(h[:n], 1e-16) * weight
        return g[:, None], h[:, None]

    return grad_hess


@dataclasses.dataclass(frozen=True)
class RankingObjective:
    """Objective requiring group segments; the trainer passes group_rows."""

    name: str
    grad_hess_ranked: Callable
    num_outputs: int = 1
    default_metric: str = "ndcg"
    output_kind: str = "value"
    default_base_score: float = 0.5
    transform: Callable = staticmethod(lambda m: m[:, 0])
    base_score_to_margin: Callable = staticmethod(lambda s: 0.0)


def get_ranking_objective(name: str) -> RankingObjective:
    return RankingObjective(
        name=name,
        grad_hess_ranked=make_rank_grad_hess(name),
        default_metric={"rank:pairwise": "ndcg", "rank:ndcg": "ndcg", "rank:map": "map"}[name],
    )
