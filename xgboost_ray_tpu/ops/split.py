"""Split enumeration and selection from gradient histograms.

TPU-native replacement for xgboost's C++ split evaluator (part of the
``hist``/``gpu_hist`` updaters the reference selects via
``params["tree_method"]``, ``xgboost_ray/main.py:1506-1524``).

Fully vectorized over (node, feature, bin): cumulative sums over the bin axis
give left-child stats for every candidate threshold at once; the right child
is parent − left. Missing values occupy the reserved last bucket and the
default direction is *learned* per split by evaluating both placements —
mirroring xgboost's sparsity-aware split finding.

Scores use the xgboost leaf objective with L1/L2 regularization:
  w*(G,H)  = -T(G) / (H + lambda),    T(G) = soft-threshold by alpha
  score    = T(G)^2 / (H + lambda)
  gain     = score_L + score_R - score_parent    (accepted iff > gamma)
"""

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SplitParams:
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0
    min_child_weight: float = 1.0
    learning_rate: float = 0.3
    max_delta_step: float = 0.0


class LevelSplits(NamedTuple):
    """Best split per node at one tree level (all arrays [n_nodes])."""

    gain: jnp.ndarray  # float32; -inf when no valid split
    feature: jnp.ndarray  # int32
    split_bin: jnp.ndarray  # int32; rows with bin <= split_bin go left
    default_left: jnp.ndarray  # bool; where missing values go
    valid: jnp.ndarray  # bool; node splits (gain > gamma and constraints met)


def _soft_threshold(g, alpha):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - alpha, 0.0)


def score(g, h, p: SplitParams):
    t = _soft_threshold(g, p.reg_alpha)
    den = h + p.reg_lambda
    return jnp.where(den > 0, t * t / jnp.maximum(den, 1e-38), 0.0)


def leaf_weight(g, h, p: SplitParams):
    den = h + p.reg_lambda
    w = jnp.where(den > 0, -_soft_threshold(g, p.reg_alpha) / jnp.maximum(den, 1e-38), 0.0)
    if p.max_delta_step > 0:
        w = jnp.clip(w, -p.max_delta_step, p.max_delta_step)
    return w


def bounded_weight(g, h, p: SplitParams, lower, upper):
    """Leaf weight clamped to a node's feasible interval (monotone bounds)."""
    return jnp.clip(leaf_weight(g, h, p), lower, upper)


def score_given_weight(g, h, p: SplitParams, w):
    """Objective reduction achieved by a (possibly bound-clamped) weight w:
    -(2*T(g)*w + (h+lambda)*w^2). At the unclamped optimum w* = -T(g)/(h+lambda)
    this equals T(g)^2/(h+lambda) == score(), so the constrained evaluator is
    a strict generalization of the unconstrained one (xgboost's
    CalcGainGivenWeight, with our L1 soft-threshold convention)."""
    t = _soft_threshold(g, p.reg_alpha)
    return -(2.0 * t * w + (h + p.reg_lambda) * w * w)


def find_splits(
    hist: jnp.ndarray,  # [n_nodes, F, n_bins+1, 2]; last bucket = missing
    node_gh: jnp.ndarray,  # [n_nodes, 2] parent totals (includes missing)
    p: SplitParams,
    feature_mask: jnp.ndarray = None,  # [F] bool; False = column sampled out
    cat_mask: jnp.ndarray = None,  # [F] bool; True = categorical feature
    monotone: jnp.ndarray = None,  # [F] float32 in {-1, 0, +1}
    node_lower: jnp.ndarray = None,  # [n_nodes] weight lower bounds
    node_upper: jnp.ndarray = None,  # [n_nodes] weight upper bounds
) -> LevelSplits:
    """For numeric features, candidate s means "bins <= s go left" (prefix
    scan). For categorical features (``cat_mask``), candidate s means the
    one-vs-rest partition "category s goes left" — bins ARE category codes,
    so the left child stats are a single histogram slot (xgboost's one-hot
    categorical splits behind ``enable_categorical``).

    With ``monotone`` (xgboost ``monotone_constraints``, the hist updater's
    MonotonicConstraint evaluator): child weights are clamped to the node's
    inherited ``[node_lower, node_upper]`` interval, candidate gains are
    computed from the clamped weights, and candidates whose child-weight
    ordering violates the sign (+1 requires w_left <= w_right) score -inf."""
    n_nodes, num_features, nbt, _ = hist.shape
    n_bins = nbt - 1
    g = hist[..., 0]  # [n, F, nbt]
    h = hist[..., 1]
    gm, hm = g[..., n_bins], h[..., n_bins]  # missing bucket [n, F]
    # cumulative over present bins; candidate s in 0..n_bins-2 (split after bin s)
    gl = jnp.cumsum(g[..., :n_bins], axis=-1)[..., : n_bins - 1]  # [n, F, B-1]
    hl = jnp.cumsum(h[..., :n_bins], axis=-1)[..., : n_bins - 1]
    if cat_mask is not None:
        # one-vs-rest: left child = the single candidate category's slot
        cm = cat_mask[None, :, None]
        gl = jnp.where(cm, g[..., : n_bins - 1], gl)
        hl = jnp.where(cm, h[..., : n_bins - 1], hl)
    gp = node_gh[:, 0][:, None, None]
    hp = node_gh[:, 1][:, None, None]

    if monotone is not None:
        lo = (jnp.full((n_nodes,), -jnp.inf) if node_lower is None
              else node_lower)[:, None, None]
        hi = (jnp.full((n_nodes,), jnp.inf) if node_upper is None
              else node_upper)[:, None, None]
        mono = monotone[None, :, None]
        parent_score = score_given_weight(
            gp, hp, p, bounded_weight(gp, hp, p, lo, hi)
        )

        def gain_for(gl_, hl_):
            gr_, hr_ = gp - gl_, hp - hl_
            ok = (hl_ >= p.min_child_weight) & (hr_ >= p.min_child_weight)
            wl = bounded_weight(gl_, hl_, p, lo, hi)
            wr = bounded_weight(gr_, hr_, p, lo, hi)
            viol = ((mono > 0) & (wl > wr)) | ((mono < 0) & (wl < wr))
            gain = (score_given_weight(gl_, hl_, p, wl)
                    + score_given_weight(gr_, hr_, p, wr) - parent_score)
            return jnp.where(ok & ~viol, gain, -jnp.inf)
    else:
        parent_score = score(node_gh[:, 0], node_gh[:, 1], p)[:, None, None]

        def gain_for(gl_, hl_):
            gr_, hr_ = gp - gl_, hp - hl_
            ok = (hl_ >= p.min_child_weight) & (hr_ >= p.min_child_weight)
            gain = score(gl_, hl_, p) + score(gr_, hr_, p) - parent_score
            return jnp.where(ok, gain, -jnp.inf)

    gain_missing_left = gain_for(gl + gm[..., None], hl + hm[..., None])
    gain_missing_right = gain_for(gl, hl)
    default_left = gain_missing_left >= gain_missing_right
    gain = jnp.maximum(gain_missing_left, gain_missing_right)  # [n, F, B-1]
    if feature_mask is not None:
        # [F] (tree/level sampling) or [n_nodes, F] (per-node sampling)
        mask = (
            feature_mask[None, :, None]
            if feature_mask.ndim == 1
            else feature_mask[:, :, None]
        )
        gain = jnp.where(mask, gain, -jnp.inf)

    flat = gain.reshape(n_nodes, -1)
    best = jnp.argmax(flat, axis=-1)  # first max -> deterministic ties
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0]
    feat = (best // (n_bins - 1)).astype(jnp.int32)
    sbin = (best % (n_bins - 1)).astype(jnp.int32)
    dl = jnp.take_along_axis(
        default_left.reshape(n_nodes, -1), best[:, None], axis=-1
    )[:, 0]
    valid = jnp.isfinite(best_gain) & (best_gain > p.gamma)
    return LevelSplits(gain=best_gain, feature=feat, split_bin=sbin, default_left=dl, valid=valid)


def elect_across_feature_shards(
    sp: LevelSplits,  # per-shard best splits, feature indices LOCAL
    f_offset,  # this shard's first global feature index (traced)
    n_bins: int,  # present bins (== max_bin; candidates are n_bins - 1)
    p: SplitParams,
    axis_name: str,  # the feature mesh axis
    counter=None,  # AllreduceBytes with the feature-axis ring extent
) -> LevelSplits:
    """Elect the global best split per node from each feature shard's local
    winner (the 2D row x feature mesh's split step).

    One tiny ``[n_nodes, 3]`` all_gather over the feature axis carries
    (gain, flat candidate index, default_left) per node; the winner is the
    max gain with ties broken by the LOWEST global flat index — exactly the
    first-max rule the single-shard ``find_splits`` argmax applies over the
    full flattened (feature, bin) axis, so a (R, C) mesh elects the
    bitwise-identical split a (R, 1) mesh does. The flat index rides as
    f32 (exact below 2^24; the engine rejects feature_parallel configs
    whose padded F x (max_bin - 1) exceeds that), so the record is a single
    dtype-uniform payload and the gather is ONE collective.
    """
    n_cand = n_bins - 1
    feat_g = f_offset + sp.feature
    flat = (feat_g * n_cand + sp.split_bin).astype(jnp.float32)
    payload = jnp.stack(
        [sp.gain, flat, sp.default_left.astype(jnp.float32)], axis=1
    )  # [n_nodes, 3]
    if counter is not None:
        counter.add_all_gather(payload)
    allp = jax.lax.all_gather(payload, axis_name)  # [C, n_nodes, 3]
    gains, flats, dls = allp[..., 0], allp[..., 1], allp[..., 2]
    best_gain = jnp.max(gains, axis=0)  # [n_nodes]
    # among shards achieving the max, the lowest flat index wins (an
    # all--inf node keeps shard 0's placeholder record, matching the 1D
    # argmax-over--inf result; `valid` is False there either way)
    tie_key = jnp.where(gains == best_gain[None, :], flats, jnp.inf)
    win = jnp.argmin(tie_key, axis=0)  # [n_nodes]
    flat_w = jnp.take_along_axis(flats, win[None, :], axis=0)[0]
    flat_w = flat_w.astype(jnp.int32)
    dl_w = jnp.take_along_axis(dls, win[None, :], axis=0)[0] > 0.5
    valid = jnp.isfinite(best_gain) & (best_gain > p.gamma)
    return LevelSplits(
        gain=best_gain,
        feature=(flat_w // n_cand).astype(jnp.int32),
        split_bin=(flat_w % n_cand).astype(jnp.int32),
        default_left=dl_w,
        valid=valid,
    )
