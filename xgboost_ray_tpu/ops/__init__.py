"""Device compute ops for the tpu_hist GBDT engine (JAX/XLA/Pallas).

These modules replace the native (C++/CUDA) compute core of xgboost that the
reference orchestrates (SURVEY.md §2.2): binning/quantile sketch, gradient
histograms, split search, tree growth, objectives, metrics, and prediction.
"""

from xgboost_ray_tpu.ops.binning import (
    bin_matrix,
    bin_matrix_np,
    sketch_cuts_np,
)
from xgboost_ray_tpu.ops.grow import GrowConfig, Tree, build_tree
from xgboost_ray_tpu.ops.objectives import Objective, get_objective
from xgboost_ray_tpu.ops.sampling import SamplingSpec, sample_rows
from xgboost_ray_tpu.ops.split import SplitParams

__all__ = [
    "bin_matrix",
    "bin_matrix_np",
    "sketch_cuts_np",
    "GrowConfig",
    "Tree",
    "build_tree",
    "Objective",
    "get_objective",
    "SamplingSpec",
    "sample_rows",
    "SplitParams",
]
