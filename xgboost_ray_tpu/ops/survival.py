"""Accelerated failure time (AFT) survival objective.

Completes the label-bounds data path the reference carries end-to-end
(``label_lower_bound``/``label_upper_bound`` through
``xgboost_ray/matrix.py:283-358``) with the objective that consumes it:
``survival:aft`` with normal/logistic error distributions, interval/right/
left censoring, and the ``aft-nloglik`` metric.

Model: log(T) = margin + sigma * Z. For an observation with bounds
[t_lo, t_hi]: uncensored (t_lo == t_hi) uses the density, censored uses
P(z_lo < Z < z_hi). Closed-form grad/hess w.r.t. the margin, hessians
clamped for stability (same discipline as xgboost's AFT implementation).
"""

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12
_SQRT2PI = float(np.sqrt(2.0 * np.pi))


def _normal_pdf(z):
    return jnp.exp(-0.5 * z * z) / _SQRT2PI


def _normal_cdf(z):
    return 0.5 * (1.0 + jax.lax.erf(z / np.sqrt(2.0)))


def _logistic_pdf(z):
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


def _logistic_cdf(z):
    return jax.nn.sigmoid(z)


_DISTS = {
    "normal": (_normal_pdf, _normal_cdf),
    "logistic": (_logistic_pdf, _logistic_cdf),
}


def make_aft_grad_hess(distribution: str, sigma: float) -> Callable:
    if distribution not in _DISTS:
        raise ValueError(
            f"aft_loss_distribution must be one of {sorted(_DISTS)}, got "
            f"{distribution!r}"
        )
    pdf, cdf = _DISTS[distribution]

    def grad_hess(margin, lower, upper, weight):
        """margin [N, 1]; lower/upper raw times (upper may be +inf)."""
        m = margin[:, 0]
        log_lo = jnp.log(jnp.maximum(lower, _EPS))
        z_lo = (log_lo - m) / sigma
        uncensored = jnp.isfinite(upper) & (jnp.abs(upper - lower) < 1e-10)
        right_censored = ~jnp.isfinite(upper)
        z_hi = jnp.where(
            right_censored, 0.0, (jnp.log(jnp.maximum(upper, _EPS)) - m) / sigma
        )

        # uncensored: L = -log pdf(z) + log(sigma t); dL/dm via autodiff-free forms
        def uncensored_gh(z):
            if distribution == "normal":
                g = -z / sigma
                h = jnp.ones_like(z) / (sigma * sigma)
            else:  # logistic: -log pdf = z + 2 log(1+e^-z); d/dz = 1 - 2(1-s)
                s = jax.nn.sigmoid(z)
                g = -(2.0 * s - 1.0) / sigma
                h = 2.0 * s * (1.0 - s) / (sigma * sigma)
            return g, h

        gu, hu = uncensored_gh(z_lo)

        # censored: L = -log(F(z_hi) - F(z_lo));  dF/dm = -pdf/sigma
        cdf_hi = jnp.where(right_censored, 1.0, cdf(z_hi))
        pdf_hi = jnp.where(right_censored, 0.0, pdf(z_hi))
        cdf_lo = cdf(z_lo)
        pdf_lo = pdf(z_lo)
        denom = jnp.maximum(cdf_hi - cdf_lo, _EPS)
        gc = (pdf_hi - pdf_lo) / (sigma * denom)
        # Gauss-Newton style hessian (positive, stable)
        hc = jnp.maximum(
            (pdf_lo - pdf_hi) ** 2 / (sigma * sigma * denom * denom),
            1e-6,
        )

        g = jnp.where(uncensored, gu, gc) * weight
        h = jnp.maximum(jnp.where(uncensored, hu, hc), 1e-6) * weight
        return g[:, None], h[:, None]

    return grad_hess


def aft_nloglik_contrib(
    margin,
    lower,
    upper,
    weight,
    distribution: str = "normal",
    sigma: float = 1.0,
):
    """Device-side psum-able (num, den) for the ``aft-nloglik`` metric.

    Same likelihood as :func:`aft_nloglik_np`, expressed as weighted-sum
    contributions so survival training can batch rounds (lax.scan fast path)
    and run on multi-host meshes where labels/bounds are process-local —
    mirrors the reference's allreduce-merged native metrics
    (``xgboost_ray/main.py:745-752`` leaves metric merging to xgboost).
    ``weight`` must already be zeroed on padding rows.
    """
    if distribution not in _DISTS:
        raise ValueError(
            f"aft_loss_distribution must be one of {sorted(_DISTS)}, got "
            f"{distribution!r}"
        )
    _, cdf = _DISTS[distribution]
    m = margin[:, 0]
    log_lo = jnp.log(jnp.maximum(lower, _EPS))
    z_lo = (log_lo - m) / sigma
    uncensored = jnp.isfinite(upper) & (jnp.abs(upper - lower) < 1e-10)
    if distribution == "normal":
        logpdf = -0.5 * z_lo * z_lo - jnp.log(_SQRT2PI)
    else:  # logistic: log pdf(z) = -(softplus(z) + softplus(-z))
        logpdf = -(jax.nn.softplus(z_lo) + jax.nn.softplus(-z_lo))
    nll_unc = -(logpdf - jnp.log(sigma) - log_lo)
    finite_hi = jnp.isfinite(upper)
    z_hi = (
        jnp.log(jnp.maximum(jnp.where(finite_hi, upper, 1.0), _EPS)) - m
    ) / sigma
    cdf_hi = jnp.where(finite_hi, cdf(z_hi), 1.0)
    nll_cen = -jnp.log(jnp.maximum(cdf_hi - cdf(z_lo), _EPS))
    nll = jnp.where(uncensored, nll_unc, nll_cen)
    return jnp.sum(nll * weight), jnp.sum(weight)


def aft_nloglik_np(
    margin: np.ndarray,
    lower: np.ndarray,
    upper: np.ndarray,
    weight,
    distribution: str = "normal",
    sigma: float = 1.0,
) -> float:
    """Host-side mean negative log likelihood (metric ``aft-nloglik``)."""
    from scipy import stats

    m = np.asarray(margin, np.float64).reshape(-1)
    lower = np.asarray(lower, np.float64)
    upper = np.asarray(upper, np.float64)
    w = np.ones_like(m) if weight is None else np.asarray(weight, np.float64)
    dist = stats.norm if distribution == "normal" else stats.logistic
    z_lo = (np.log(np.maximum(lower, _EPS)) - m) / sigma
    uncensored = np.isfinite(upper) & (np.abs(upper - lower) < 1e-10)
    nll = np.empty_like(m)
    # uncensored: -log( pdf(z)/(sigma * t) )
    nll[uncensored] = -(
        dist.logpdf(z_lo[uncensored])
        - np.log(sigma)
        - np.log(np.maximum(lower[uncensored], _EPS))
    )
    cen = ~uncensored
    cdf_hi = np.where(
        np.isfinite(upper[cen]),
        dist.cdf((np.log(np.maximum(upper[cen], _EPS)) - m[cen]) / sigma),
        1.0,
    )
    nll[cen] = -np.log(np.maximum(cdf_hi - dist.cdf(z_lo[cen]), _EPS))
    return float(np.sum(nll * w) / max(np.sum(w), _EPS))


@dataclasses.dataclass(frozen=True)
class SurvivalObjective:
    """Objective consuming label bounds; the engine passes (lower, upper)."""

    name: str
    grad_hess_bounds: Callable
    distribution: str
    sigma: float
    num_outputs: int = 1
    default_metric: str = "aft-nloglik"
    output_kind: str = "value"
    default_base_score: float = 0.5
    transform: Callable = staticmethod(lambda m: jnp.exp(m[:, 0]))
    base_score_to_margin: Callable = staticmethod(
        lambda s: float(np.log(max(s, 1e-16)))
    )


def get_survival_objective(
    name: str, distribution: str = "normal", sigma: float = 1.0
) -> SurvivalObjective:
    if name != "survival:aft":
        raise ValueError(f"Unsupported survival objective: {name!r}")
    return SurvivalObjective(
        name=name,
        grad_hess_bounds=make_aft_grad_hess(distribution, sigma),
        distribution=distribution,
        sigma=sigma,
    )
