"""Pallas TPU kernel for the gradient-histogram hot op.

The XLA formulations in ``histogram.py`` either materialize one-hot operands
in HBM (onehot/partition) or rely on XLA's scatter lowering (scatter). This
kernel keeps the whole accumulation in VMEM: rows arrive pre-partitioned into
node-uniform blocks (the ``hist_partition`` layout), the grid walks blocks,
and each step contracts a [block, n_bins] one-hot tile (built in-register via
iota compare) against the block's [block, 2] grad/hess on the MXU, adding
into the output tile selected by the block's node id (scalar-prefetched).

Same-node blocks are contiguous, so each output tile is resident in VMEM for
exactly one run of grid steps; tiles start from the zero-initialized aliased
output, giving plain accumulate semantics with no flags.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas availability varies across platforms
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False


def _kernel(node_ref, init_ref, bp_ref, ghp_ref, out_ref, *, nb_reg,
            n_features, precision):
    # bp_ref: [1, block, F] int (storage dtype); ghp_ref: [1, block, 2] f32
    # init_ref aliases out_ref (zero-initialized accumulator); unused directly
    # out_ref: [1, F, nb_reg, 2] f32 (accumulate) — bins on sublanes, gh pair
    # on lanes. (A bins-on-lanes orientation was tried and MISCOMPILES on
    # real v5e — wrong sums at <128-lane tiles and at large grids — with an
    # identical MXU pass count, so this orientation is the only one.)
    # The missing bucket is reconstructed by subtraction outside the kernel.
    del init_ref
    gh = ghp_ref[0]  # [block, 2]
    # Mosaic rejects per-operand Precision, so gh's mantissa is split by hand
    # into bf16-exact terms entering the MXU (the one-hot operand is exact in
    # bf16 already). "highest": three terms = 24 mantissa bits, true f32
    # accuracy (bf16x3). "fast": one bf16-rounded pass (~0.2% per entry).
    if precision == "highest":
        hi = gh.astype(jnp.bfloat16).astype(jnp.float32)
        r1 = gh - hi
        mid = r1.astype(jnp.bfloat16).astype(jnp.float32)
        gh_terms = (hi, mid, r1 - mid)
    else:
        gh_terms = (gh,)
    bins_ids = jax.lax.broadcasted_iota(jnp.int32, (1, nb_reg), 1)
    for f in range(n_features):
        col = bp_ref[0, :, f][:, None].astype(jnp.int32)  # [block, 1]
        # missing rows (bin == nb_reg) match no iota value -> all-zero row
        oh = (col == bins_ids).astype(jnp.float32)  # [block, nb_reg]
        contrib = sum(
            jax.lax.dot_general(
                oh,
                term,
                (((0,), (0,)), ((), ())),  # contract over rows -> [nb_reg, 2]
                preferred_element_type=jnp.float32,
            )
            for term in gh_terms
        )
        out_ref[0, f, :, :] += contrib


def hist_pallas_blocks(
    bp: jnp.ndarray,  # [n_blocks, block, F] int32 (node-uniform blocks)
    ghp: jnp.ndarray,  # [n_blocks, block, 2] float32
    node_of_block: jnp.ndarray,  # [n_blocks] int32 (monotone, n_nodes = scratch)
    n_nodes: int,
    n_bins_total: int,
    interpret: bool = False,
    precision: str = "highest",
) -> jnp.ndarray:
    """Accumulate per-node histograms from node-uniform blocks.

    The kernel builds only the ``n_bins_total - 1`` regular bins (keeping the
    lane dimension 128-aligned); the missing bucket is reconstructed as
    node_total - sum(regular bins). Returns [n_nodes + 1, F, n_bins_total, 2];
    row n_nodes is the scratch row for padding blocks.
    """
    n_blocks, block, n_features = bp.shape
    nb_reg = n_bins_total - 1
    kernel = functools.partial(
        _kernel, nb_reg=nb_reg, n_features=n_features, precision=precision
    )
    out_init = jnp.zeros((n_nodes + 1, n_features, nb_reg, 2), jnp.float32)
    out_block_spec = pl.BlockSpec(
        (1, n_features, nb_reg, 2), lambda i, node: (node[i], 0, 0, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            out_block_spec,  # aliased zero-initialized accumulator
            pl.BlockSpec((1, block, n_features), lambda i, node: (i, 0, 0)),
            pl.BlockSpec((1, block, 2), lambda i, node: (i, 0, 0)),
        ],
        out_specs=out_block_spec,
    )
    hist_reg = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(out_init.shape, jnp.float32),
        input_output_aliases={1: 0},  # out_init (after the scalar operand)
        interpret=interpret,
    )(node_of_block, out_init, bp, ghp)
    from xgboost_ray_tpu.ops.histogram import (
        _append_missing,
        _node_totals_from_blocks,
    )

    node_tot = _node_totals_from_blocks(ghp, node_of_block, n_nodes)
    return _append_missing(hist_reg, node_tot)


def hist_pallas_presorted(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    order: jnp.ndarray,  # [N] rows sorted stably by node (maintained O(N))
    counts: jnp.ndarray,  # [n_nodes] rows per node
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    interpret: bool = False,
    precision: str = "highest",
) -> jnp.ndarray:
    """Pallas block kernel fed from the incrementally-maintained row order
    (``histogram.update_partition_order``) — skips ``hist_pallas``'s internal
    argsort, the same presorted trick ``hist_partition_presorted`` uses.
    """
    from xgboost_ray_tpu.ops.histogram import presorted_block_layout

    bp, ghp, node_of_block = presorted_block_layout(
        bins, gh, order, counts, n_nodes, block
    )
    hist = hist_pallas_blocks(
        bp, ghp, node_of_block, n_nodes, n_bins_total, interpret=interpret,
        precision=precision,
    )
    return hist[:n_nodes]


def hist_pallas(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    interpret: bool = False,
    precision: str = "highest",
) -> jnp.ndarray:
    """Full histogram via node partitioning + the Pallas block kernel.

    Same layout machinery as ``histogram.hist_partition``; the per-block
    contraction runs in the Pallas kernel instead of an XLA einsum.
    """
    n, num_features = bins.shape
    order = jnp.argsort(pos, stable=True)
    pos_s = pos[order]
    counts = jnp.bincount(pos, length=n_nodes)
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    padded_counts = ((counts + block - 1) // block) * block
    padded_cum = jnp.cumsum(padded_counts)
    padded_start = jnp.concatenate(
        [jnp.zeros((1,), padded_cum.dtype), padded_cum[:-1]]
    )
    rank_in_node = jnp.arange(n) - seg_start[pos_s]
    dest = (padded_start[pos_s] + rank_in_node).astype(jnp.int32)

    cap = (-(-n // block) + n_nodes) * block
    n_blocks = cap // block
    row_of_slot = jnp.full((cap,), n, jnp.int32).at[dest].set(order.astype(jnp.int32))
    node_of_block = jnp.clip(
        jnp.searchsorted(padded_cum, jnp.arange(n_blocks) * block, side="right"),
        0,
        n_nodes,
    ).astype(jnp.int32)

    bins_ext = jnp.concatenate([bins, jnp.zeros((1, num_features), bins.dtype)])
    gh_ext = jnp.concatenate([gh, jnp.zeros((1, 2), gh.dtype)])
    bp = bins_ext[row_of_slot].reshape(n_blocks, block, num_features)
    ghp = gh_ext[row_of_slot].reshape(n_blocks, block, 2)

    # padding blocks (row sentinel n) land their zero gh in the scratch row,
    # but their bin ids are 0 — zero gh means zero contribution either way
    hist = hist_pallas_blocks(
        bp, ghp, node_of_block, n_nodes, n_bins_total, interpret=interpret,
        precision=precision,
    )
    return hist[:n_nodes]
