"""Row sampling with compacted histogram builds.

Before this module, ``subsample < 1`` merely ZEROED the dropped rows'
grad/hess (the old branch in ``engine.py``'s round closure), so every
histogram scatter / one-hot matmul, partition update, and node-id gather
still ran over all N rows — a sampled round cost exactly as much as a full
one. The per-round histogram build is the hot op of GBDT training (SURVEY
§5.8) and its cost scales with the number of live rows per level, so
sampling must shrink the ROW BUFFER, not just the values in it.

The shape-static formulation: per tree, select a FIXED budget of
``M = ceil(rate * N_local)`` row slots (XLA needs static shapes, so the
budget is a trace-time constant derived from the shard's padded block
size), then gather ``gh`` and the binned rows down to the M-row buffer.
``build_tree`` / ``build_tree_lossguide`` are row-count-blind — they derive
N from ``bins.shape`` — so the whole level loop (histogram builds,
partition updates, sibling-subtraction child compaction,
``select_small_child_rows``'s M//2 buffer) runs over M rows with no grower
changes. Full-row work remains only in the once-per-tree leaf-value margin
update, which reuses the eval-set tree walk (``predict_tree_binned``).

Two policies (``sampling_method`` in params):

* ``"uniform"`` — ``subsample``-rate sampling WITHOUT replacement via
  top-k over per-row uniform keys (the fixed-budget analog of the
  reference's Bernoulli row mask; "XGBoost: Scalable GPU Accelerated
  Learning", arxiv 1806.11248 §5). No weight amplification — leaf values
  come from the sampled statistics, matching xgboost's ``subsample``.
* ``"gradient_based"`` — GOSS/MVS-style (LightGBM's Gradient-based
  One-Side Sampling; MVS, arxiv 1910.13204): keep the deterministic top
  ``top_rate`` fraction by ``|g| * sqrt(h)`` (the rows that dominate the
  split-gain signal), sample ``other_rate`` of the remainder uniformly,
  and amplify the sampled remainder's gh by ``pool / rand_n`` so the
  histogram sums stay unbiased estimates of the full-data sums.

Selection is per-actor (the PRNG key is folded with the mesh axis index by
the engine, mirroring the old subsample fold), so re-sharding the same
rows onto a different world size changes which rows are drawn — the same
world-size determinism caveat the Bernoulli mask had. ``subsample=1.0``
with the default policy produces NO spec (``spec_from_params`` returns
None) and the engine's round closure traces the exact pre-sampling
program — compaction is a provable no-op when sampling is off.
"""

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingSpec:
    """Jit-static row-sampling policy (hashable; closed over at trace time).

    ``policy`` is "uniform" (rate = ``subsample``) or "gradient_based"
    (GOSS: ``top_rate`` kept deterministically, ``other_rate`` sampled with
    amplification). Budgets are derived per shard from the traced row-block
    shape via ``row_budget`` so every shard's compacted buffer is static.
    """

    policy: str
    rate: float = 1.0
    top_rate: float = 0.2
    other_rate: float = 0.1


def spec_from_params(params) -> Optional[SamplingSpec]:
    """Resolve TrainParams into a SamplingSpec, or None when sampling is
    off (the None path must stay bit-identical to pre-sampling training)."""
    if params.sampling_method == "gradient_based":
        return SamplingSpec(
            "gradient_based",
            top_rate=float(params.top_rate),
            other_rate=float(params.other_rate),
        )
    if params.subsample < 1.0:
        return SamplingSpec("uniform", rate=float(params.subsample))
    return None


def _ceil_frac(rate: float, n: int) -> int:
    # ceil(rate * n) without float-dust surprises at exact multiples
    return int(math.ceil(round(rate * n, 9)))


def goss_counts(n: int, spec: SamplingSpec) -> Tuple[int, int]:
    """Static (top_n, rand_n) for a gradient_based spec over ``n`` rows."""
    top_n = min(n, _ceil_frac(spec.top_rate, n))
    rand_n = min(n - top_n, _ceil_frac(spec.other_rate, n))
    if top_n + rand_n == 0:
        rand_n = 1  # validation forbids this, but never emit an empty buffer
    return top_n, rand_n


def row_budget(n: int, spec: SamplingSpec) -> int:
    """Compacted buffer size M for an ``n``-row shard (trace-time constant)."""
    if spec.policy == "uniform":
        return max(1, min(n, _ceil_frac(spec.rate, n)))
    top_n, rand_n = goss_counts(n, spec)
    return top_n + rand_n


def sample_rows(
    gh: jnp.ndarray,  # [N, 2] grad/hess (0 for padding rows): float32, or a
    #   quantized int8/int16 buffer (gh_precision) with ``scale`` supplied
    valid: jnp.ndarray,  # [N] bool — real data rows (padding excluded)
    key: jnp.ndarray,  # PRNG key, already folded per (tree, actor)
    spec: SamplingSpec,
    scale: Optional[jnp.ndarray] = None,  # [2] f32 dequantization scales of
    #   a quantized gh buffer (required for gradient_based over int gh)
    lane_budget: Optional[jnp.ndarray] = None,  # traced int32 scalar: keep
    #   only the first ``lane_budget`` of the M selected slots (vmapped-K
    #   HPO's per-lane subsample rate; uniform policy only)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Select the round's row budget. Returns ``(rows, gh_sel)``:

    * ``rows`` [M] int32 — indices into the shard's row block. Slots are
      distinct within each selection stage; slots landing on ineligible
      rows (padding under the uniform draw, or GOSS budget exceeding the
      eligible pool) have their ``gh_sel`` entry zeroed, so they
      contribute nothing downstream.
    * ``gh_sel`` [M, 2] — the selected rows' grad/hess, with GOSS's
      remainder amplification (``pool / rand_n``, the unbiased inflation
      of the sampled non-top mass) already applied.

    Deterministic in ``key`` — identical (seed, iteration, actor) always
    draws the same rows, so checkpoint-resumed rounds replay bit-identically.

    Quantized gh (``gh_precision``): the uniform policy gathers the narrow
    INTEGER buffer straight through (the zero-mask is exact in any int
    dtype), so the compacted build stays on the int -> int32 fast path. The
    gradient_based policy scores in f32 FROM the quantized values and
    gathers from the int buffer, but its compacted [M, 2] result is
    dequantized f32: GOSS's remainder amplification is a real-valued per-row
    multiplier that cannot ride an int8 grid without either overflowing it
    or clipping the amplified mass. M is small (top_rate + other_rate of N),
    so the full-N gh plane keeps the 4x cut and the model still trains on
    quantized-grid gradients.
    """
    n = gh.shape[0]
    int_gh = jnp.issubdtype(gh.dtype, jnp.integer)
    if int_gh and scale is None and spec.policy == "gradient_based":
        raise ValueError(
            "gradient_based sampling over a quantized gh buffer needs the "
            "dequantization scale (quantize_gh's [2] scales)"
        )
    if spec.policy == "uniform":
        # top-k over UNMASKED uniform keys: every row slot — valid or
        # padding — competes equally, so each valid row is kept with
        # probability ~ m/n == rate no matter how much of the shard is
        # padding. Preferring valid rows here would silently keep ALL of a
        # heavily-padded shard's rows (budget derives from the padded block
        # size), overweighting that shard's data vs the Bernoulli semantics
        # this replaces; selected padding slots instead just waste budget,
        # contributing nothing (their gh is zeroed below).
        m = row_budget(n, spec)
        u = jax.random.uniform(key, (n,))
        _, rows = jax.lax.top_k(u, m)
        ok = valid[rows][:, None].astype(gh.dtype)
        if lane_budget is not None:
            # top_k sorts descending, so slots [0, lane_budget) ARE the
            # lane's own exact top-k selection; the surplus slots keep
            # their row ids (shape stays the vmapped program's shared M)
            # but contribute zero gh downstream
            ok = ok * (jnp.arange(m) < lane_budget)[:, None].astype(gh.dtype)
        return rows.astype(jnp.int32), gh[rows] * ok
    if lane_budget is not None:
        raise NotImplementedError(
            "per-lane budgets (vmapped-K subsample) are only supported for "
            "the 'uniform' sampling policy"
        )
    if spec.policy != "gradient_based":
        raise ValueError(f"unknown sampling policy {spec.policy!r}")

    top_n, rand_n = goss_counts(n, spec)

    def take(rows):
        # gather from the (possibly int) buffer; the compacted result is
        # f32 quantized-grid values when gh is quantized (see docstring)
        sel = gh[rows]
        return sel.astype(jnp.float32) * scale if int_gh else sel

    if int_gh:
        g_f = gh[:, 0].astype(jnp.float32) * scale[0]
        h_f = gh[:, 1].astype(jnp.float32) * scale[1]
    else:
        g_f, h_f = gh[:, 0], gh[:, 1]
    # |g| * sqrt(h): the gradient magnitude weighted by curvature — rows
    # with large values dominate split gains g^2/(h+lambda), so keeping
    # them deterministically preserves the gain landscape (GOSS keeps
    # top-|g|; the sqrt(h) factor is the MVS-style curvature correction).
    score = jnp.abs(g_f) * jnp.sqrt(jnp.maximum(h_f, 0.0))
    score = jnp.where(valid, score, -jnp.inf)
    rows_parts = []
    gh_parts = []
    eligible = valid
    if top_n:
        tvals, rows_top = jax.lax.top_k(score, top_n)
        ok_top = jnp.isfinite(tvals)[:, None].astype(jnp.float32)
        rows_parts.append(rows_top)
        gh_parts.append(take(rows_top) * ok_top)
        eligible = eligible & (
            jnp.ones((n,), bool).at[rows_top].set(False)
        )
    if rand_n:
        u = jax.random.uniform(key, (n,))
        rscore = jnp.where(eligible, u, -1.0)
        rvals, rows_rand = jax.lax.top_k(rscore, rand_n)
        # unbiased amplification: the sampled rows stand in for the whole
        # eligible pool, so their mass is inflated by pool/rand_n (the
        # per-shard exact form of GOSS's (1-a)/b — exact even on padded
        # shards where the nominal fractions overcount dead rows). When
        # the pool is smaller than the budget every pool row is selected
        # (the surplus slots are zeroed), so the factor collapses to 1 —
        # the selection IS the pool and must not be shrunk.
        pool = jnp.sum(eligible.astype(jnp.float32))
        amp = jnp.where(
            pool > 0, pool / jnp.minimum(pool, float(rand_n)), 0.0
        )
        ok = (rvals >= 0.0)[:, None].astype(jnp.float32)
        rows_parts.append(rows_rand)
        gh_parts.append(take(rows_rand) * amp * ok)
    rows = jnp.concatenate(rows_parts).astype(jnp.int32)
    gh_sel = jnp.concatenate(gh_parts, axis=0)
    return rows, gh_sel
