"""Evaluation metrics for the tpu_hist learner.

TPU-native replacement for xgboost's metric kernels; the reference forwards
``params["eval_metric"]`` to ``xgb.train`` and merges rank-0's
``evals_result`` (``xgboost_ray/main.py:1327-1328``).

Each metric is expressed as a (numerator, denominator) contribution so the
distributed path can psum both and divide — the same trick xgboost's
allreduce-based metric reduction uses. Sort-based metrics (auc, ndcg, map)
operate on full gathered arrays.
"""

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# --- elementwise metrics: margin [N, K], label [N], weight [N] -> (num, den)


def _rmse(margin, label, weight):
    d = margin[:, 0] - label
    return jnp.sum(weight * d * d), jnp.sum(weight)


def _mae(margin, label, weight):
    return jnp.sum(weight * jnp.abs(margin[:, 0] - label)), jnp.sum(weight)


def _logloss(margin, label, weight):
    m = margin[:, 0]
    # numerically stable: log(1+exp(-m)) for y=1, log(1+exp(m)) for y=0
    ll = jnp.where(label > 0.5, jax.nn.softplus(-m), jax.nn.softplus(m))
    return jnp.sum(weight * ll), jnp.sum(weight)


def _error(margin, label, weight, threshold=0.5):
    p = _sigmoid(margin[:, 0])
    wrong = jnp.where((p > threshold) != (label > 0.5), 1.0, 0.0)
    return jnp.sum(weight * wrong), jnp.sum(weight)


def _merror(margin, label, weight):
    pred = jnp.argmax(margin, axis=-1)
    wrong = jnp.where(pred != label.astype(jnp.int32), 1.0, 0.0)
    return jnp.sum(weight * wrong), jnp.sum(weight)


def _mlogloss(margin, label, weight):
    logp = jax.nn.log_softmax(margin, axis=-1)
    k = label.astype(jnp.int32)
    ll = -jnp.take_along_axis(logp, k[:, None], axis=1)[:, 0]
    return jnp.sum(weight * ll), jnp.sum(weight)


def _rmsle(margin, label, weight):
    # labels must be > -1 (validated by the engine for the SLE objective;
    # standalone use propagates NaN rather than silently clamping)
    d = jnp.log1p(jnp.maximum(margin[:, 0], -1.0 + 1e-6)) - jnp.log1p(label)
    return jnp.sum(weight * d * d), jnp.sum(weight)


def _mphe(margin, label, weight, slope=1.0):
    r = margin[:, 0] - label
    loss = slope * slope * (jnp.sqrt(1.0 + (r / slope) ** 2) - 1.0)
    return jnp.sum(weight * loss), jnp.sum(weight)


def _mape(margin, label, weight):
    ape = jnp.abs((margin[:, 0] - label) / jnp.maximum(jnp.abs(label), 1e-10))
    return jnp.sum(weight * ape), jnp.sum(weight)


def _poisson_nloglik(margin, label, weight):
    m = jnp.clip(margin[:, 0], -30.0, 30.0)
    mu = jnp.exp(m)
    # -log p(y|mu) ignoring log(y!) like xgboost does not: xgboost includes lgamma(y+1)
    nll = mu - label * m + jax.lax.lgamma(label + 1.0)
    return jnp.sum(weight * nll), jnp.sum(weight)


def _quantile_pinball(m, label, weight, alphas=(0.5,)):
    """Mean pinball loss over the alpha outputs (xgboost "quantile" metric)."""
    a = jnp.asarray(alphas, jnp.float32)[None, :]
    if m.ndim == 1:
        m = m[:, None]
    if m.shape[1] != a.shape[1]:
        if a.shape[1] != 1:
            # a mismatch with >1 alphas means the caller wired the wrong
            # outputs/alphas together — broadcasting would silently score
            # every column against alphas[0] and mask the bug
            raise ValueError(
                f"quantile metric got {m.shape[1]} margin columns but "
                f"{a.shape[1]} quantile_alpha values; they must align."
            )
        a = jnp.broadcast_to(a, (1, m.shape[1]))
    diff = label[:, None] - m
    pin = jnp.maximum(a * diff, (a - 1.0) * diff).mean(axis=1)
    return jnp.sum(weight * pin), jnp.sum(weight)


_ELEMENTWISE: Dict[str, Callable] = {
    "rmse": _rmse,
    "mae": _mae,
    "logloss": _logloss,
    "error": _error,
    "merror": _merror,
    "mlogloss": _mlogloss,
    "poisson-nloglik": _poisson_nloglik,
    "rmsle": _rmsle,
    "mphe": _mphe,
    "mape": _mape,
    "quantile": _quantile_pinball,
}


# --- device-side sort-based metrics -----------------------------------------
#
# These keep auc/aucpr/ndcg/map on the batched (lax.scan) fast path instead of
# forcing per-round host stepping + full margin gathers (the reference gets
# this for free from xgboost's native allreduce-based metrics).
#
# * auc/aucpr: scores are bucketed into AUC_BINS sigmoid-spaced bins (sigmoid
#   is monotone, so ranks are preserved); the per-shard (pos, neg) weight
#   histograms are psum-merged and the area computed from the merged CDF with
#   midrank (trapezoid) tie handling inside the bin. Distributed xgboost is
#   itself approximate here (it averages per-worker AUCs); 4096 bins is
#   tighter than that. When the binning error matters (reporting, paper
#   numbers), request "auc_exact": the exact sort-based rank statistic,
#   deliberately NOT a device metric — it runs on the host gather path
#   (per-round stepping; on multi-host meshes it degrades to the reference's
#   per-worker weighted mean). tests/test_metrics_device.py pins the binned
#   metric's error bound against it.
# * ndcg/map: computed per query group on the padded [NG, G] group layout the
#   ranking gradients already use (groups never straddle shards), reduced to
#   psum-able (sum over groups, group count).

AUC_BINS = 4096


def auc_hist(margin, label, weight):
    """Per-shard (pos, neg) weight histogram over sigmoid-score bins. [2, B]."""
    score = margin[:, 0] if margin.shape[1] == 1 else margin[:, 1]
    p = jax.nn.sigmoid(score)
    b = jnp.clip((p * AUC_BINS).astype(jnp.int32), 0, AUC_BINS - 1)
    pos = weight * (label > 0.5)
    neg = weight * (label <= 0.5)
    hp = jnp.zeros((AUC_BINS,), jnp.float32).at[b].add(pos)
    hn = jnp.zeros((AUC_BINS,), jnp.float32).at[b].add(neg)
    return jnp.stack([hp, hn])


def auc_from_hist(h):
    """ROC AUC from a merged [2, B] histogram (midrank ties within bins)."""
    pos, neg = h[0], h[1]
    cneg_before = jnp.cumsum(neg) - neg
    num = jnp.sum(pos * (cneg_before + 0.5 * neg))
    pos_tot = jnp.sum(pos)
    neg_tot = jnp.sum(neg)
    denom = pos_tot * neg_tot
    return jnp.where(denom > 0, num / jnp.maximum(denom, 1e-12), 0.5)


def aucpr_from_hist(h):
    """PR AUC from a merged [2, B] histogram (step integration, high-to-low)."""
    pos = h[0][::-1]  # descending score order
    neg = h[1][::-1]
    tp = jnp.cumsum(pos)
    fp = jnp.cumsum(neg)
    pos_tot = jnp.maximum(tp[-1], 1e-12)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / pos_tot
    d_recall = jnp.diff(recall, prepend=0.0)
    return jnp.where(h[0].sum() > 0, jnp.sum(precision * d_recall), 0.0)


def rank_metric_contrib(kind, margin, label, group_rows, k, group_chunk: int = 0):
    """Per-shard (sum of per-group ndcg@k or map@k, non-empty group count).

    margin [N, K], label [N], group_rows [NG, G] (row indices local to the
    shard, sentinel >= N for padding). Chunked over groups to bound the
    [chunk, G] sort working set.
    """
    n = label.shape[0]
    ng, gsz = group_rows.shape
    kk = gsz if k is None else max(1, min(int(k), gsz))
    if group_chunk:
        chunk = group_chunk
    else:
        chunk = int(np.clip(4_000_000 // max(gsz, 1), 1, 4096))
    chunk = min(chunk, max(ng, 1))  # never pad past the real group count
    s_ext = jnp.concatenate([margin[:, 0], jnp.zeros((1,), margin.dtype)])
    y_ext = jnp.concatenate([label, jnp.zeros((1,), label.dtype)])
    valid = group_rows < n
    rows = jnp.minimum(group_rows, n)

    n_chunks = -(-ng // chunk)
    pad = n_chunks * chunk - ng
    rows_p = jnp.pad(rows, ((0, pad), (0, 0)), constant_values=n)
    valid_p = jnp.pad(valid, ((0, pad), (0, 0)), constant_values=False)
    rows_c = rows_p.reshape(n_chunks, chunk, gsz)
    valid_c = valid_p.reshape(n_chunks, chunk, gsz)
    disc = jnp.where(
        jnp.arange(gsz) < kk,
        1.0 / jnp.log2(2.0 + jnp.arange(gsz, dtype=jnp.float32)),
        0.0,
    )
    topk_mask = (jnp.arange(gsz) < kk).astype(jnp.float32)

    def chunk_step(acc, args):
        r, v = args  # [C, G]
        s = jnp.where(v, s_ext[r], -jnp.inf)
        y = jnp.where(v, y_ext[r], 0.0)
        order = jnp.argsort(s, axis=1, descending=True, stable=True)
        ys = jnp.take_along_axis(y, order, axis=1)
        if kind == "ndcg":
            dcg = jnp.sum((jnp.exp2(ys) - 1.0) * disc[None, :], axis=1)
            y_ideal = jnp.sort(y, axis=1, descending=True)
            idcg = jnp.sum((jnp.exp2(y_ideal) - 1.0) * disc[None, :], axis=1)
            val = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 1.0)
        else:  # map
            rel = (ys > 0).astype(jnp.float32) * topk_mask[None, :]
            prec = jnp.cumsum(rel, axis=1) / jnp.arange(1, gsz + 1, dtype=jnp.float32)
            ap_num = jnp.sum(prec * rel, axis=1)
            ap_den = jnp.sum(rel, axis=1)
            val = jnp.where(ap_den > 0, ap_num / jnp.maximum(ap_den, 1e-12), 0.0)
        nonempty = jnp.any(v, axis=1)
        num, den = acc
        num = num + jnp.sum(jnp.where(nonempty, val, 0.0))
        den = den + jnp.sum(nonempty.astype(jnp.float32))
        return (num, den), None

    (num, den), _ = jax.lax.scan(
        chunk_step, (jnp.float32(0.0), jnp.float32(0.0)), (rows_c, valid_c)
    )
    return num, den


def is_device_metric(name: str, has_groups: bool, has_bounds: bool = False) -> bool:
    """True if the metric can be computed inside the sharded round step
    (keeping the lax.scan batched path available). ``has_bounds``: every
    eval set carries device-resident label bounds (survival training)."""
    base, _ = parse_metric_name(name)
    if base in _ELEMENTWISE:
        return True
    if base in ("auc", "aucpr"):
        return True
    if base in ("ndcg", "map"):
        return has_groups
    if name == "aft-nloglik":
        return has_bounds
    if name == "cox-nloglik":
        return True
    return False


def cox_nloglik_global(m, label, weight):
    """(num, den) of the Breslow negative partial log-likelihood — the
    survival:cox default metric. Risk sets span every shard, so the rows
    are all_gathered and the already-global scalars returned un-psummed
    (identical on every shard). Outside shard_map the local arrays are the
    global arrays."""
    from xgboost_ray_tpu.ops.objectives import (
        cox_risk_terms,
        gather_global_rows,
    )

    (mg, lg, wg), _ = gather_global_rows(m, label, weight)
    _, ev, _, _, logD = cox_risk_terms(mg, lg, wg)
    num = jnp.sum(ev * (logD - mg))
    den = jnp.sum(ev)
    return num, den


def device_metric_contrib(name, margin, label, weight, group_rows, psum,
                          huber_slope: float = 1.0, quantile_alpha=(0.5,),
                          bounds=None, aft_distribution: str = "normal",
                          aft_sigma: float = 1.0):
    """Device-side psum-merged (num, den) for any device metric.

    The caller divides num/den on host (rmse additionally sqrts), so every
    metric is reduced to two replicated scalars. ``bounds`` carries the
    (lower, upper) label-bound arrays for aft-nloglik (the analog of
    ``group_rows`` for the ranking metrics).
    """
    base, arg = parse_metric_name(name)
    if name == "aft-nloglik":
        from xgboost_ray_tpu.ops.survival import aft_nloglik_contrib

        num, den = aft_nloglik_contrib(
            margin, bounds[0], bounds[1], weight,
            distribution=aft_distribution, sigma=aft_sigma,
        )
        return psum(num), psum(den)
    if name == "cox-nloglik":
        # cross-shard risk sets: gather, compute the GLOBAL value on every
        # shard (replicated), and return it WITHOUT psum — it is already
        # the merged scalar
        num, den = cox_nloglik_global(margin[:, 0], label, weight)
        return num, den
    if base in _ELEMENTWISE:
        num, den = elementwise_contrib(
            name, margin, label, weight,
            huber_slope=huber_slope, quantile_alpha=quantile_alpha,
        )
        return psum(num), psum(den)
    if base in ("auc", "aucpr"):
        h = psum(auc_hist(margin, label, weight))
        val = auc_from_hist(h) if base == "auc" else aucpr_from_hist(h)
        return val, jnp.float32(1.0)
    if base in ("ndcg", "map"):
        num, den = rank_metric_contrib(base, margin, label, group_rows, arg)
        return psum(num), psum(den)
    raise ValueError(f"not a device metric: {name!r}")


# --- sort-based metrics (host/global) ---------------------------------------


def _auc_np(score: np.ndarray, label: np.ndarray, weight: np.ndarray) -> float:
    """Weighted ROC AUC via rank statistic (ties handled by midranks)."""
    order = np.argsort(score, kind="stable")
    s, y, w = score[order], label[order], weight[order]
    # midranks for ties on weighted positions
    cw = np.cumsum(w)
    ranks = cw - w / 2.0
    # average ranks within tied score groups (weighted midrank)
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    grp_sum = np.zeros(counts.shape[0])
    grp_w = np.zeros(counts.shape[0])
    np.add.at(grp_sum, inv, ranks * w)
    np.add.at(grp_w, inv, w)
    mid = grp_sum / np.maximum(grp_w, 1e-12)
    ranks = mid[inv]
    pos_w = np.sum(w * (y > 0.5))
    neg_w = np.sum(w * (y <= 0.5))
    if pos_w <= 0 or neg_w <= 0:
        return 0.5
    sum_pos_ranks = np.sum(ranks * w * (y > 0.5))
    # weighted Mann-Whitney U
    auc = (sum_pos_ranks - pos_w * pos_w / 2.0) / (pos_w * neg_w)
    return float(auc)


def _aucpr_np(score: np.ndarray, label: np.ndarray, weight: np.ndarray) -> float:
    """Weighted PR AUC (step integration over descending unique scores)."""
    order = np.argsort(-score, kind="stable")
    y, w = (label[order] > 0.5).astype(np.float64), weight[order].astype(np.float64)
    tp = np.cumsum(w * y)
    fp = np.cumsum(w * (1.0 - y))
    pos_tot = tp[-1] if tp.size else 0.0
    if pos_tot <= 0:
        return 0.0
    # evaluate at the last index of each tied-score run
    s = score[order]
    last = np.r_[s[1:] != s[:-1], True]
    tp, fp = tp[last], fp[last]
    precision = tp / np.maximum(tp + fp, 1e-12)
    recall = tp / pos_tot
    d_recall = np.diff(np.r_[0.0, recall])
    return float(np.sum(precision * d_recall))


def _dcg_at(labels: np.ndarray, k: int) -> float:
    labels = labels[:k]
    gains = (2.0 ** labels - 1.0) / np.log2(np.arange(2, labels.size + 2))
    return float(np.sum(gains))


def _ndcg_np(score: np.ndarray, label: np.ndarray, group_ptr: np.ndarray, k: int) -> float:
    """Mean NDCG@k over query groups. group_ptr: [n_groups+1] row offsets."""
    total, n_groups = 0.0, 0
    for g in range(group_ptr.size - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        ls, ss = label[lo:hi], score[lo:hi]
        order = np.argsort(-ss, kind="stable")
        dcg = _dcg_at(ls[order], k)
        ideal = _dcg_at(np.sort(ls)[::-1], k)
        total += (dcg / ideal) if ideal > 0 else 1.0
        n_groups += 1
    return total / max(n_groups, 1)


def _map_np(score: np.ndarray, label: np.ndarray, group_ptr: np.ndarray, k: int) -> float:
    """Mean average precision@k over groups (binary relevance: label > 0)."""
    total, n_groups = 0.0, 0
    for g in range(group_ptr.size - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        ls = (label[lo:hi] > 0).astype(np.float64)
        order = np.argsort(-score[lo:hi], kind="stable")
        rel = ls[order][:k]
        if rel.sum() == 0:
            total += 0.0
        else:
            prec = np.cumsum(rel) / np.arange(1, rel.size + 1)
            total += float(np.sum(prec * rel) / rel.sum())
        n_groups += 1
    return total / max(n_groups, 1)


def is_elementwise_metric(name: str) -> bool:
    """True if the metric reduces to psum-able (num, den) contributions."""
    base, _ = parse_metric_name(name)
    return base in _ELEMENTWISE


def elementwise_contrib(name: str, margin, label, weight,
                        huber_slope: float = 1.0, quantile_alpha=(0.5,)):
    """Device-side (num, den) contribution for an elementwise metric.

    margin: [N, K], label/weight: [N] (weight 0 for padding rows). The caller
    psums both parts across shards; rmse additionally takes a sqrt on host.
    Parameterized metrics (quantile, mphe) take their objective params so
    host-side evaluation matches the trained objective.
    """
    base, arg = parse_metric_name(name)
    if base == "error" and arg is not None:
        return _error(margin, label, weight, arg)
    if base == "mphe":
        return _mphe(margin, label, weight, slope=huber_slope)
    if base == "quantile":
        return _quantile_pinball(margin, label, weight, _as_alphas(quantile_alpha))
    return _ELEMENTWISE[base](margin, label, weight)


def _as_alphas(quantile_alpha) -> Tuple[float, ...]:
    if isinstance(quantile_alpha, (list, tuple, np.ndarray)):
        return tuple(float(a) for a in quantile_alpha)
    return (float(quantile_alpha),)


def parse_metric_name(name: str) -> Tuple[str, Optional[float]]:
    """Split 'ndcg@10' / 'error@0.7' style names into (base, arg)."""
    if "@" in name:
        base, arg = name.split("@", 1)
        # xgboost's "ndcg@10-" means "minus" convention; strip trailing '-'
        arg = arg.rstrip("-")
        return base, float(arg)
    return name, None


def is_maximize_metric(name: str) -> bool:
    base, _ = parse_metric_name(name)
    return base in ("auc", "ndcg", "map", "aucpr", "auc_exact")


def compute_metric(
    name: str,
    margin: np.ndarray,
    label: np.ndarray,
    weight: Optional[np.ndarray] = None,
    group_ptr: Optional[np.ndarray] = None,
    huber_slope: float = 1.0,
    quantile_alpha=(0.5,),
    bounds=None,
    aft_distribution: str = "normal",
    aft_sigma: float = 1.0,
) -> float:
    """Compute a named metric on full (gathered) arrays.

    margin: [N] or [N, K] raw margin scores; label: [N]; weight: [N] or None;
    group_ptr: [n_groups+1] for ranking metrics. huber_slope/quantile_alpha
    parameterize the mphe and quantile metrics (pass the objective's values
    so evaluation matches training); bounds=(lower, upper) + the aft params
    feed aft-nloglik.
    """
    margin = np.asarray(margin, dtype=np.float32)
    if margin.ndim == 1:
        margin = margin[:, None]
    if name == "aft-nloglik":
        from xgboost_ray_tpu.ops.survival import aft_nloglik_np

        if bounds is None:
            raise ValueError(
                "aft-nloglik needs bounds=(label_lower_bound, "
                "label_upper_bound)."
            )
        return aft_nloglik_np(
            margin, bounds[0], bounds[1], weight,
            distribution=aft_distribution, sigma=aft_sigma,
        )
    label = np.asarray(label, dtype=np.float32)
    weight = (
        np.ones(label.shape[0], np.float32)
        if weight is None or np.size(weight) == 0
        else np.asarray(weight, np.float32)
    )
    if name == "cox-nloglik":
        num, den = cox_nloglik_global(
            jnp.asarray(margin[:, 0]), jnp.asarray(label), jnp.asarray(weight)
        )
        return float(num) / max(float(den), 1e-12)
    base, arg = parse_metric_name(name)
    if base in _ELEMENTWISE:
        num, den = elementwise_contrib(
            name, jnp.asarray(margin), jnp.asarray(label), jnp.asarray(weight),
            huber_slope=huber_slope, quantile_alpha=quantile_alpha,
        )
        num, den = float(num), float(den)
        val = num / max(den, 1e-12)
        return float(np.sqrt(val)) if base in ("rmse", "rmsle") else val
    if base in ("auc", "aucpr", "auc_exact"):
        score = margin[:, 0] if margin.shape[1] == 1 else margin[:, 1]
        fn = _aucpr_np if base == "aucpr" else _auc_np
        return fn(score.astype(np.float64), label, weight.astype(np.float64))
    if base in ("ndcg", "map"):
        if group_ptr is None:
            group_ptr = np.array([0, label.shape[0]], dtype=np.int64)
        k = int(arg) if arg is not None else (2 ** 31 - 1)
        fn = _ndcg_np if base == "ndcg" else _map_np
        return fn(margin[:, 0].astype(np.float64), label.astype(np.float64), group_ptr, k)
    raise ValueError(f"Unsupported eval_metric: {name!r}")
