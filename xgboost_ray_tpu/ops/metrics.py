"""Evaluation metrics for the tpu_hist learner.

TPU-native replacement for xgboost's metric kernels; the reference forwards
``params["eval_metric"]`` to ``xgb.train`` and merges rank-0's
``evals_result`` (``xgboost_ray/main.py:1327-1328``).

Each metric is expressed as a (numerator, denominator) contribution so the
distributed path can psum both and divide — the same trick xgboost's
allreduce-based metric reduction uses. Sort-based metrics (auc, ndcg, map)
operate on full gathered arrays.
"""

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# --- elementwise metrics: margin [N, K], label [N], weight [N] -> (num, den)


def _rmse(margin, label, weight):
    d = margin[:, 0] - label
    return jnp.sum(weight * d * d), jnp.sum(weight)


def _mae(margin, label, weight):
    return jnp.sum(weight * jnp.abs(margin[:, 0] - label)), jnp.sum(weight)


def _logloss(margin, label, weight):
    m = margin[:, 0]
    # numerically stable: log(1+exp(-m)) for y=1, log(1+exp(m)) for y=0
    ll = jnp.where(label > 0.5, jax.nn.softplus(-m), jax.nn.softplus(m))
    return jnp.sum(weight * ll), jnp.sum(weight)


def _error(margin, label, weight, threshold=0.5):
    p = _sigmoid(margin[:, 0])
    wrong = jnp.where((p > threshold) != (label > 0.5), 1.0, 0.0)
    return jnp.sum(weight * wrong), jnp.sum(weight)


def _merror(margin, label, weight):
    pred = jnp.argmax(margin, axis=-1)
    wrong = jnp.where(pred != label.astype(jnp.int32), 1.0, 0.0)
    return jnp.sum(weight * wrong), jnp.sum(weight)


def _mlogloss(margin, label, weight):
    logp = jax.nn.log_softmax(margin, axis=-1)
    k = label.astype(jnp.int32)
    ll = -jnp.take_along_axis(logp, k[:, None], axis=1)[:, 0]
    return jnp.sum(weight * ll), jnp.sum(weight)


def _poisson_nloglik(margin, label, weight):
    m = jnp.clip(margin[:, 0], -30.0, 30.0)
    mu = jnp.exp(m)
    # -log p(y|mu) ignoring log(y!) like xgboost does not: xgboost includes lgamma(y+1)
    nll = mu - label * m + jax.lax.lgamma(label + 1.0)
    return jnp.sum(weight * nll), jnp.sum(weight)


_ELEMENTWISE: Dict[str, Callable] = {
    "rmse": _rmse,
    "mae": _mae,
    "logloss": _logloss,
    "error": _error,
    "merror": _merror,
    "mlogloss": _mlogloss,
    "poisson-nloglik": _poisson_nloglik,
}


# --- sort-based metrics (host/global) ---------------------------------------


def _auc_np(score: np.ndarray, label: np.ndarray, weight: np.ndarray) -> float:
    """Weighted ROC AUC via rank statistic (ties handled by midranks)."""
    order = np.argsort(score, kind="stable")
    s, y, w = score[order], label[order], weight[order]
    # midranks for ties on weighted positions
    cw = np.cumsum(w)
    ranks = cw - w / 2.0
    # average ranks within tied score groups (weighted midrank)
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    grp_sum = np.zeros(counts.shape[0])
    grp_w = np.zeros(counts.shape[0])
    np.add.at(grp_sum, inv, ranks * w)
    np.add.at(grp_w, inv, w)
    mid = grp_sum / np.maximum(grp_w, 1e-12)
    ranks = mid[inv]
    pos_w = np.sum(w * (y > 0.5))
    neg_w = np.sum(w * (y <= 0.5))
    if pos_w <= 0 or neg_w <= 0:
        return 0.5
    sum_pos_ranks = np.sum(ranks * w * (y > 0.5))
    # weighted Mann-Whitney U
    auc = (sum_pos_ranks - pos_w * pos_w / 2.0) / (pos_w * neg_w)
    return float(auc)


def _dcg_at(labels: np.ndarray, k: int) -> float:
    labels = labels[:k]
    gains = (2.0 ** labels - 1.0) / np.log2(np.arange(2, labels.size + 2))
    return float(np.sum(gains))


def _ndcg_np(score: np.ndarray, label: np.ndarray, group_ptr: np.ndarray, k: int) -> float:
    """Mean NDCG@k over query groups. group_ptr: [n_groups+1] row offsets."""
    total, n_groups = 0.0, 0
    for g in range(group_ptr.size - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        ls, ss = label[lo:hi], score[lo:hi]
        order = np.argsort(-ss, kind="stable")
        dcg = _dcg_at(ls[order], k)
        ideal = _dcg_at(np.sort(ls)[::-1], k)
        total += (dcg / ideal) if ideal > 0 else 1.0
        n_groups += 1
    return total / max(n_groups, 1)


def _map_np(score: np.ndarray, label: np.ndarray, group_ptr: np.ndarray, k: int) -> float:
    """Mean average precision@k over groups (binary relevance: label > 0)."""
    total, n_groups = 0.0, 0
    for g in range(group_ptr.size - 1):
        lo, hi = group_ptr[g], group_ptr[g + 1]
        if hi <= lo:
            continue
        ls = (label[lo:hi] > 0).astype(np.float64)
        order = np.argsort(-score[lo:hi], kind="stable")
        rel = ls[order][:k]
        if rel.sum() == 0:
            total += 0.0
        else:
            prec = np.cumsum(rel) / np.arange(1, rel.size + 1)
            total += float(np.sum(prec * rel) / rel.sum())
        n_groups += 1
    return total / max(n_groups, 1)


def is_elementwise_metric(name: str) -> bool:
    """True if the metric reduces to psum-able (num, den) contributions."""
    base, _ = parse_metric_name(name)
    return base in _ELEMENTWISE


def elementwise_contrib(name: str, margin, label, weight):
    """Device-side (num, den) contribution for an elementwise metric.

    margin: [N, K], label/weight: [N] (weight 0 for padding rows). The caller
    psums both parts across shards; rmse additionally takes a sqrt on host.
    """
    base, arg = parse_metric_name(name)
    if base == "error" and arg is not None:
        return _error(margin, label, weight, arg)
    return _ELEMENTWISE[base](margin, label, weight)


def parse_metric_name(name: str) -> Tuple[str, Optional[float]]:
    """Split 'ndcg@10' / 'error@0.7' style names into (base, arg)."""
    if "@" in name:
        base, arg = name.split("@", 1)
        # xgboost's "ndcg@10-" means "minus" convention; strip trailing '-'
        arg = arg.rstrip("-")
        return base, float(arg)
    return name, None


def is_maximize_metric(name: str) -> bool:
    base, _ = parse_metric_name(name)
    return base in ("auc", "ndcg", "map", "aucpr")


def compute_metric(
    name: str,
    margin: np.ndarray,
    label: np.ndarray,
    weight: Optional[np.ndarray] = None,
    group_ptr: Optional[np.ndarray] = None,
) -> float:
    """Compute a named metric on full (gathered) arrays.

    margin: [N] or [N, K] raw margin scores; label: [N]; weight: [N] or None;
    group_ptr: [n_groups+1] for ranking metrics.
    """
    margin = np.asarray(margin, dtype=np.float32)
    if margin.ndim == 1:
        margin = margin[:, None]
    label = np.asarray(label, dtype=np.float32)
    weight = (
        np.ones(label.shape[0], np.float32)
        if weight is None or np.size(weight) == 0
        else np.asarray(weight, np.float32)
    )
    base, arg = parse_metric_name(name)
    if base in _ELEMENTWISE:
        if base == "error" and arg is not None:
            num, den = _error(jnp.asarray(margin), jnp.asarray(label), jnp.asarray(weight), arg)
        else:
            num, den = _ELEMENTWISE[base](
                jnp.asarray(margin), jnp.asarray(label), jnp.asarray(weight)
            )
        num, den = float(num), float(den)
        val = num / max(den, 1e-12)
        return float(np.sqrt(val)) if base == "rmse" else val
    if base == "auc":
        score = margin[:, 0] if margin.shape[1] == 1 else margin[:, 1]
        return _auc_np(score.astype(np.float64), label, weight.astype(np.float64))
    if base in ("ndcg", "map"):
        if group_ptr is None:
            group_ptr = np.array([0, label.shape[0]], dtype=np.int64)
        k = int(arg) if arg is not None else (2 ** 31 - 1)
        fn = _ndcg_np if base == "ndcg" else _map_np
        return fn(margin[:, 0].astype(np.float64), label.astype(np.float64), group_ptr, k)
    raise ValueError(f"Unsupported eval_metric: {name!r}")
