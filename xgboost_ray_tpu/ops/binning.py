"""Quantile sketch and feature binning.

TPU-native replacement for the quantile sketch + CSR binning that the
reference delegates to the xgboost C++ core (DMatrix construction at
``xgboost_ray/main.py:379-445``, iterator feed at
``xgboost_ray/matrix.py:127-196``).

Design
------
Instead of the GK-style weighted quantile sketch, we use a *histogram CDF*
sketch that is (a) fully vectorized, (b) exactly mergeable across shards via a
single ``psum`` — so the distributed sketch is one collective, not a
tree-merge protocol:

1. per-feature global ``min``/``max`` (ignoring NaN)         -> psum-min/max
2. fine-grained weighted histogram (``SKETCH_BINS`` buckets) -> psum
3. cut points read off the merged CDF at equi-weight quantiles

Bin encoding: present values map to ``0 .. max_bin-1``; missing (NaN) maps to
the reserved bin ``max_bin``.  A split at bin ``s`` sends ``bin <= s`` left,
which corresponds to the raw-value rule ``x < cuts[f, s]``.

Everything here is shape-static and jittable; the distributed variants live in
``xgboost_ray_tpu/parallel``.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Number of fine histogram buckets used by the sketch. Must be >= max_bin;
# larger values give a more faithful quantile approximation.
SKETCH_BINS = 2048


def bin_dtype(max_bin: int):
    """Smallest integer dtype that can hold bins 0..max_bin (missing == max_bin)."""
    return np.uint8 if max_bin + 1 <= 256 else np.int16


# ---------------------------------------------------------------------------
# Host-side (numpy) sketch: used by the central data loader, where the driver
# sees the full dataset. Exact quantiles over the observed values.
# ---------------------------------------------------------------------------


def sketch_cuts_np(
    x: np.ndarray, max_bin: int, sample_weight: Optional[np.ndarray] = None
) -> np.ndarray:
    """Compute per-feature cut points on the host. Returns [F, max_bin-1].

    Cut points are the (i+1)/max_bin weighted quantiles of each feature's
    non-missing values. Duplicate cuts are allowed (they produce empty bins,
    which split finding simply never selects).
    """
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"expected 2-D feature matrix, got shape {x.shape}")
    n, num_features = x.shape
    qs = np.arange(1, max_bin, dtype=np.float64) / max_bin
    cuts = np.empty((num_features, max_bin - 1), dtype=np.float32)
    for f in range(num_features):
        col = x[:, f]
        mask = ~np.isnan(col)
        vals = col[mask]
        if vals.size == 0:
            cuts[f] = 0.0
            continue
        if sample_weight is not None:
            w = np.asarray(sample_weight, dtype=np.float64)[mask]
            order = np.argsort(vals, kind="stable")
            sv, sw = vals[order], w[order]
            cw = np.cumsum(sw)
            total = cw[-1]
            if total <= 0:
                cuts[f] = np.quantile(vals, qs).astype(np.float32)
                continue
            idx = np.searchsorted(cw / total, qs, side="left")
            idx = np.clip(idx, 0, sv.size - 1)
            cuts[f] = sv[idx].astype(np.float32)
        else:
            cuts[f] = np.quantile(vals, qs).astype(np.float32)
    return cuts


def bin_matrix_np(x: np.ndarray, cuts: np.ndarray, max_bin: int) -> np.ndarray:
    """Bin a raw feature matrix on the host. Returns [N, F] ints in 0..max_bin.

    bin(x) = #cuts <= x  (``searchsorted(..., side='right')``), NaN -> max_bin.
    """
    x = np.asarray(x, dtype=np.float32)
    n, num_features = x.shape
    out = np.empty((n, num_features), dtype=bin_dtype(max_bin))
    for f in range(num_features):
        col = x[:, f]
        b = np.searchsorted(cuts[f], col, side="right")
        b = np.where(np.isnan(col), max_bin, b)
        out[:, f] = b.astype(out.dtype)
    return out


# ---------------------------------------------------------------------------
# Device-side (jax) sketch: building blocks for the distributed path. The
# min/max and fine histogram are per-shard quantities that the caller merges
# with psum before calling cuts_from_sketch.
# ---------------------------------------------------------------------------


def feature_min_max(x: jnp.ndarray, valid: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-feature (min, max) over valid, non-NaN entries. x: [N, F], valid: [N]."""
    mask = valid[:, None] & ~jnp.isnan(x)
    big = jnp.float32(np.finfo(np.float32).max)
    mn = jnp.min(jnp.where(mask, x, big), axis=0)
    mx = jnp.max(jnp.where(mask, x, -big), axis=0)
    return mn, mx


def sketch_histogram(
    x: jnp.ndarray,
    valid: jnp.ndarray,
    mn: jnp.ndarray,
    mx: jnp.ndarray,
    weight: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fine weighted histogram per feature over [mn, mx]. Returns [F, SKETCH_BINS].

    Mergeable across shards by summation (psum).
    """
    n, num_features = x.shape
    scale = jnp.where(mx > mn, (mx - mn), 1.0)
    t = (x - mn[None, :]) / scale[None, :]
    idx = jnp.clip((t * SKETCH_BINS).astype(jnp.int32), 0, SKETCH_BINS - 1)
    mask = valid[:, None] & ~jnp.isnan(x)
    w = jnp.ones((n,), jnp.float32) if weight is None else weight.astype(jnp.float32)
    wv = jnp.where(mask, w[:, None], 0.0)
    # One scatter-add per feature via segment offsets into a flat histogram.
    flat_idx = idx + (jnp.arange(num_features, dtype=jnp.int32) * SKETCH_BINS)[None, :]
    hist = jnp.zeros((num_features * SKETCH_BINS,), jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(wv.reshape(-1))
    return hist.reshape(num_features, SKETCH_BINS)


def cuts_from_sketch(
    mn: jnp.ndarray, mx: jnp.ndarray, hist: jnp.ndarray, max_bin: int
) -> jnp.ndarray:
    """Turn a merged fine histogram into cut points [F, max_bin-1].

    Reads the CDF at equi-weight quantiles; cut value is the upper edge of the
    bucket where the quantile falls, mapped back to feature scale.
    """
    num_features = hist.shape[0]
    cdf = jnp.cumsum(hist, axis=1)
    total = jnp.maximum(cdf[:, -1:], 1e-12)
    cdf = cdf / total
    qs = jnp.arange(1, max_bin, dtype=jnp.float32) / max_bin  # [B-1]
    # For each quantile, the first bucket whose cdf >= q.
    # cdf: [F, S], qs: [B-1] -> idx [F, B-1]
    idx = jax.vmap(lambda c: jnp.searchsorted(c, qs, side="left"))(cdf)
    idx = jnp.clip(idx, 0, SKETCH_BINS - 1)
    scale = jnp.where(mx > mn, (mx - mn), 1.0)
    edges = (idx.astype(jnp.float32) + 1.0) / SKETCH_BINS  # upper edge in [0,1]
    return mn[:, None] + edges * scale[:, None]


def bin_matrix(x: jnp.ndarray, cuts: jnp.ndarray, max_bin: int) -> jnp.ndarray:
    """Device-side binning. x: [N, F] float, cuts: [F, max_bin-1] -> [N, F] ints."""
    def one_feature(col, c):
        b = jnp.searchsorted(c, col, side="right")
        return jnp.where(jnp.isnan(col), max_bin, b)

    bins = jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(x, cuts)
    return bins.astype(jnp.uint8 if max_bin + 1 <= 256 else jnp.int16)
