"""Quantile sketch and feature binning.

TPU-native replacement for the quantile sketch + CSR binning that the
reference delegates to the xgboost C++ core (DMatrix construction at
``xgboost_ray/main.py:379-445``, iterator feed at
``xgboost_ray/matrix.py:127-196``).

Design
------
Instead of the GK-style weighted quantile sketch, we use a *histogram CDF*
sketch that is (a) fully vectorized, (b) exactly mergeable across shards via a
single ``psum`` — so the distributed sketch is one collective, not a
tree-merge protocol:

1. per-feature global ``min``/``max`` (ignoring NaN)         -> psum-min/max
2. fine-grained weighted histogram (``SKETCH_BINS`` buckets) -> psum
3. cut points read off the merged CDF at equi-weight quantiles

Bin encoding: present values map to ``0 .. max_bin-1``; missing (NaN) maps to
the reserved bin ``max_bin``.  A split at bin ``s`` sends ``bin <= s`` left,
which corresponds to the raw-value rule ``x < cuts[f, s]``.

Everything here is shape-static and jittable; the distributed variants live in
``xgboost_ray_tpu/parallel``.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Number of fine histogram buckets used by the sketch. Must be >= max_bin;
# larger values give a more faithful quantile approximation.
SKETCH_BINS = 2048


def bin_dtype(max_bin: int):
    """Smallest integer dtype that can hold bins 0..max_bin (missing == max_bin)."""
    return np.uint8 if max_bin + 1 <= 256 else np.int16


# ---------------------------------------------------------------------------
# Host-side (numpy) sketch: used by the central data loader, where the driver
# sees the full dataset. Exact quantiles over the observed values.
# ---------------------------------------------------------------------------


def _sketch_cuts_np_loop(
    x: np.ndarray, max_bin: int, sample_weight: Optional[np.ndarray] = None
) -> np.ndarray:
    """Reference per-feature-loop implementation of :func:`sketch_cuts_np`.

    Kept (non-exported) as the bitwise oracle the vectorized version is
    pinned against in ``tests/test_streaming.py`` — host sketching sits on
    the streaming ingest hot path now, so the vectorized form is the one
    that ships.
    """
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"expected 2-D feature matrix, got shape {x.shape}")
    n, num_features = x.shape
    qs = np.arange(1, max_bin, dtype=np.float64) / max_bin
    cuts = np.empty((num_features, max_bin - 1), dtype=np.float32)
    for f in range(num_features):
        col = x[:, f]
        mask = ~np.isnan(col)
        vals = col[mask]
        if vals.size == 0:
            cuts[f] = 0.0
            continue
        if sample_weight is not None:
            w = np.asarray(sample_weight, dtype=np.float64)[mask]
            order = np.argsort(vals, kind="stable")
            sv, sw = vals[order], w[order]
            cw = np.cumsum(sw)
            total = cw[-1]
            if total <= 0:
                cuts[f] = np.quantile(vals, qs).astype(np.float32)
                continue
            idx = np.searchsorted(cw / total, qs, side="left")
            idx = np.clip(idx, 0, sv.size - 1)
            cuts[f] = sv[idx].astype(np.float32)
        else:
            cuts[f] = np.quantile(vals, qs).astype(np.float32)
    return cuts


def sketch_cuts_np(
    x: np.ndarray, max_bin: int, sample_weight: Optional[np.ndarray] = None
) -> np.ndarray:
    """Compute per-feature cut points on the host. Returns [F, max_bin-1].

    Cut points are the (i+1)/max_bin weighted quantiles of each feature's
    non-missing values. Duplicate cuts are allowed (they produce empty bins,
    which split finding simply never selects).

    Vectorized across the feature axis (bitwise-equal to
    :func:`_sketch_cuts_np_loop`): the unweighted path is one
    ``nanquantile`` over axis 0; the weighted path sorts every column at
    once (stable, NaN last, NaN weights zeroed so the tail is inert) and
    reads the weighted CDF per feature with the loop's exact
    ``searchsorted(..., side='left')``, with no float-key arithmetic that
    could flip boundary cases.
    """
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"expected 2-D feature matrix, got shape {x.shape}")
    n, num_features = x.shape
    qs = np.arange(1, max_bin, dtype=np.float64) / max_bin
    nan = np.isnan(x)
    all_nan = nan.all(axis=0)

    def unweighted_cuts(cols: np.ndarray, cols_all_nan: np.ndarray):
        with np.errstate(invalid="ignore"), \
                np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            out = (
                np.nanquantile(cols, qs, axis=0).T.astype(np.float32)
                if n else np.zeros((cols.shape[1], max_bin - 1), np.float32)
            )
        out[cols_all_nan] = 0.0
        return out

    if sample_weight is None or n == 0:
        return unweighted_cuts(x, all_nan)

    w = np.asarray(sample_weight, dtype=np.float64).reshape(n, 1)
    w_eff = np.where(nan, 0.0, w)  # [n, F]
    order = np.argsort(x, axis=0, kind="stable")  # NaN sorts last
    sv = np.take_along_axis(x, order, axis=0)
    sw = np.take_along_axis(w_eff, order, axis=0)
    cw = np.cumsum(sw, axis=0)
    total = cw[-1] if n else np.zeros(num_features)
    weighted_ok = total > 0
    z = cw / np.where(weighted_ok, total, 1.0)[None, :]
    # per-feature searchsorted('left') on the sorted CDF == count of
    # z < q, the loop oracle's exact semantics (a flat float-offset key
    # could collapse z-vs-q boundary cases; per-quantile full-matrix
    # comparison counts would be O(max_bin·N·F)). The zero-weight NaN
    # tail holds z == 1.0 exactly, never counted for q < 1.
    zt = np.ascontiguousarray(z.T)
    idx = np.empty((num_features, max_bin - 1), np.int64)
    for f in range(num_features):
        idx[f] = np.searchsorted(zt[f], qs, side="left")
    finite_n = n - nan.sum(axis=0)
    idx = np.clip(idx, 0, np.maximum(finite_n, 1)[:, None] - 1)
    cuts = np.take_along_axis(sv, idx.T, axis=0).T.astype(np.float32)
    if weighted_ok.all():
        return cuts
    # unweighted fallback only for the zero-total-weight columns (the loop
    # oracle's np.quantile arm) — not a full second quantile pass
    bad = ~weighted_ok
    cuts[bad] = unweighted_cuts(x[:, bad], all_nan[bad])
    return cuts


def validate_feature_types_count(cat_features, n_features: int) -> None:
    """Every categorical feature index must name a real column."""
    if any(i >= n_features for i in cat_features):
        raise ValueError("feature_types has more entries than features.")


def validate_categorical_codes(
    x: np.ndarray, cat_features, max_bin: int
) -> None:
    """Categorical columns must hold integer codes in [0, max_bin-2]
    (NaN = missing is fine). The ONE validator shared by the engine's
    materialized load and the streamed per-chunk mirror, so the two paths
    structurally cannot accept different data."""
    validate_feature_types_count(cat_features, x.shape[1])
    for fi in cat_features:
        col = x[:, fi]
        vals = col[~np.isnan(col)]
        if vals.size and (
            (vals < 0).any()
            or (vals != np.round(vals)).any()
            or vals.max() > max_bin - 2
        ):
            raise ValueError(
                f"categorical feature {fi} must hold integer codes in "
                f"[0, {max_bin - 2}] (max_bin={max_bin}); raise max_bin or "
                f"re-encode the column."
            )


def _f32_order_keys(a: np.ndarray) -> np.ndarray:
    """Strictly order-preserving uint64 keys of float32 values: the
    sign-flipped bit pattern, with -0.0 normalized to +0.0 first so float
    equality survives the transform. NaN keys are unspecified (mask them)."""
    a = np.asarray(a, np.float32) + np.float32(0.0)  # -0.0 -> +0.0
    u = a.view(np.uint32)
    keys = np.where(u >> 31 == 1, ~u, u | np.uint32(0x80000000))
    return keys.astype(np.uint64)


def _bin_matrix_np_loop(x: np.ndarray, cuts: np.ndarray, max_bin: int) -> np.ndarray:
    """Reference per-feature-loop implementation of :func:`bin_matrix_np`
    (the bitwise oracle for the flat-searchsorted version)."""
    x = np.asarray(x, dtype=np.float32)
    n, num_features = x.shape
    out = np.empty((n, num_features), dtype=bin_dtype(max_bin))
    for f in range(num_features):
        col = x[:, f]
        b = np.searchsorted(cuts[f], col, side="right")
        b = np.where(np.isnan(col), max_bin, b)
        out[:, f] = b.astype(out.dtype)
    return out


#: row-block size bounding bin_matrix_np's transient uint64 key buffers
#: (~4 x F x 8 bytes per row in flight; 8192 rows x F=2048 ≈ 0.5 GB would
#: be the 65536 figure — the streaming budget wants these transients small)
_BIN_BLOCK_ROWS = 8192


def bin_matrix_np(x: np.ndarray, cuts: np.ndarray, max_bin: int) -> np.ndarray:
    """Bin a raw feature matrix on the host. Returns [N, F] ints in 0..max_bin.

    bin(x) = #cuts <= x  (``searchsorted(..., side='right')``), NaN -> max_bin.

    One flat ``searchsorted`` over the whole feature axis instead of a
    per-column Python loop (this is the streaming ingest hot path; at
    F=2048 the per-column loop is real time): values and cuts map through
    the order-preserving float32 bit-pattern keys, offset per feature by
    ``f << 32`` so feature blocks can never interleave — bitwise-equal to
    :func:`_bin_matrix_np_loop` by strict monotonicity of the key map.
    """
    x = np.asarray(x, dtype=np.float32)
    cuts = np.asarray(cuts, np.float32)
    if np.isnan(cuts).any():
        # NaN keys are unspecified under _f32_order_keys, so NaN cuts (a
        # feature whose quantiles mix -inf and +inf) would break the flat
        # key array's sortedness and bin silently differently from the
        # per-feature oracle — fail loudly instead
        raise ValueError(
            "cut points contain NaN (a feature holding both -inf and "
            "+inf?); clean non-finite values out of the feature matrix."
        )
    n, num_features = x.shape
    n_cuts = cuts.shape[1]
    feat_off = (np.arange(num_features, dtype=np.uint64) << np.uint64(32))
    flat_cuts = (_f32_order_keys(cuts) + feat_off[:, None]).ravel()
    out = np.empty((n, num_features), dtype=bin_dtype(max_bin))
    for lo in range(0, n, _BIN_BLOCK_ROWS):
        hi = min(lo + _BIN_BLOCK_ROWS, n)
        block = x[lo:hi]
        keys = _f32_order_keys(block) + feat_off[None, :]
        b = np.searchsorted(flat_cuts, keys.ravel(), side="right").reshape(
            hi - lo, num_features
        )
        b = b - np.arange(num_features, dtype=np.int64)[None, :] * n_cuts
        out[lo:hi] = np.where(np.isnan(block), max_bin, b).astype(out.dtype)
    return out


# ---------------------------------------------------------------------------
# Device-side (jax) sketch: building blocks for the distributed path. The
# min/max and fine histogram are per-shard quantities that the caller merges
# with psum before calling cuts_from_sketch.
# ---------------------------------------------------------------------------


def feature_min_max(x: jnp.ndarray, valid: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-feature (min, max) over valid, non-NaN entries. x: [N, F], valid: [N]."""
    mask = valid[:, None] & ~jnp.isnan(x)
    big = jnp.float32(np.finfo(np.float32).max)
    mn = jnp.min(jnp.where(mask, x, big), axis=0)
    mx = jnp.max(jnp.where(mask, x, -big), axis=0)
    return mn, mx


def sketch_histogram(
    x: jnp.ndarray,
    valid: jnp.ndarray,
    mn: jnp.ndarray,
    mx: jnp.ndarray,
    weight: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Fine weighted histogram per feature over [mn, mx]. Returns [F, SKETCH_BINS].

    Mergeable across shards by summation (psum).
    """
    n, num_features = x.shape
    scale = jnp.where(mx > mn, (mx - mn), 1.0)
    t = (x - mn[None, :]) / scale[None, :]
    idx = jnp.clip((t * SKETCH_BINS).astype(jnp.int32), 0, SKETCH_BINS - 1)
    mask = valid[:, None] & ~jnp.isnan(x)
    w = jnp.ones((n,), jnp.float32) if weight is None else weight.astype(jnp.float32)
    wv = jnp.where(mask, w[:, None], 0.0)
    # One scatter-add per feature via segment offsets into a flat histogram.
    flat_idx = idx + (jnp.arange(num_features, dtype=jnp.int32) * SKETCH_BINS)[None, :]
    hist = jnp.zeros((num_features * SKETCH_BINS,), jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(wv.reshape(-1))
    return hist.reshape(num_features, SKETCH_BINS)


def sketch_histogram_items(
    vals: jnp.ndarray, wts: jnp.ndarray, mn: jnp.ndarray, mx: jnp.ndarray
) -> jnp.ndarray:
    """Rasterize per-feature summary items onto the fine sketch grid.

    The streamed-ingest analog of :func:`sketch_histogram`: instead of raw
    rows, the input is one actor's exported quantile-sketch summary —
    ``vals``/``wts`` [F, C] (inert slots hold (+inf, 0)). The bucket-index
    formula is identical, so the merged histogram feeds the SAME
    :func:`cuts_from_sketch` readout and the psum merge shape matches the
    materialized sketch program collective for collective.
    """
    num_features, _cap = vals.shape
    scale = jnp.where(mx > mn, (mx - mn), 1.0)
    t = (vals - mn[:, None]) / scale[:, None]
    idx = jnp.clip((t * SKETCH_BINS).astype(jnp.int32), 0, SKETCH_BINS - 1)
    mask = jnp.isfinite(vals) & (wts > 0)
    wv = jnp.where(mask, wts.astype(jnp.float32), 0.0)
    flat_idx = idx + (
        jnp.arange(num_features, dtype=jnp.int32) * SKETCH_BINS
    )[:, None]
    hist = jnp.zeros((num_features * SKETCH_BINS,), jnp.float32)
    hist = hist.at[flat_idx.reshape(-1)].add(wv.reshape(-1))
    return hist.reshape(num_features, SKETCH_BINS)


def cuts_from_sketch(
    mn: jnp.ndarray, mx: jnp.ndarray, hist: jnp.ndarray, max_bin: int
) -> jnp.ndarray:
    """Turn a merged fine histogram into cut points [F, max_bin-1].

    Reads the CDF at equi-weight quantiles; cut value is the upper edge of the
    bucket where the quantile falls, mapped back to feature scale.
    """
    num_features = hist.shape[0]
    cdf = jnp.cumsum(hist, axis=1)
    total = jnp.maximum(cdf[:, -1:], 1e-12)
    cdf = cdf / total
    qs = jnp.arange(1, max_bin, dtype=jnp.float32) / max_bin  # [B-1]
    # For each quantile, the first bucket whose cdf >= q.
    # cdf: [F, S], qs: [B-1] -> idx [F, B-1]
    idx = jax.vmap(lambda c: jnp.searchsorted(c, qs, side="left"))(cdf)
    idx = jnp.clip(idx, 0, SKETCH_BINS - 1)
    scale = jnp.where(mx > mn, (mx - mn), 1.0)
    edges = (idx.astype(jnp.float32) + 1.0) / SKETCH_BINS  # upper edge in [0,1]
    return mn[:, None] + edges * scale[:, None]


def bin_matrix(x: jnp.ndarray, cuts: jnp.ndarray, max_bin: int) -> jnp.ndarray:
    """Device-side binning. x: [N, F] float, cuts: [F, max_bin-1] -> [N, F] ints."""
    def one_feature(col, c):
        b = jnp.searchsorted(c, col, side="right")
        return jnp.where(jnp.isnan(col), max_bin, b)

    bins = jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(x, cuts)
    return bins.astype(jnp.uint8 if max_bin + 1 <= 256 else jnp.int16)
