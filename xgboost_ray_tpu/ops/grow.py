"""Level-wise (depth-wise) tree growth under static shapes.

TPU-native replacement for xgboost's C++ ``hist``/``gpu_hist`` tree updaters
(the compute core behind ``xgb.train`` in the reference's actor hot loop,
``xgboost_ray/main.py:745-752``).

XLA wants static shapes, so the dynamic frontier of xgboost's tree growth
becomes a *padded heap*: a tree of max_depth D occupies ``2^(D+1)-1`` node
slots (root 0, children of i at 2i+1 / 2i+2). At level d all ``2^d`` node
positions are processed at once; nodes that stopped splitting are masked.
Rows carry an int32 position vector (their node at the current level) that is
updated with pure gathers each level — no host round-trips, no sorting.

The histogram allreduce point is the ``allreduce`` callable: identity on a
single device, ``lax.psum(..., "actors")`` inside the shard_map round step —
this is the exact spot where the reference relied on Rabit (SURVEY §5.8).

Histogram impl choice (and the fate of the hand-written Pallas kernel):
``scatter`` (segment-sum), ``onehot`` (one-hot matmul on the MXU), and
``partition``/``mixed`` (node-contiguous presorted blocks; ``mixed`` =
onehot at tiny fan-out, presorted beyond) are all XLA formulations.
A hand-written Pallas presorted-histogram kernel shipped r2-r4 behind an
opt-in flag and was DELETED in r5: on-chip v5e measurement (r2,
tpu_logs/r2.log) showed it ~1.4x SLOWER per level than the identical-layout
XLA einsum — the blocked one-hot matmul IS the idiomatic MXU formulation,
XLA already fuses/tiles it, and the kernel's only remaining niche
(high-bin scatter-bound shapes) is served by ``partition`` without custom
code. It also rode the axon remote-compile helper, which hung/died
repeatedly on the tunnel. Verdict: a kernel that loses to the compiler on
its own target hardware is dead weight; the learning stays here.
"""

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.histogram import (
    select_small_child_rows,
    node_sums,
    update_partition_order,
    zero_phantom_missing,
)
from xgboost_ray_tpu.ops.split import (
    SplitParams,
    bounded_weight,
    elect_across_feature_shards,
    find_splits,
    leaf_weight,
)

# Disjoint fold_in domains for the per-tree sampling mechanisms, so row
# subsampling and the three column-sampling masks never draw from overlapping
# PRNG streams (a bare fold_in(key, d) for bylevel would collide with
# fold_in(key, 0) for bytree and fold_in(key, rank+1) for subsample).
SALT_SUBSAMPLE = 0x51D1
SALT_BYTREE = 0x51D2
SALT_BYLEVEL = 0x51D3
SALT_BYNODE = 0x51D4
SALT_GOSS = 0x51D5  # gradient_based row sampling (ops/sampling.py)
SALT_SR = 0x51D6  # stochastic gh rounding (gh_precision, ops/objectives.py)


def route_right_binned(bin_vals, split_bin, default_left, is_cat, missing_bin):
    """The one binned routing rule (build_tree, lossguide, binned predict):
    numeric = bin > split_bin goes right, categorical one-vs-rest = the
    candidate category (bin == split_bin) goes left, missing bucket follows
    the learned default. All args broadcast elementwise; ``is_cat`` may be
    None when the tree has no categorical features. predict.py's raw-x
    walk mirrors this rule in value space (``_step_right``)."""
    present_right = bin_vals > split_bin
    if is_cat is not None:
        present_right = jnp.where(is_cat, bin_vals != split_bin, present_right)
    return jnp.where(bin_vals == missing_bin, ~default_left, present_right)


def cat_mask_const(cat_features: tuple, num_features: int):
    """[F] bool compile-time constant marking categorical features (None when
    there are none) — single source for every walk/build/sketch site."""
    if not cat_features:
        return None
    return (
        jnp.zeros((num_features,), bool)
        .at[jnp.asarray(cat_features, jnp.int32)]
        .set(True)
    )


def fshard_local_views(fshard, cat_features, num_features, feat_has_missing,
                       feature_mask):
    """Global-vs-local per-feature state for one feature shard — the ONE
    derivation both growers share.

    Returns ``(cat_mask_global, cat_mask_local, fhm_local, fmask_local,
    f_global_max)``: the GLOBAL (padded) categorical mask for row routing,
    its local slice plus the local feat-has-missing / feature-mask slices
    for the split search, and the max valid global feature index.

    Padded columns (global index >= ``fshard.f_real``) are masked OUT of
    the local split search explicitly: they bin entirely to the missing
    bucket, which scores -inf for any ``min_child_weight > 0``, but at
    ``min_child_weight=0`` an empty child passes the hessian gate and the
    pad column's gain is f32 rounding noise around 0 — electable, which
    would break (R,1)<->(R,C) parity and emit a split on a nonexistent
    feature. The mask closes that hole for every SplitParams setting.
    """
    cat_mask = cat_mask_const(cat_features, fshard.f_padded)
    cat_mask_local = (
        None if cat_mask is None
        else fshard.slice_cols(cat_mask, num_features)
    )
    fhm_local = (
        None if feat_has_missing is None
        else fshard.slice_cols(feat_has_missing, num_features)
    )
    fmask_local = (
        None if feature_mask is None
        else fshard.slice_cols(feature_mask, num_features)
    )
    if fshard.f_padded != fshard.f_real:
        real_cols = (
            fshard.offset(num_features)
            + jnp.arange(num_features, dtype=jnp.int32)
        ) < fshard.f_real
        fmask_local = (
            real_cols if fmask_local is None else (fmask_local & real_cols)
        )
    return cat_mask, cat_mask_local, fhm_local, fmask_local, fshard.f_padded - 1


def sample_feature_mask(
    key: jnp.ndarray,
    n_features: int,
    rate: float,
    log_fw: Optional[jnp.ndarray] = None,
    batch: Optional[int] = None,
) -> jnp.ndarray:
    """Draw a boolean feature-sampling mask ([F], or [batch, F]).

    Without feature weights: independent Bernoulli(rate) per feature (with a
    never-empty guard) — the historical behavior. With ``log_fw`` (log of the
    user's per-feature weights, -inf for weight 0): weighted sampling WITHOUT
    replacement of k = max(1, round(rate * F)) features via Gumbel-top-k, the
    semantics of xgboost's ``feature_weights`` (zero-weight features are never
    drawn; reference surface: xgboost_ray/matrix.py:283-358 + its
    tests/test_end_to_end.py:429-468 demo).
    """
    shape = (n_features,) if batch is None else (batch, n_features)
    if log_fw is None:
        mask = jax.random.uniform(key, shape) < rate
        # never mask out every feature (of a node)
        guard = jnp.arange(n_features) == jnp.argmax(mask, axis=-1, keepdims=batch is not None)
        return mask | guard
    k = max(1, int(round(rate * n_features)))
    scores = log_fw + jax.random.gumbel(key, shape)
    kth = jax.lax.top_k(scores, k)[0][..., -1:]
    mask = (scores >= kth) & jnp.isfinite(log_fw)
    guard = jnp.arange(n_features) == jnp.argmax(scores, axis=-1, keepdims=batch is not None)
    return mask | guard


@dataclasses.dataclass(frozen=True)
class GrowConfig:
    max_depth: int = 6
    max_bin: int = 256
    split: SplitParams = dataclasses.field(default_factory=SplitParams)
    hist_impl: str = "scatter"
    hist_chunk: int = 8192
    # "highest": f32-exact histogram sums (bf16x3 MXU passes); "fast": one
    # rounded bf16 pass (~0.2% relative error on bin sums, 2-3x fewer passes)
    hist_precision: str = "highest"
    # Build only the globally-smaller child's histogram per parent and derive
    # the sibling as parent - child (xgboost hist/gpu_hist's core trick):
    # halves the built/allreduced histogram tensor at every level >= 1, and
    # halves the one-hot matmul FLOPs for the onehot path.
    sibling_subtract: bool = True
    # indices of categorical features (bins are category codes; splits are
    # one-vs-rest partitions routed by equality). Static tuple so it can ride
    # inside this hashable jit-static config.
    cat_features: tuple = ()
    # True when this shard's counts can differ from the allreduced ones
    # (world size > 1): the compacted sibling build then carries a lax.cond
    # fallback for selections overflowing the N//2 buffer. Single-shard
    # training sets False — the selection provably fits, and skipping the
    # cond halves the per-level histogram code to compile.
    shards_may_skew: bool = True
    # per-feature monotone constraints (len == F, values -1/0/+1) or () —
    # xgboost's monotone_constraints via per-node weight-bound propagation
    # (reference passthrough surface: xgboost_ray/main.py:745-752)
    monotone_constraints: tuple = ()
    # tuple of feature-index groups; a node may only split on features that
    # share a constraint set with every feature used on its root path
    # (xgboost's interaction_constraints semantics)
    interaction_constraints: tuple = ()
    # "depthwise" (level-wise, this module) or "lossguide" (best-first,
    # ops/grow_lossguide.py — the LightGBM growth strategy)
    grow_policy: str = "depthwise"
    # leaf budget for lossguide (resolved by the engine: 0 -> 2^max_depth)
    max_leaves: int = 0
    # wire format of the per-level histogram allreduce: "none" (f32 psum) |
    # "int16" | "int8" (row-scale quantized collective) | "int16_block" |
    # "int8_block" (block-scale ppermute ring, no absmax pre-pass;
    # ops/histogram.py). The engine resolves this into the
    # ``hist_allreduce`` callable; carried here so the jit-static config
    # names the full histogram contract. The exact-totals side-psum and 2D
    # min_bytes rescale decisions key on != "none", so the block modes
    # compose through both growers with no further plumbing.
    hist_quant: str = "none"
    # sub-threshold payloads keep the exact f32 psum (latency-bound regime)
    hist_quant_min_bytes: int = 32768
    # elements per in-band scale block (``*_block`` wire modes only)
    hist_quant_block: int = 512
    # on-chip gh storage/accumulation precision: "float32" (default, exact
    # pre-PR program) | "int16" | "int8" — g/h quantized at the objective
    # kernel (stochastic rounding, per-tree pmax scales; ops/objectives.py)
    # and accumulated int -> int32 through the histogram build. The growers
    # key off the traced gh buffer (``gh_scale`` arg); this field names the
    # contract in the jit-static config and the progreg meta.
    gh_precision: str = "float32"

    @property
    def heap_size(self) -> int:
        return (1 << (self.max_depth + 1)) - 1

    def hist_provider(self):
        """Resolve (hist_impl, hist_precision, hist_chunk) into the one
        :class:`~xgboost_ray_tpu.ops.provider.HistogramProvider` object
        every build in this tree dispatches through — the protocol that
        replaced the per-site string branching."""
        from xgboost_ray_tpu.ops.provider import resolve_hist_provider

        return resolve_hist_provider(
            self.hist_impl, precision=self.hist_precision,
            chunk=self.hist_chunk,
        )


class Tree(NamedTuple):
    """One decision tree in padded-heap layout; all arrays [heap_size]."""

    feature: jnp.ndarray  # int32, -1 if leaf/unused
    split_bin: jnp.ndarray  # int32, rows with bin <= split_bin go left
    threshold: jnp.ndarray  # float32 raw-value threshold (go left iff x < threshold)
    default_left: jnp.ndarray  # bool, where missing goes
    is_leaf: jnp.ndarray  # bool
    value: jnp.ndarray  # float32 leaf value (already scaled by learning_rate)
    gain: jnp.ndarray  # float32 split gain at internal nodes (importances)
    cover: jnp.ndarray  # float32 hessian sum reaching each node (xgb 'cover')
    base_weight: jnp.ndarray  # float32 lr-scaled leaf_weight of EVERY node
    #   (internal nodes included) — the E[f(x)|node] estimate Saabas/SHAP
    #   path attribution needs; equals `value` at real leaves


def empty_tree(heap_size: int) -> Tree:
    return Tree(
        feature=jnp.full((heap_size,), -1, jnp.int32),
        split_bin=jnp.zeros((heap_size,), jnp.int32),
        threshold=jnp.zeros((heap_size,), jnp.float32),
        default_left=jnp.zeros((heap_size,), bool),
        is_leaf=jnp.zeros((heap_size,), bool),
        value=jnp.zeros((heap_size,), jnp.float32),
        gain=jnp.zeros((heap_size,), jnp.float32),
        cover=jnp.zeros((heap_size,), jnp.float32),
        base_weight=jnp.zeros((heap_size,), jnp.float32),
    )


def build_tree(
    bins: jnp.ndarray,  # [N, F] int bins (max_bin == missing bucket); may be
    #   a COMPACTED [M, F] row selection (ops/sampling.py) — every shape in
    #   the level loop derives from bins.shape, so the grower is
    #   row-count-blind and sampled builds cost O(M), not O(N_full)
    gh: jnp.ndarray,  # [N, 2] float32 grad/hess (0 for padding rows;
    #   GOSS-amplified for sampled-remainder rows)
    cuts: jnp.ndarray,  # [F, max_bin-1] raw cut values for threshold recovery
    cfg: GrowConfig,
    feature_mask: Optional[jnp.ndarray] = None,  # [F] bool (colsample_bytree)
    level_rng: Optional[jnp.ndarray] = None,  # PRNG key for level/node sampling
    colsample_bylevel: float = 1.0,
    colsample_bynode: float = 1.0,
    allreduce: Callable[[jnp.ndarray], jnp.ndarray] = lambda x: x,
    feature_log_weights: Optional[jnp.ndarray] = None,  # [F] log(fw), -inf at 0
    feat_has_missing: Optional[jnp.ndarray] = None,  # [F] bool, global
    hist_allreduce: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    ar_counter=None,  # AllreduceBytes: scan-scoped byte accounting
    fshard=None,  # ops.provider.FeatureShard on a 2D row x feature mesh
    gh_scale: Optional[jnp.ndarray] = None,  # [2] f32 per-channel scales of a
    #   quantized integer gh buffer (gh_precision; None = f32 legacy path)
    depth_limit: Optional[jnp.ndarray] = None,  # traced int32 scalar: levels
    #   >= depth_limit force still-active nodes to leaves (vmapped-K HPO's
    #   per-lane max_depth; the program still traces cfg.max_depth levels)
):
    """Grow one tree. Returns (Tree, row_value[N]) — row_value is the leaf
    value each row receives (learning-rate scaled), used to update margins
    without re-walking the tree.

    With ``gh_scale`` (``gh_precision`` int8/int16), ``gh`` is the quantized
    INTEGER buffer from ``ops.objectives.quantize_gh``: histogram bins and
    node totals accumulate integer-exact (int -> int32), the histogram
    allreduce rides int32 (exact) or the quantized wire, and the sums are
    dequantized ONCE per level at the split-search/leaf-weight boundary —
    node totals and leaf weights are exact f32 of the quantized values.

    ``hist_allreduce`` merges the per-level [n_nodes, F, nbt, 2] histogram
    across shards (the hot collective; may be quantized per
    ``cfg.hist_quant``). The small exact reductions — per-child row counts
    and final-level node sums — always go through ``allreduce``, so leaf
    weights and the sibling-subtraction child choice never carry
    quantization error. Defaults to ``allreduce`` when not given.

    With ``fshard`` (``feature_parallel`` > 1), ``bins`` is this chip's
    [N_shard, F_pad/C] feature tile and ``cuts``/``feat_has_missing``/
    ``feature_mask`` are GLOBAL (feature-padded) arrays: histograms and the
    split search run over the local tile (the psums above still ride the
    actors axis only), the per-node winner is elected across the feature
    axis (``elect_across_feature_shards``), and the winning feature's bin
    column is owner-broadcast so row routing stays O(rows)."""
    hist_ar = hist_allreduce if hist_allreduce is not None else allreduce
    if cfg.grow_policy == "lossguide":
        if depth_limit is not None:
            # lossguide's frontier scan has no per-level structure to mask;
            # vmapped-K lanes must share max_depth under lossguide (the
            # engine/params validation names the key before tracing)
            raise NotImplementedError(
                "depth_limit (per-lane max_depth) is not supported with "
                "grow_policy='lossguide'"
            )
        from xgboost_ray_tpu.ops.grow_lossguide import build_tree_lossguide

        # engine validation guarantees the unsupported-combination params
        # (bylevel/bynode sampling, constraints) never reach this point
        return build_tree_lossguide(
            bins, gh, cuts, cfg,
            feature_mask=feature_mask,
            allreduce=allreduce,
            feat_has_missing=feat_has_missing,
            hist_allreduce=hist_ar,
            ar_counter=ar_counter,
            fshard=fshard,
            gh_scale=gh_scale,
        )
    n, num_features = bins.shape
    nbt = cfg.max_bin + 1
    lr = cfg.split.learning_rate
    missing_bin = cfg.max_bin
    provider = cfg.hist_provider()

    # quantized-gh mode: sums stay in the exact integer domain until this
    # one dequantization point (gh_scale is None on the f32 legacy path,
    # where deq is the identity and every branch below traces the exact
    # pre-quantization program)
    quant = gh_scale is not None
    if quant:
        from xgboost_ray_tpu.ops.objectives import dequantize_gh_sums

        deq = lambda s: dequantize_gh_sums(s, gh_scale)  # noqa: E731
        gh_zero = jnp.zeros((), gh.dtype)
    else:
        deq = lambda s: s  # noqa: E731
        # the bare literal, NOT jnp.zeros((), f32): the float32 path must
        # keep tracing the exact pre-quantization program (weak-typed
        # constant and all — the schedule-golden/fingerprint discipline)
        gh_zero = 0.0

    if fshard is None:
        cat_mask = cat_mask_const(cfg.cat_features, num_features)
        cat_mask_local = cat_mask
        fhm_local = feat_has_missing
        fmask_tree = feature_mask
        f_global_max = num_features - 1
    else:
        # params.py gates the combinations whose per-level state is
        # global-F; enforce here too for direct build_tree callers
        if (colsample_bylevel < 1.0 or colsample_bynode < 1.0
                or any(cfg.monotone_constraints)
                or cfg.interaction_constraints):
            raise NotImplementedError(
                "per-level/per-node column sampling and constraints are "
                "not supported with feature_parallel > 1"
            )
        # global routing view vs local split-search view of per-feature
        # state (shared derivation incl. the pad-column mask)
        (cat_mask, cat_mask_local, fhm_local, fmask_tree,
         f_global_max) = fshard_local_views(
            fshard, cfg.cat_features, num_features, feat_has_missing,
            feature_mask,
        )

    tree = empty_tree(cfg.heap_size)
    pos = jnp.zeros((n,), jnp.int32)
    done = jnp.zeros((n,), bool)
    row_value = jnp.zeros((n,), jnp.float32)
    active = jnp.ones((1,), bool)

    # monotone constraints: per-node feasible weight interval, narrowed at
    # every constrained-feature split by the children's weight midpoint
    # (xgboost hist's MonotonicConstraint propagation)
    mono_on = any(int(c) != 0 for c in cfg.monotone_constraints)
    mono_arr = lower = upper = None
    if mono_on:
        # the engine validates + zero-pads to exactly num_features
        # (engine.py constraint block); keep one normalization layer
        if len(cfg.monotone_constraints) != num_features:
            raise ValueError(
                f"monotone_constraints length "
                f"{len(cfg.monotone_constraints)} != {num_features} features"
                f" (pad with 0 for unconstrained columns)."
            )
        mono_arr = jnp.asarray(cfg.monotone_constraints, jnp.float32)
        lower = jnp.full((1,), -jnp.inf, jnp.float32)
        upper = jnp.full((1,), jnp.inf, jnp.float32)

    # interaction constraints: per-node set of still-active constraint
    # groups (those containing every feature used on the root path); the
    # allowed features are their union plus the path's own features
    ic_on = len(cfg.interaction_constraints) > 0
    if ic_on:
        import numpy as _np

        n_sets = len(cfg.interaction_constraints)
        mem_np = _np.zeros((n_sets, num_features), bool)
        for s, grp in enumerate(cfg.interaction_constraints):
            for fi in grp:
                if fi < num_features:
                    mem_np[s, fi] = True
        ic_membership = jnp.asarray(mem_np)  # [S, F]
        ic_active = jnp.ones((1, n_sets), bool)
        ic_used = jnp.zeros((1, num_features), bool)
        ic_has_used = jnp.zeros((1,), bool)

    # partition-based providers keep rows sorted by node across levels with
    # an O(N) stable segment split (no per-level argsort)
    track_order = provider.wants_order
    order = counts = None
    if track_order:
        order = jnp.arange(n, dtype=jnp.int32)
        counts = jnp.full((1,), n, jnp.int32)

    prev_hist = None
    for d in range(cfg.max_depth):
        n_nodes = 1 << d
        base = n_nodes - 1

        # Does THIS level's histogram cross the quantization size threshold?
        # (Mirrors quantized_hist_allreduce's static decision on the built
        # tensor; != "none" covers the row AND block wire modes.) Sub-
        # threshold levels take the exact f32 psum, and then node totals
        # also come from the histogram readout — bit-identical to
        # hist_quant="none", so small problems are a provable no-op.
        sib = cfg.sibling_subtract and d > 0
        build_nodes = (n_nodes // 2) if sib else n_nodes
        exact_totals = (
            cfg.hist_quant != "none"
            and build_nodes * num_features * nbt * 2 * 4
            >= cfg.hist_quant_min_bytes
        )

        node_gh_exact = counts_live = None
        if exact_totals:
            # quantized histogram wire: node totals must stay full-precision
            # (they become leaf weights -g/(h+lambda)), and the sibling-
            # subtraction child choice needs exact live-row counts. ONE
            # packed [n_nodes, 3] psum carries both — a single extra small
            # collective per level regardless of mode. Under quantized gh
            # the whole packed payload rides int32 (sums AND counts), so the
            # side-psum is an exact integer reduction dequantized once
            # (deq is the identity on the f32 path).
            cdt = jnp.int32 if quant else jnp.float32
            gh_live = jnp.where(done[:, None], gh_zero, gh)
            packed = allreduce(
                jnp.concatenate(
                    [
                        node_sums(gh_live, pos, n_nodes),
                        jnp.zeros((n_nodes, 1), cdt)
                        .at[pos, 0]
                        .add((~done).astype(cdt)),
                    ],
                    axis=1,
                )
            )
            node_gh_exact = deq(packed[:, :2])
            counts_live = packed[:, 2]

        def _build(gh_b, pos_b, order_b, counts_b, nn, rows_sel=None):
            """One histogram build over nn node slots via the provider.

            ``rows_sel`` is a compacted row-id view into the FULL bins/gh
            (sentinel n for unused slots). Presorted providers consume it
            directly as the row order — the padded-block gather is then the
            only copy; gather-based providers materialize the selection
            first (``ops.provider._gather_rows``).

            The missing bucket is reconstructed by subtraction (node_total -
            sum of regular bins), so with hist_precision="fast" the bf16
            rounding residue of the regular bins lands there; for features
            with NO missing values (known globally from the binned matrix)
            the bucket is exactly zero, so it is zeroed to keep phantom
            missing mass from steering the learned default direction.
            """
            return zero_phantom_missing(
                provider.build(
                    bins, gh_b, pos_b, nn, nbt,
                    order=order_b, counts=counts_b, rows_sel=rows_sel,
                ),
                fhm_local,
            )

        if cfg.sibling_subtract and d > 0 and prev_hist is not None:
            # Sibling subtraction: per parent, build only the globally-smaller
            # child's histogram (indexed by parent -> half the tensor and half
            # the one-hot width) and derive the sibling as parent - child.
            # The choice must be identical on every shard, so it is made from
            # allreduced per-child row counts.
            n_par = n_nodes // 2
            child_counts = (
                counts_live
                if counts_live is not None
                else allreduce(
                    jnp.zeros((n_nodes,), jnp.float32).at[pos].add(
                        (~done).astype(jnp.float32)
                    )
                )
            )
            # [n_par] True when the right child is the (weakly) smaller one
            small_is_right = child_counts[1::2] <= child_counts[0::2]
            if track_order:
                # compact the smaller child's rows into an [N // 2] buffer so
                # every impl processes HALF the rows (vs just zeroing gh).
                # The child choice is GLOBAL (allreduced counts), so on a
                # skewed shard the chosen children's LOCAL rows can exceed
                # N // 2 — lax.cond falls back to the gh-zeroed full-row
                # build there (shard-local control flow; the psum sits
                # outside and runs on every shard either way).
                rows, par_of_slot, _valid_sel, counts_sel = (
                    select_small_child_rows(order, counts, small_is_right)
                )

                def _compacted(_):
                    # done rows only live under inactive parents (they always
                    # route left below their leaf), so the active nodes this
                    # histogram feeds never see them — no done-mask needed;
                    # sentinel slots zero out via the layouts' appended row.
                    return _build(gh, par_of_slot, None, counts_sel, n_par,
                                  rows_sel=rows)

                def _zeroed(_):
                    parent_pos = pos >> 1
                    is_right = (pos & 1).astype(bool)
                    sel = (is_right == small_is_right[parent_pos]) & ~done
                    gh_sel = gh * sel[:, None].astype(gh.dtype)
                    counts_par = counts.reshape(-1, 2).sum(axis=1)
                    return _build(gh_sel, parent_pos, order, counts_par, n_par)

                if cfg.shards_may_skew:
                    fits = counts_sel.sum() <= rows.shape[0]
                    hist_small = hist_ar(
                        jax.lax.cond(fits, _compacted, _zeroed, None)
                    )
                else:
                    hist_small = hist_ar(_compacted(None))
            else:
                parent_pos = pos >> 1
                is_right = (pos & 1).astype(bool)
                sel = (is_right == small_is_right[parent_pos]) & ~done
                gh_sel = gh * sel[:, None].astype(gh.dtype)
                hist_small = hist_ar(
                    _build(gh_sel, parent_pos, None, None, n_par)
                )
            hist_big = prev_hist - hist_small
            sir = small_is_right[:, None, None, None]
            left = jnp.where(sir, hist_big, hist_small)
            right = jnp.where(sir, hist_small, hist_big)
            hist = jnp.stack([left, right], axis=1).reshape(
                (n_nodes,) + hist_small.shape[1:]
            )
        else:
            hist = hist_ar(_build(gh, pos, order, counts, n_nodes))
        prev_hist = hist
        # [n_nodes, 2]: feature 0's buckets cover every row. Under
        # hist_precision="fast" these totals carry the regular bins' bf16
        # rounding (when feature 0 has no missing values its zeroed missing
        # bucket no longer re-balances the sum) — accepted as part of the
        # fast-precision accuracy/speed contract; use the default precision
        # when exact node totals matter.
        # under a quantized wire the histogram's feature-0 totals carry the
        # quantization rounding, which would land straight in the leaf
        # weights -g/(h+lambda); the packed exact psum above keeps node
        # totals full-precision while only the split *search* sees
        # quantized bin sums
        if exact_totals:
            node_gh = node_gh_exact
        else:
            node_gh = hist[:, 0, :, :].sum(axis=1)
            if fshard is not None:
                # each shard's column-0 readout sums a DIFFERENT feature's
                # buckets (same value up to f32 rounding); leaf weights must
                # be identical on every chip, so global feature 0's owner —
                # the column the (R, 1) program reads — wins
                node_gh = fshard.bcast_from_shard0(node_gh)
            # quantized gh + exact int32 wire: the readout sums are exact
            # integer node totals — dequantize at the same boundary the
            # packed side-psum uses, so both totals paths agree bitwise
            node_gh = deq(node_gh)
        # the split search consumes real-valued bin sums: dequantize the
        # merged histogram ONCE per level (identity on the f32 path);
        # prev_hist stays in the quantized domain for sibling subtraction
        hist_sv = deq(hist)

        fmask = fmask_tree
        if colsample_bylevel < 1.0 and level_rng is not None:
            k = jax.random.fold_in(jax.random.fold_in(level_rng, SALT_BYLEVEL), d)
            lmask = sample_feature_mask(
                k, num_features, colsample_bylevel, feature_log_weights
            )
            fmask = lmask if fmask is None else (fmask & lmask)
        if colsample_bynode < 1.0 and level_rng is not None:
            k = jax.random.fold_in(jax.random.fold_in(level_rng, SALT_BYNODE), d)
            nmask = sample_feature_mask(
                k, num_features, colsample_bynode, feature_log_weights,
                batch=n_nodes,
            )
            fmask = nmask if fmask is None else (nmask & fmask[None, :])

        if ic_on:
            # allowed = union of still-active groups + the path's features;
            # a node that has not split yet (root) may use any feature
            union_active = jnp.any(
                ic_active[:, :, None] & ic_membership[None, :, :], axis=1
            )  # [n_nodes, F]
            allowed = jnp.where(
                ic_has_used[:, None], union_active | ic_used, True
            )
            if fmask is None:
                fmask = allowed
            else:
                fmask = (fmask[None, :] if fmask.ndim == 1 else fmask) & allowed

        sp = find_splits(hist_sv, node_gh, cfg.split, feature_mask=fmask,
                         cat_mask=cat_mask_local, monotone=mono_arr,
                         node_lower=lower, node_upper=upper)
        if fshard is not None:
            # the per-shard winner covers only this chip's feature slice;
            # one tiny per-node record gather over the feature axis elects
            # the global split (first-max tie-break — bitwise the (R, 1)
            # argmax)
            sp = elect_across_feature_shards(
                sp, fshard.offset(num_features), cfg.max_bin, cfg.split,
                fshard.axis, counter=fshard.counter,
            )
        valid_split = sp.valid & active
        if depth_limit is not None:
            # per-lane depth ceiling: a lane whose limit is this level keeps
            # its active nodes but may not split them — they fall through to
            # is_new_leaf below with node values from the histogram readout
            # (vs the final-level exact psum, so a depth-masked lane matches
            # its sequential twin to f32 rounding, bitwise only when its
            # limit equals cfg.max_depth and this mask is never engaged)
            valid_split = valid_split & (d < depth_limit)
        if mono_on:
            node_value = lr * bounded_weight(
                node_gh[:, 0], node_gh[:, 1], cfg.split, lower, upper
            )
        else:
            node_value = lr * leaf_weight(
                node_gh[:, 0], node_gh[:, 1], cfg.split
            )
        is_new_leaf = active & ~valid_split

        fsafe = jnp.clip(sp.feature, 0, f_global_max)
        thr = cuts[fsafe, jnp.clip(sp.split_bin, 0, cfg.max_bin - 2)]
        sl = slice(base, base + n_nodes)
        tree = tree._replace(
            feature=tree.feature.at[sl].set(jnp.where(valid_split, sp.feature, -1)),
            split_bin=tree.split_bin.at[sl].set(jnp.where(valid_split, sp.split_bin, 0)),
            threshold=tree.threshold.at[sl].set(jnp.where(valid_split, thr, 0.0)),
            default_left=tree.default_left.at[sl].set(sp.default_left & valid_split),
            is_leaf=tree.is_leaf.at[sl].set(is_new_leaf),
            value=tree.value.at[sl].set(jnp.where(is_new_leaf, node_value, 0.0)),
            gain=tree.gain.at[sl].set(jnp.where(valid_split, sp.gain, 0.0)),
            cover=tree.cover.at[sl].set(jnp.where(active, node_gh[:, 1], 0.0)),
            base_weight=tree.base_weight.at[sl].set(
                jnp.where(active, node_value, 0.0)
            ),
        )

        newly_leafed = is_new_leaf[pos] & ~done
        row_value = jnp.where(newly_leafed, node_value[pos], row_value)
        done = done | newly_leafed

        f_of_row = fsafe[pos]
        if fshard is None:
            b = jnp.take_along_axis(
                bins.astype(jnp.int32), f_of_row[:, None], axis=1
            )[:, 0]
        else:
            # winning feature's bin column, owner-broadcast over the
            # feature axis: one [N] collective — O(rows), not O(rows x F)
            b = fshard.bin_column(bins, f_of_row)
        go_right = route_right_binned(
            b, sp.split_bin[pos], sp.default_left[pos],
            None if cat_mask is None else cat_mask[f_of_row], missing_bin,
        )
        effective_right = jnp.where(done, False, go_right)
        pos = pos * 2 + effective_right.astype(jnp.int32)
        active = jnp.repeat(valid_split, 2)
        if track_order:
            order, counts = update_partition_order(order, counts, effective_right)

        if mono_on:
            # Recompute the CHOSEN split's child weights (same clamped
            # formula find_splits scored with) to narrow the children's
            # feasible interval at the midpoint — xgboost's monotone bound
            # propagation. O(n_nodes * bins), negligible next to the build.
            hist_f = jnp.take_along_axis(
                hist_sv, fsafe[:, None, None, None], axis=1
            )[:, 0]  # [n_nodes, nbt, 2]
            gf, hf = hist_f[..., 0], hist_f[..., 1]
            sbin_c = jnp.clip(sp.split_bin, 0, cfg.max_bin - 2)[:, None]
            gl_c = jnp.take_along_axis(
                jnp.cumsum(gf[:, : cfg.max_bin], axis=-1), sbin_c, axis=1
            )[:, 0]
            hl_c = jnp.take_along_axis(
                jnp.cumsum(hf[:, : cfg.max_bin], axis=-1), sbin_c, axis=1
            )[:, 0]
            if cat_mask is not None:
                is_cat = cat_mask[fsafe]
                gl_c = jnp.where(
                    is_cat, jnp.take_along_axis(gf, sbin_c, axis=1)[:, 0], gl_c
                )
                hl_c = jnp.where(
                    is_cat, jnp.take_along_axis(hf, sbin_c, axis=1)[:, 0], hl_c
                )
            gl_c = jnp.where(sp.default_left, gl_c + gf[:, cfg.max_bin], gl_c)
            hl_c = jnp.where(sp.default_left, hl_c + hf[:, cfg.max_bin], hl_c)
            wl = bounded_weight(gl_c, hl_c, cfg.split, lower, upper)
            wr = bounded_weight(
                node_gh[:, 0] - gl_c, node_gh[:, 1] - hl_c, cfg.split,
                lower, upper,
            )
            mid = 0.5 * (wl + wr)
            c = jnp.where(valid_split, mono_arr[fsafe], 0.0)
            lower_l = jnp.where(c < 0, jnp.maximum(lower, mid), lower)
            upper_l = jnp.where(c > 0, jnp.minimum(upper, mid), upper)
            lower_r = jnp.where(c > 0, jnp.maximum(lower, mid), lower)
            upper_r = jnp.where(c < 0, jnp.minimum(upper, mid), upper)
            lower = jnp.stack([lower_l, lower_r], axis=1).reshape(-1)
            upper = jnp.stack([upper_l, upper_r], axis=1).reshape(-1)

        if ic_on:
            contains_f = ic_membership.T[fsafe]  # [n_nodes, S]
            ic_active = jnp.where(
                valid_split[:, None], ic_active & contains_f, ic_active
            )
            f_onehot = jnp.arange(num_features)[None, :] == fsafe[:, None]
            ic_used = ic_used | (valid_split[:, None] & f_onehot)
            ic_has_used = ic_has_used | valid_split
            ic_active = jnp.repeat(ic_active, 2, axis=0)
            ic_used = jnp.repeat(ic_used, 2, axis=0)
            ic_has_used = jnp.repeat(ic_has_used, 2)

    # Final level: every still-active node is a leaf.
    n_nodes = 1 << cfg.max_depth
    base = n_nodes - 1
    gh_final = jnp.where(done[:, None], gh_zero, gh)
    node_gh = deq(allreduce(node_sums(gh_final, pos, n_nodes)))
    if mono_on:
        node_value = lr * bounded_weight(
            node_gh[:, 0], node_gh[:, 1], cfg.split, lower, upper
        )
    else:
        node_value = lr * leaf_weight(node_gh[:, 0], node_gh[:, 1], cfg.split)
    sl = slice(base, base + n_nodes)
    tree = tree._replace(
        is_leaf=tree.is_leaf.at[sl].set(active),
        value=tree.value.at[sl].set(jnp.where(active, node_value, 0.0)),
        cover=tree.cover.at[sl].set(jnp.where(active, node_gh[:, 1], 0.0)),
        base_weight=tree.base_weight.at[sl].set(
            jnp.where(active, node_value, 0.0)
        ),
    )
    row_value = jnp.where(done, row_value, node_value[pos])
    return tree, row_value


def predict_tree_binned(
    tree: Tree, bins: jnp.ndarray, max_depth: int, missing_bin: int,
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Walk one tree over pre-binned rows; returns leaf value per row [N].

    Used during training to update eval-set margins with each new tree
    without leaving the device.
    """
    n, num_features = bins.shape
    idx = jnp.zeros((n,), jnp.int32)
    b32 = bins.astype(jnp.int32)
    cat_mask = cat_mask_const(cat_features, num_features)
    for _ in range(max_depth):
        f = jnp.clip(tree.feature[idx], 0, num_features - 1)
        bv = jnp.take_along_axis(b32, f[:, None], axis=1)[:, 0]
        go_right = route_right_binned(
            bv, tree.split_bin[idx], tree.default_left[idx],
            None if cat_mask is None else cat_mask[f], missing_bin,
        )
        nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
        idx = jnp.where(tree.is_leaf[idx], idx, nxt)
    return tree.value[idx]


def predict_tree_binned_fsharded(
    tree: Tree, bins: jnp.ndarray, max_depth: int, missing_bin: int,
    fshard, cat_features: tuple = (),
) -> jnp.ndarray:
    """``predict_tree_binned`` over a feature-sharded [N, F_pad/C] tile.

    The tree's split features are global indices, so each depth step
    owner-broadcasts the needed bin column across the feature axis (one
    [N] collective per step — the O(rows x depth) cost the 2D mesh pays
    for eval-set / sampled-build margin walks instead of replicating F).
    Routing state (idx) stays identical on every feature shard.
    """
    n = bins.shape[0]
    idx = jnp.zeros((n,), jnp.int32)
    cat_mask = cat_mask_const(cat_features, fshard.f_padded)
    for _ in range(max_depth):
        f = jnp.clip(tree.feature[idx], 0, fshard.f_padded - 1)
        bv = fshard.bin_column(bins, f)
        go_right = route_right_binned(
            bv, tree.split_bin[idx], tree.default_left[idx],
            None if cat_mask is None else cat_mask[f], missing_bin,
        )
        nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
        idx = jnp.where(tree.is_leaf[idx], idx, nxt)
    return tree.value[idx]
