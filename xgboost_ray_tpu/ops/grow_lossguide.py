"""Leaf-wise (``grow_policy=lossguide``) tree growth under static shapes.

xgboost's lossguide policy repeatedly splits the FRONTIER LEAF WITH THE
HIGHEST GAIN until ``max_leaves`` is reached — depth-asymmetric trees that
chase the best objective reduction first (the LightGBM growth strategy;
reference surface: the params dict forwarded untouched at
``xgboost_ray/main.py:745-752``).

TPU-native formulation: the dynamic best-first loop becomes ONE
``lax.scan`` of ``max_leaves - 1`` identical steps over a static frontier
table of ``2*max_leaves - 1`` entries (every node the tree can ever
create). Each step: argmax over frontier gains -> split that leaf (dynamic
heap slot, pure scatters) -> route only its rows -> build the two
children's histograms (one-hot MXU pass over all rows, psum-merged at the
reference's Rabit point) -> score their best splits into the two
append-slots ``1+2t, 2+2t``. Append-only indexing keeps every shape static
and the whole tree build a single compiled program.

Cost note: each step's histogram pass is O(N) regardless of the split
leaf's row count (rows outside the leaf are masked, not skipped), so a
full lossguide tree costs O(N * max_leaves) histogram work vs depthwise's
O(N * max_depth). That is the static-shape price; the constant is one
bf16/f32 one-hot matmul per step, which the MXU absorbs.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.grow import (
    GrowConfig,
    Tree,
    cat_mask_const,
    empty_tree,
    fshard_local_views,
    route_right_binned,
)
from xgboost_ray_tpu.ops.histogram import (
    hist_onehot,
    node_sums,
    zero_phantom_missing,
)
from xgboost_ray_tpu.ops.split import (
    elect_across_feature_shards,
    find_splits,
    leaf_weight,
)


def build_tree_lossguide(
    bins: jnp.ndarray,  # [N, F] int bins (max_bin == missing bucket); may be
    #   a compacted [M, F] row selection (ops/sampling.py) — each step's
    #   O(N) one-hot pass then costs O(M)
    gh: jnp.ndarray,  # [N, 2] grad/hess (0 for padding rows; GOSS-amplified
    #   for sampled-remainder rows)
    cuts: jnp.ndarray,  # [F, max_bin-1] raw cut values
    cfg: GrowConfig,
    feature_mask: Optional[jnp.ndarray] = None,  # [F] bool (colsample_bytree)
    allreduce: Callable[[jnp.ndarray], jnp.ndarray] = lambda x: x,
    feat_has_missing: Optional[jnp.ndarray] = None,
    hist_allreduce: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    ar_counter=None,  # AllreduceBytes: the scan body traces once, runs
    #   leaves-1 times — the repeated() scope keeps byte accounting exact
    fshard=None,  # ops.provider.FeatureShard on a 2D row x feature mesh
    gh_scale: Optional[jnp.ndarray] = None,  # [2] f32 scales of a quantized
    #   integer gh buffer (gh_precision); None = the f32 legacy path
):
    """Grow one leaf-wise tree. Returns (Tree, row_value[N]) — the same
    contract as ``build_tree`` so the engine's round step is policy-blind.

    With ``gh_scale`` the per-step 2-node histogram accumulates the integer
    gh buffer exactly (int -> int32) and bin sums / node totals are
    dequantized once at the split-search boundary, mirroring ``build_tree``'s
    quantized-gh contract.

    ``hist_allreduce`` merges the per-step 2-node histogram (may be
    quantized per ``cfg.hist_quant``); exact node totals ride ``allreduce``
    when quantization is on, mirroring the depthwise grower. With
    ``fshard`` the per-step histogram/split search covers this chip's
    feature tile and the step's winner is elected over the feature axis,
    mirroring ``build_tree``'s 2D contract (bins local, cuts/
    feat_has_missing/feature_mask global feature-padded)."""
    hist_ar = hist_allreduce if hist_allreduce is not None else allreduce
    quant = gh_scale is not None
    if quant:
        from xgboost_ray_tpu.ops.objectives import dequantize_gh_sums

        deq = lambda s: dequantize_gh_sums(s, gh_scale)  # noqa: E731
    else:
        deq = lambda s: s  # noqa: E731
    n, num_features = bins.shape
    nbt = cfg.max_bin + 1
    missing_bin = cfg.max_bin
    lr = cfg.split.learning_rate
    heap = cfg.heap_size
    leaves = max(1, int(cfg.max_leaves))
    n_ent = 2 * leaves - 1
    if fshard is None:
        cat_mask = cat_mask_const(cfg.cat_features, num_features)
        cat_mask_local = cat_mask
        fhm_local = feat_has_missing
        fmask_local = feature_mask
        f_global_max = num_features - 1
    else:
        # shared global-vs-local derivation (incl. the pad-column mask)
        (cat_mask, cat_mask_local, fhm_local, fmask_local,
         f_global_max) = fshard_local_views(
            fshard, cfg.cat_features, num_features, feat_has_missing,
            feature_mask,
        )

    def _hist(gh_b, pos_b, nn):
        # node totals downstream are read from the zeroed histogram's
        # feature-0 row, so under hist_precision="fast" they carry the
        # regular bins' bf16 rounding — the SAME accepted contract as the
        # depthwise grower's node_gh (see ops/grow.py's node_gh comment).
        # Always the one-hot MXU pass: the per-step 2-node fan-out is the
        # regime where every provider would pick it anyway (params.py pins
        # hist_impl to auto|onehot for lossguide).
        h = hist_onehot(
            bins, gh_b, pos_b, nn, nbt,
            chunk=cfg.hist_chunk, precision=cfg.hist_precision,
        )
        return zero_phantom_missing(hist_ar(h), fhm_local)

    def _node_gh(hist, gh_b, pos_b, nn):
        # [nn, 2] totals: exact psum when the histogram wire is quantized
        # (leaf weights must not carry quantization rounding), feature-0
        # readout otherwise (free). Mirrors quantized_hist_allreduce's
        # static size-threshold decision — != "none" covers row and block
        # wire modes alike — so sub-threshold trees stay bit-identical to
        # hist_quant="none".
        quantized = (
            cfg.hist_quant != "none"
            and nn * num_features * nbt * 2 * 4 >= cfg.hist_quant_min_bytes
        )
        if quantized:
            # under quantized gh the side-psum rides int32 (exact) and is
            # dequantized here — the one boundary both totals paths share
            return deq(allreduce(node_sums(gh_b, pos_b, nn)))
        totals = hist[:, 0, :, :].sum(axis=1)
        if fshard is not None:
            # column-0 readout differs per feature shard in f32 rounding;
            # global feature 0's owner wins (see build_tree's node_gh)
            totals = fshard.bcast_from_shard0(totals)
        return deq(totals)

    tree = empty_tree(heap)
    pos = jnp.zeros((n,), jnp.int32)

    # --- root: evaluate its best split, seed the frontier -------------------
    root_hist = _hist(gh, pos, 1)  # [1, F_local, nbt, 2]
    root_gh = _node_gh(root_hist, gh, pos, 1)  # [1, 2]
    sp0 = find_splits(deq(root_hist), root_gh, cfg.split,
                      feature_mask=fmask_local, cat_mask=cat_mask_local)
    if fshard is not None:
        sp0 = elect_across_feature_shards(
            sp0, fshard.offset(num_features), cfg.max_bin, cfg.split,
            fshard.axis, counter=fshard.counter,
        )
    root_value = lr * leaf_weight(root_gh[:, 0], root_gh[:, 1], cfg.split)[0]
    tree = tree._replace(
        is_leaf=tree.is_leaf.at[0].set(True),
        value=tree.value.at[0].set(root_value),
        cover=tree.cover.at[0].set(root_gh[0, 1]),
        base_weight=tree.base_weight.at[0].set(root_value),
    )

    # frontier entry table (append-only; entry 0 = root)
    ent_pos = jnp.full((n_ent,), -1, jnp.int32).at[0].set(0)
    ent_active = jnp.zeros((n_ent,), bool).at[0].set(True)
    can_root = heap > 1  # max_depth >= 1
    ent_gain = jnp.full((n_ent,), -jnp.inf).at[0].set(
        jnp.where(sp0.valid[0] & can_root, sp0.gain[0], -jnp.inf)
    )
    ent_feat = jnp.zeros((n_ent,), jnp.int32).at[0].set(sp0.feature[0])
    ent_bin = jnp.zeros((n_ent,), jnp.int32).at[0].set(sp0.split_bin[0])
    ent_dl = jnp.zeros((n_ent,), bool).at[0].set(sp0.default_left[0])

    b32 = bins.astype(jnp.int32)

    def body(carry, t):
        tree, pos, ent_pos, ent_active, ent_gain, ent_feat, ent_bin, ent_dl = carry

        scores = jnp.where(ent_active, ent_gain, -jnp.inf)
        i = jnp.argmax(scores)
        do_split = jnp.isfinite(scores[i])

        slot = ent_pos[i]
        feat = jnp.clip(ent_feat[i], 0, f_global_max)
        sbin = ent_bin[i]
        dl = ent_dl[i]
        thr = cuts[feat, jnp.clip(sbin, 0, cfg.max_bin - 2)]
        slot_c = jnp.maximum(slot, 0)

        # parent leaf -> internal node (scatters guarded by do_split)
        def setw(arr, idx, new):
            return arr.at[idx].set(jnp.where(do_split, new, arr[idx]))

        tree = tree._replace(
            feature=setw(tree.feature, slot_c, feat),
            split_bin=setw(tree.split_bin, slot_c, sbin),
            threshold=setw(tree.threshold, slot_c, thr),
            default_left=setw(tree.default_left, slot_c, dl),
            is_leaf=setw(tree.is_leaf, slot_c, False),
            value=setw(tree.value, slot_c, 0.0),
            gain=setw(tree.gain, slot_c, ent_gain[i]),
        )

        # route ONLY this leaf's rows
        sel = (pos == slot) & do_split
        if fshard is None:
            bv = jnp.take_along_axis(b32, jnp.full((n, 1), feat), axis=1)[:, 0]
        else:
            # split feature is a global index; owner-broadcast its column
            bv = fshard.bin_column(bins, jnp.full((n,), feat))
        go_right = route_right_binned(
            bv, sbin, dl,
            None if cat_mask is None else cat_mask[feat], missing_bin,
        )
        l_slot, r_slot = 2 * slot_c + 1, 2 * slot_c + 2
        pos = jnp.where(sel, jnp.where(go_right, r_slot, l_slot), pos)

        # the two children's histograms + best splits
        gh_sel = gh * sel[:, None].astype(gh.dtype)
        pos2 = go_right.astype(jnp.int32)
        hist2 = _hist(gh_sel, pos2, 2)  # [2, F_local, nbt, 2]
        child_gh = _node_gh(hist2, gh_sel, pos2, 2)  # [2, 2]
        sp2 = find_splits(deq(hist2), child_gh, cfg.split,
                          feature_mask=fmask_local, cat_mask=cat_mask_local)
        if fshard is not None:
            sp2 = elect_across_feature_shards(
                sp2, fshard.offset(num_features), cfg.max_bin, cfg.split,
                fshard.axis, counter=fshard.counter,
            )
        child_slots = jnp.stack([l_slot, r_slot])
        # children may split further only while their own children fit the
        # depth-bounded heap
        can_deepen = 2 * child_slots + 2 < heap
        child_gain = jnp.where(
            sp2.valid & can_deepen & do_split, sp2.gain, -jnp.inf
        )
        child_value = lr * leaf_weight(child_gh[:, 0], child_gh[:, 1],
                                       cfg.split)

        def set2(arr, new):
            upd = jnp.where(do_split, new, arr[child_slots])
            return arr.at[child_slots].set(upd)

        tree = tree._replace(
            is_leaf=set2(tree.is_leaf, jnp.array([True, True])),
            value=set2(tree.value, child_value),
            cover=set2(tree.cover, child_gh[:, 1]),
            base_weight=set2(tree.base_weight, child_value),
        )

        # frontier bookkeeping: retire entry i, append children at 1+2t, 2+2t
        ent_active = ent_active.at[i].set(
            jnp.where(do_split, False, ent_active[i])
        )
        k = 1 + 2 * t
        ks = jnp.stack([k, k + 1])

        def app(arr, new, fill):
            upd = jnp.where(do_split, new, jnp.asarray(fill, arr.dtype))
            return arr.at[ks].set(upd)

        ent_pos = app(ent_pos, child_slots, -1)
        ent_active = app(ent_active, jnp.array([True, True]), False)
        ent_gain = app(ent_gain, child_gain, -jnp.inf)
        ent_feat = app(ent_feat, sp2.feature, 0)
        ent_bin = app(ent_bin, sp2.split_bin, 0)
        ent_dl = app(ent_dl, sp2.default_left, False)

        return (tree, pos, ent_pos, ent_active, ent_gain, ent_feat, ent_bin,
                ent_dl), None

    if leaves > 1:
        import contextlib

        carry = (tree, pos, ent_pos, ent_active, ent_gain, ent_feat, ent_bin,
                 ent_dl)
        scope = (
            ar_counter.repeated(leaves - 1)
            if ar_counter is not None
            else contextlib.nullcontext()
        )
        # the feature-axis counter (election gather + bin-column psum in
        # the scan body) multiplies by the step count too
        fscope = (
            fshard.counter.repeated(leaves - 1)
            if fshard is not None and fshard.counter is not None
            else contextlib.nullcontext()
        )
        with scope, fscope:
            carry, _ = jax.lax.scan(body, carry, jnp.arange(leaves - 1))
        tree, pos = carry[0], carry[1]

    row_value = tree.value[pos]
    return tree, row_value
