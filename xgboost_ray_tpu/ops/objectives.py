"""Objective functions: gradient/hessian closures for the tpu_hist learner.

TPU-native replacement for xgboost's C++ objective kernels (the reference
passes ``params["objective"]`` straight through to ``xgb.train`` at
``xgboost_ray/main.py:745-752``; custom objectives are exercised by
``tests/test_xgboost_api.py:77-150``).

Each objective is a small pure-function bundle; grad/hess are computed on
device inside the jitted round step (closed-form, not autodiff — these are
classic second-order formulas and closed-form is both faster and matches
xgboost semantics exactly). Ranking objectives live in ``ranking.py``.

Vmapped-K HPO (``engine.step_vmapped``) maps the whole round — including
``grad_hess`` and the ``quantize_gh`` source quantization — over a leading
lane axis: margins arrive as ``[K, N, out]`` and every formula here batches
element-wise with no change (nothing in an objective may branch on a traced
per-lane param, which is why the lane-vectorizable set in ``params.py``
only contains split-arithmetic scalars; ``scale_pos_weight`` et al. stay
static per program). Per-lane gradients therefore differ only through the
lane's own margins/PRNG stream, keeping each lane's gh bitwise-identical
to its sequential twin's.
"""

import dataclasses
import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.constants import AXIS_ACTORS


@dataclasses.dataclass(frozen=True)
class Objective:
    name: str
    # margin [N, K], label [N] (float; class index for multiclass),
    # weight [N] -> (grad [N, K], hess [N, K])
    grad_hess: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]
    # margin [N, K] -> user-facing prediction (probabilities / values)
    transform: Callable[[jnp.ndarray], jnp.ndarray]
    # number of model outputs per row (1, or num_class for softprob/softmax)
    num_outputs: int = 1
    # default eval metric name (used when user supplies none)
    default_metric: str = "rmse"
    # map user base_score (prediction space) -> initial margin
    base_score_to_margin: Callable[[float], float] = lambda s: s
    default_base_score: float = 0.5
    # "value" | "prob" | "class": what transform returns
    output_kind: str = "value"


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _make_squarederror() -> Objective:
    def gh(margin, label, weight):
        g = (margin[:, 0] - label) * weight
        h = weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:squarederror",
        grad_hess=gh,
        transform=lambda m: m[:, 0],
        default_metric="rmse",
        default_base_score=0.5,
    )


def _make_absoluteerror() -> Objective:
    # xgboost uses g = sign(pred - y), h = 1 (with line search refinements we skip)
    def gh(margin, label, weight):
        g = jnp.sign(margin[:, 0] - label) * weight
        h = weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:absoluteerror",
        grad_hess=gh,
        transform=lambda m: m[:, 0],
        default_metric="mae",
        default_base_score=0.5,
    )


def _make_logistic(name: str, raw_output: bool, scale_pos_weight: float) -> Objective:
    def gh(margin, label, weight):
        p = _sigmoid(margin[:, 0])
        w = weight * jnp.where(label > 0.5, scale_pos_weight, 1.0)
        g = (p - label) * w
        h = jnp.maximum(p * (1.0 - p), 1e-16) * w
        return g[:, None], h[:, None]

    return Objective(
        name=name,
        grad_hess=gh,
        transform=(lambda m: m[:, 0]) if raw_output else (lambda m: _sigmoid(m[:, 0])),
        default_metric="logloss",
        base_score_to_margin=lambda s: float(jnp.log(s / (1.0 - s))) if 0 < s < 1 else 0.0,
        default_base_score=0.5,
        output_kind="value" if raw_output else "prob",
    )


def _make_softmax(num_class: int, prob_output: bool) -> Objective:
    def gh(margin, label, weight):
        p = jax.nn.softmax(margin, axis=-1)  # [N, K]
        y = jax.nn.one_hot(label.astype(jnp.int32), num_class, dtype=p.dtype)
        g = (p - y) * weight[:, None]
        h = jnp.maximum(2.0 * p * (1.0 - p), 1e-16) * weight[:, None]
        return g, h

    def transform(m):
        p = jax.nn.softmax(m, axis=-1)
        return p if prob_output else jnp.argmax(p, axis=-1).astype(jnp.float32)

    return Objective(
        name="multi:softprob" if prob_output else "multi:softmax",
        grad_hess=gh,
        transform=transform,
        num_outputs=num_class,
        default_metric="mlogloss" if prob_output else "merror",
        base_score_to_margin=lambda s: 0.0,
        default_base_score=0.5,
        output_kind="prob" if prob_output else "class",
    )


def _make_quantile(alpha) -> Objective:
    """reg:quantileerror (xgboost >= 2.0): pinball loss at one or several
    quantiles. Multi-alpha trains one output per quantile (round-major trees,
    like multiclass); g = 1{m >= y} - alpha, h = 1 (xgboost's convention for
    the curvature-free pinball loss)."""
    alphas = tuple(
        float(a) for a in (alpha if isinstance(alpha, (list, tuple)) else [alpha])
    )
    if not alphas or not all(0.0 < a < 1.0 for a in alphas):
        raise ValueError(
            f"quantile_alpha must be in (0, 1), got {alphas!r}"
        )
    k = len(alphas)
    a_vec = jnp.asarray(alphas, jnp.float32)

    def gh(margin, label, weight):
        ge = (margin >= label[:, None]).astype(jnp.float32)  # [N, K]
        g = (ge - a_vec[None, :]) * weight[:, None]
        h = jnp.broadcast_to(weight[:, None], g.shape)
        return g, h

    return Objective(
        name="reg:quantileerror",
        grad_hess=gh,
        transform=(lambda m: m) if k > 1 else (lambda m: m[:, 0]),
        num_outputs=k,
        default_metric="quantile",
        default_base_score=0.5,
    )


def _make_poisson() -> Objective:
    # log-link: pred = exp(margin); g = exp(m) - y; h = exp(m)
    def gh(margin, label, weight):
        mu = jnp.exp(jnp.clip(margin[:, 0], -30.0, 30.0))
        g = (mu - label) * weight
        h = jnp.maximum(mu, 1e-16) * weight
        return g[:, None], h[:, None]

    return Objective(
        name="count:poisson",
        grad_hess=gh,
        transform=lambda m: jnp.exp(m[:, 0]),
        default_metric="poisson-nloglik",
        base_score_to_margin=lambda s: float(jnp.log(jnp.maximum(s, 1e-16))),
        default_base_score=0.5,
    )


def _make_gamma() -> Objective:
    # gamma deviance, log link: g = 1 - y*exp(-m), h = y*exp(-m)
    def gh(margin, label, weight):
        ym = label * jnp.exp(-jnp.clip(margin[:, 0], -30.0, 30.0))
        g = (1.0 - ym) * weight
        h = jnp.maximum(ym, 1e-6) * weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:gamma",
        grad_hess=gh,
        transform=lambda m: jnp.exp(m[:, 0]),
        default_metric="rmse",
        base_score_to_margin=lambda s: float(jnp.log(jnp.maximum(s, 1e-16))),
        default_base_score=0.5,
    )


def _make_tweedie(rho: float) -> Objective:
    # tweedie deviance, log link (1 < rho < 2)
    def gh(margin, label, weight):
        m = jnp.clip(margin[:, 0], -30.0, 30.0)
        a = label * jnp.exp((1.0 - rho) * m)
        b = jnp.exp((2.0 - rho) * m)
        g = (-a + b) * weight
        h = jnp.maximum(-(1.0 - rho) * a + (2.0 - rho) * b, 1e-6) * weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:tweedie",
        grad_hess=gh,
        transform=lambda m: jnp.exp(m[:, 0]),
        default_metric="rmse",
        base_score_to_margin=lambda s: float(jnp.log(jnp.maximum(s, 1e-16))),
        default_base_score=0.5,
    )


def _make_hinge() -> Objective:
    # binary:hinge: loss max(0, 1 - ym) with y in {-1, +1}; g = -y on the
    # margin-violating side, h = 1 (xgboost's constant-hessian convention);
    # predictions are hard 0/1 labels
    def gh(margin, label, weight):
        y = jnp.where(label > 0.5, 1.0, -1.0)
        violating = y * margin[:, 0] < 1.0
        g = jnp.where(violating, -y, 0.0) * weight
        h = weight
        return g[:, None], h[:, None]

    return Objective(
        name="binary:hinge",
        grad_hess=gh,
        transform=lambda m: (m[:, 0] > 0).astype(jnp.float32),
        default_metric="error",
        # hinge has no link function: base_score IS the initial margin
        # (xgboost identity ProbToMargin for hinge)
        base_score_to_margin=lambda s: float(s),
        default_base_score=0.5,
        output_kind="class",
    )


def _make_squaredlogerror() -> Objective:
    # loss 0.5*(log1p(p) - log1p(y))^2; predictions clamp to > -1 (xgboost
    # convention); labels must be > -1 — validated host-side by the engine,
    # not silently clamped
    def gh(margin, label, weight):
        p = jnp.maximum(margin[:, 0], -1.0 + 1e-6)
        d = jnp.log1p(p) - jnp.log1p(label)
        g = d / (p + 1.0) * weight
        h = jnp.maximum((1.0 - d) / (p + 1.0) ** 2, 1e-6) * weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:squaredlogerror",
        grad_hess=gh,
        transform=lambda m: m[:, 0],
        default_metric="rmsle",
        default_base_score=0.5,
    )


def _make_pseudohuber(slope: float) -> Objective:
    # loss d^2*(sqrt(1+(r/d)^2)-1): quadratic near 0, linear in the tails
    def gh(margin, label, weight):
        r = margin[:, 0] - label
        scale = 1.0 + (r / slope) ** 2
        sqrt_scale = jnp.sqrt(scale)
        g = r / sqrt_scale * weight
        h = jnp.maximum(1.0 / (scale * sqrt_scale), 1e-16) * weight
        return g[:, None], h[:, None]

    return Objective(
        name="reg:pseudohubererror",
        grad_hess=gh,
        transform=lambda m: m[:, 0],
        default_metric="mphe",
        default_base_score=0.5,
    )


RANKING_OBJECTIVES = ("rank:pairwise", "rank:ndcg", "rank:map")
SURVIVAL_OBJECTIVES = ("survival:aft",)


# ---------------------------------------------------------------------------
# End-to-end low-precision gradients (``gh_precision`` in params).
#
# PR 1 quantized only the histogram *wire*; this is the on-chip half: g/h are
# quantized AT THE SOURCE — right where the objective kernel's f32 grad/hess
# leave this module — onto a symmetric int8/int16 grid with per-tree
# per-channel scales shared across the mesh (one tiny [2] pmax), and carried
# low-precision through compaction and histogram accumulation (int -> int32,
# exact), so the per-shard gh plane shrinks 4x (int8) and integer accumulate
# becomes the histogram fast path. "Quantized Training of GBDT"
# (arxiv 2207.09682) shows this matches f32 accuracy PROVIDED rounding is
# stochastic — deterministic rounding correlates the per-row quantization
# error with the gradient sign and biases every split gain the same way —
# so rounding here draws one uniform per element from a key folded with
# ``SALT_SR`` per (seed, iteration, tree, actor): unbiased
# (E[floor(x/s + u)] = x/s) yet bitwise reproducible across reruns.
#
# Downstream exactness contract: every sum of quantized g/h (histogram bins,
# node totals) is an exact int32 integer sum, dequantized ONCE by
# ``dequantize_gh_sums`` at the split search / leaf-weight boundary — the
# only lossy step is the per-row rounding at the source. Node totals and
# leaf weights therefore stay exact f32 *of the quantized values* (the
# hist_quant discipline), and the exact-int psum wire composes with the
# quantized hist_quant wire without ever round-tripping through f32.
# ---------------------------------------------------------------------------

GH_PRECISION_MODES = ("float32", "int16", "int8")
_GH_QMAX = {"int16": 32767, "int8": 127}
_GH_QDTYPE = {"int16": jnp.int16, "int8": jnp.int8}


def gh_plane_itemsize(mode: str) -> int:
    """Bytes per stored g (or h) value under a ``gh_precision`` mode — the
    static per-shard gh-plane footprint is ``rows * 2 * this``."""
    return {"float32": 4, "int16": 2, "int8": 1}[mode]


def quantize_gh(
    gh: jnp.ndarray,  # [N, 2] float32 (grad, hess); 0 for padding rows
    mode: str,  # "int8" | "int16"
    key: jnp.ndarray,  # PRNG key already folded with SALT_SR per (tree, actor)
    axis_name: Optional[str] = None,
    counter=None,  # ops.histogram.AllreduceBytes for the [2] pmax pre-reduce
    max_rows: Optional[int] = None,  # GLOBAL row bound (padded): caps the
    #   grid so the int32 accumulation provably cannot overflow
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize per-tree grad/hess onto the ``mode`` integer grid.

    Returns ``(gh_q [N, 2] int, scale [2] f32)`` with
    ``gh ~= gh_q * scale`` (per-channel symmetric scales). The scales come
    from the GLOBAL absmax (pmax over ``axis_name`` when traced under
    shard_map — every actor agrees on them, the precondition for exact
    cross-shard integer accumulation); rounding is stochastic
    (``floor(x/s + u)``, u ~ U[0,1)): unbiased, and values already on the
    grid round deterministically (floor(k + u) == k for every u < 1), so
    zero gradients — padding rows included — stay exactly zero.

    ``max_rows`` makes exact accumulation a THEOREM, not a hope: the
    worst-case merged sum is ``qmax * max_rows`` (every row in one bin at
    absmax — logistic hessians really do hit this at the root, where every
    row's h ~ 0.25 quantizes to ~qmax), so the effective qmax is capped at
    ``(2^31 - 1) // max_rows``. int8's 127 is unaffected up to ~16.9M
    global rows; int16's granularity degrades gracefully on very large row
    counts (e.g. ~10737 steps at 200k rows) instead of silently wrapping
    int32 and training garbage.
    """
    if mode not in _GH_QMAX:
        raise ValueError(
            f"unknown gh_precision mode {mode!r}; use one of "
            f"{GH_PRECISION_MODES}"
        )
    qmax = _GH_QMAX[mode]
    if max_rows:
        qmax = max(1, min(qmax, (2**31 - 1) // int(max_rows)))
    amax = jnp.max(jnp.abs(gh), axis=0)  # [2] per-channel
    if axis_name is not None:
        try:
            amax_g = jax.lax.pmax(amax, axis_name)
            if counter is not None:
                counter.add_allreduce(amax)
            amax = amax_g
        except NameError:  # not under shard_map (unit tests, host paths)
            pass
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    u = jax.random.uniform(key, gh.shape)
    q = jnp.clip(jnp.floor(gh / scale[None, :] + u), -qmax, qmax)
    return q.astype(_GH_QDTYPE[mode]), scale


def dequantize_gh_sums(sums: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Integer (or quantized-domain f32) g/h sums ``[..., 2]`` -> f32.

    The ONE dequantization point of the low-precision path: histogram bin
    sums and node totals stay in the exact integer domain until the split
    search / leaf weights need real-valued statistics, then multiply by the
    per-channel ``scale`` from :func:`quantize_gh` once."""
    return sums.astype(jnp.float32) * scale


def gather_global_rows(*arrays):
    """Inside shard_map: all_gather each [n_local] array over the mesh axis
    into its [n_global] form (plus this shard's row offset). Outside
    shard_map the locals ARE the globals (offset 0). One home for the
    try/except idiom the cross-shard objectives/metrics (cox) share."""
    try:
        out = tuple(
            jax.lax.all_gather(a, AXIS_ACTORS).reshape(-1) for a in arrays
        )
        offset = jax.lax.axis_index(AXIS_ACTORS) * arrays[0].shape[0]
        return out, offset
    except NameError:  # not under shard_map
        return arrays, 0


def cox_risk_terms(m, label, w):
    """Shared Breslow machinery for survival:cox grad/hess and cox-nloglik.

    ``label``: time-to-event; NEGATIVE values are right-censored at |label|
    (the xgboost survival:cox convention). Returns per-row
    (r, ev, S1, S2, logD) over the GLOBAL arrays passed in, where
    r_i = w_i * exp(m_i - M) (stabilized; M cancels in grad/hess),
    ev_i = w_i * 1[event], D(tau) = sum of r over t_j >= tau (ties share
    one risk set via searchsorted), S1_i = sum over events with
    t_k <= t_i of ev_k / D_k, S2_i the same with D_k^2, and
    logD_i = log D(t_i) + M (true scale, for the nloglik metric).

    Weighted Breslow partial likelihood:
      -logL = -sum_k ev_k * (m_k - log D_k)
      grad_i = r_i * S1_i - ev_i
      hess_i = r_i * S1_i - r_i^2 * S2_i
    """
    t = jnp.abs(label)
    delta = (label > 0).astype(jnp.float32)
    mM = jnp.max(jnp.where(w > 0, m, -jnp.inf))
    mM = jnp.where(jnp.isfinite(mM), mM, 0.0)
    r = w * jnp.exp(m - mM)
    ev = w * delta

    neg_t = -t
    order = jnp.argsort(neg_t)  # descending time
    neg_ts = neg_t[order]
    cum_r = jnp.cumsum(r[order])
    # count of rows with t_j >= tau, tie-inclusive
    cnt_ge = jnp.searchsorted(neg_ts, neg_t, side="right")
    D = cum_r[jnp.maximum(cnt_ge - 1, 0)]
    D = jnp.maximum(D, 1e-38)
    logD = jnp.log(D) + mM

    # per-event 1/D and 1/D^2 in sorted order; prefix sums exclude the
    # events with t_k > t_i (they occupy the first cnt_gt_i sorted slots)
    D_sorted = D[order]
    evs = ev[order]
    term1 = evs / D_sorted
    term2 = evs / (D_sorted * D_sorted)
    pref1 = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(term1)])
    pref2 = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(term2)])
    cnt_gt = jnp.searchsorted(neg_ts, neg_t, side="left")
    S1 = pref1[-1] - pref1[cnt_gt]
    S2 = pref2[-1] - pref2[cnt_gt]
    return r, ev, S1, S2, logD


def _make_cox() -> Objective:
    """survival:cox — Breslow partial likelihood on right-censored times.

    The risk set of every event spans ALL rows, so inside the sharded round
    step the per-shard rows are all_gathered over the mesh axis, the global
    grad/hess computed (replicated work, one O(N log N) sort), and this
    shard's slice taken back. Outside shard_map (unit tests, host paths)
    the local arrays ARE the global arrays. Reference surface: xgboost's
    CoxRegression objective, passed through at xgboost_ray/main.py:745-752.
    """

    def _global_gh(m, label, w):
        r, ev, S1, S2, _ = cox_risk_terms(m, label, w)
        g = r * S1 - ev
        h = jnp.maximum(r * S1 - r * r * S2, 1e-16)
        return g, h

    def gh(margin, label, weight):
        m = margin[:, 0]
        shard_n = m.shape[0]
        (mg, lg, wg), offset = gather_global_rows(m, label, weight)
        g, h = _global_gh(mg, lg, wg)
        if mg.shape[0] != shard_n:  # gathered: slice this shard's rows back
            g = jax.lax.dynamic_slice(g, (offset,), (shard_n,))
            h = jax.lax.dynamic_slice(h, (offset,), (shard_n,))
        return g[:, None], h[:, None]

    return Objective(
        name="survival:cox",
        grad_hess=gh,
        transform=lambda m: jnp.exp(m[:, 0]),  # hazard-ratio scale
        default_metric="cox-nloglik",
        base_score_to_margin=lambda s: math.log(max(float(s), 1e-16)),
        default_base_score=0.5,
        output_kind="value",
    )


def get_objective(
    name: str,
    num_class: int = 0,
    scale_pos_weight: float = 1.0,
    tweedie_variance_power: float = 1.5,
    aft_loss_distribution: str = "normal",
    aft_loss_distribution_scale: float = 1.0,
    huber_slope: float = 1.0,
    quantile_alpha=0.5,
) -> Objective:
    """Resolve an xgboost objective string to an Objective bundle.

    Ranking objectives are resolved in ranking.py (they need qid segments);
    this function still returns their transform/base-score envelope.
    """
    if name in ("reg:squarederror", "reg:linear"):
        return _make_squarederror()
    if name == "reg:absoluteerror":
        return _make_absoluteerror()
    if name in ("binary:logistic", "reg:logistic"):
        return _make_logistic(name, raw_output=False, scale_pos_weight=scale_pos_weight)
    if name == "binary:logitraw":
        return _make_logistic(name, raw_output=True, scale_pos_weight=scale_pos_weight)
    if name in ("multi:softprob", "multi:softmax"):
        if num_class < 2:
            raise ValueError(f"{name} requires num_class >= 2, got {num_class}")
        return _make_softmax(num_class, prob_output=(name == "multi:softprob"))
    if name == "binary:hinge":
        return _make_hinge()
    if name == "reg:squaredlogerror":
        return _make_squaredlogerror()
    if name == "reg:pseudohubererror":
        return _make_pseudohuber(slope=huber_slope)
    if name == "reg:quantileerror":
        return _make_quantile(quantile_alpha)
    if name == "count:poisson":
        return _make_poisson()
    if name == "reg:gamma":
        return _make_gamma()
    if name == "reg:tweedie":
        return _make_tweedie(tweedie_variance_power)
    if name == "survival:cox":
        return _make_cox()
    if name in RANKING_OBJECTIVES:
        from xgboost_ray_tpu.ops import ranking

        return ranking.get_ranking_objective(name)
    if name in SURVIVAL_OBJECTIVES:
        from xgboost_ray_tpu.ops import survival

        return survival.get_survival_objective(
            name, aft_loss_distribution, aft_loss_distribution_scale
        )
    raise ValueError(f"Unsupported objective: {name!r}")


@dataclasses.dataclass(frozen=True)
class CustomObjective:
    """Wrap a user-supplied ``obj(preds, dtrain) -> (grad, hess)`` callable.

    Mirrors the xgboost custom-objective protocol passed through by the
    reference (``xgboost_ray/tests/test_xgboost_api.py:77-103``). The callable
    runs on host each round; grad/hess are shipped back to device.
    """

    fn: Callable
    base: Objective  # envelope providing transform/num_outputs

    @property
    def name(self):
        return "custom"

    @property
    def num_outputs(self):
        return self.base.num_outputs

    @property
    def transform(self):
        return self.base.transform

    @property
    def default_metric(self):
        return self.base.default_metric

    @property
    def base_score_to_margin(self):
        return self.base.base_score_to_margin

    @property
    def default_base_score(self):
        return self.base.default_base_score

    @property
    def output_kind(self):
        return self.base.output_kind
