"""Gradient/hessian histogram construction.

TPU-native replacement for xgboost's C++ ``hist`` / CUDA ``gpu_hist``
histogram builders (selected by the user's ``params["tree_method"]``,
validated at ``xgboost_ray/main.py:1506-1524``). This is the hot op of GBDT
training: per boosting level we accumulate (grad, hess) sums into
``[n_nodes, n_features, n_bins+1, 2]`` buckets keyed by (row's node, feature,
feature bin). The merged-across-shards histogram is obtained by ``psum`` in
the shard_map round step (replacing the Rabit allreduce, SURVEY §5.8).

Two implementations:

* ``hist_scatter`` — one flat XLA scatter-add. Correct everywhere (CPU tests,
  TPU), shape-static, reasonable on TPU for moderate fan-out.
* ``hist_onehot`` — row-chunked one-hot × (grad,hess) matmuls that run on the
  MXU; scan over features and row chunks keeps peak VMEM bounded. Preferred
  on TPU for large rows×bins products.

Selection happens in the trainer via params ("tpu_hist_impl").
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def hist_scatter(
    bins: jnp.ndarray,  # [N, F] integer bins in 0..n_bins (n_bins == missing)
    gh: jnp.ndarray,  # [N, 2] float32 (grad, hess); padding rows must be 0
    pos: jnp.ndarray,  # [N] int32 node position within level, 0..n_nodes-1
    n_nodes: int,
    n_bins_total: int,  # n_bins + 1 (missing bucket included)
) -> jnp.ndarray:
    """Returns [n_nodes, F, n_bins_total, 2] float32."""
    n, num_features = bins.shape
    b = bins.astype(jnp.int32)
    # flat bucket id per (row, feature)
    flat = (pos[:, None] * num_features + jnp.arange(num_features, dtype=jnp.int32)[None, :]) * n_bins_total + b
    out = jnp.zeros((n_nodes * num_features * n_bins_total, 2), jnp.float32)
    ghb = jnp.broadcast_to(gh[:, None, :], (n, num_features, 2))
    out = out.at[flat.reshape(-1)].add(ghb.reshape(-1, 2))
    return out.reshape(n_nodes, num_features, n_bins_total, 2)


def hist_onehot(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    chunk: int = 8192,
) -> jnp.ndarray:
    """MXU-friendly histogram: per feature, hist = onehot(node*bins)ᵀ @ gh.

    Scans row chunks (outer) and features (inner); each inner step builds a
    [chunk, n_nodes*n_bins_total] one-hot and contracts it against the chunk's
    [chunk, 2] grad/hess — a matmul XLA tiles onto the MXU. Padding rows have
    gh == 0 so over-padding of the last chunk is harmless.
    """
    n, num_features = bins.shape
    nb = n_nodes * n_bins_total
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    b = bins.astype(jnp.int32)
    if pad:
        b = jnp.pad(b, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad))
    b = b.reshape(n_chunks, chunk, num_features)
    ghc = gh.reshape(n_chunks, chunk, 2)
    posc = pos.reshape(n_chunks, chunk)

    def chunk_step(acc, args):
        bc, ghk, pk = args  # [chunk, F], [chunk, 2], [chunk]
        base = pk * n_bins_total  # [chunk]

        def feat_step(f, acc):
            idx = base + bc[:, f]  # [chunk]
            oh = jax.nn.one_hot(idx, nb, dtype=jnp.float32)  # [chunk, nb]
            contrib = jnp.matmul(oh.T, ghk, precision=jax.lax.Precision.HIGHEST)  # [nb, 2] (MXU)
            return acc.at[f].add(contrib)

        acc = jax.lax.fori_loop(0, num_features, feat_step, acc)
        return acc, None

    acc0 = jnp.zeros((num_features, nb, 2), jnp.float32)
    acc, _ = jax.lax.scan(chunk_step, acc0, (b, ghc, posc))
    # [F, n_nodes*nbt, 2] -> [n_nodes, F, nbt, 2]
    return acc.reshape(num_features, n_nodes, n_bins_total, 2).transpose(1, 0, 2, 3)


def node_sums(gh: jnp.ndarray, pos: jnp.ndarray, n_nodes: int) -> jnp.ndarray:
    """Per-node (grad, hess) totals: [n_nodes, 2] via segment-sum."""
    out = jnp.zeros((n_nodes, 2), jnp.float32)
    return out.at[pos].add(gh)


def build_histogram(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    impl: str = "scatter",
    chunk: int = 8192,
) -> jnp.ndarray:
    if impl == "onehot":
        return hist_onehot(bins, gh, pos, n_nodes, n_bins_total, chunk=chunk)
    if impl == "pallas":
        try:
            from xgboost_ray_tpu.ops import hist_pallas

            return hist_pallas.hist_pallas(bins, gh, pos, n_nodes, n_bins_total)
        except Exception:
            return hist_scatter(bins, gh, pos, n_nodes, n_bins_total)
    return hist_scatter(bins, gh, pos, n_nodes, n_bins_total)
