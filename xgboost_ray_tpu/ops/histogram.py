"""Gradient/hessian histogram construction.

TPU-native replacement for xgboost's C++ ``hist`` / CUDA ``gpu_hist``
histogram builders (selected by the user's ``params["tree_method"]``,
validated at ``xgboost_ray/main.py:1506-1524``). This is the hot op of GBDT
training: per boosting level we accumulate (grad, hess) sums into
``[n_nodes, n_features, n_bins+1, 2]`` buckets keyed by (row's node, feature,
feature bin). The merged-across-shards histogram is obtained by ``psum`` in
the shard_map round step (replacing the Rabit allreduce, SURVEY §5.8).

Two implementations:

* ``hist_scatter`` — one flat XLA scatter-add. Correct everywhere (CPU tests,
  TPU), shape-static, reasonable on TPU for moderate fan-out.
* ``hist_onehot`` — row-chunked one-hot × (grad,hess) matmuls that run on the
  MXU; scan over features and row chunks keeps peak VMEM bounded. Preferred
  on TPU for large rows×bins products.

Selection happens in the trainer via params ("tpu_hist_impl").
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def hist_scatter(
    bins: jnp.ndarray,  # [N, F] integer bins in 0..n_bins (n_bins == missing)
    gh: jnp.ndarray,  # [N, 2] float32 (grad, hess); padding rows must be 0
    pos: jnp.ndarray,  # [N] int32 node position within level, 0..n_nodes-1
    n_nodes: int,
    n_bins_total: int,  # n_bins + 1 (missing bucket included)
) -> jnp.ndarray:
    """Returns [n_nodes, F, n_bins_total, 2] float32."""
    n, num_features = bins.shape
    b = bins.astype(jnp.int32)
    # flat bucket id per (row, feature)
    flat = (pos[:, None] * num_features + jnp.arange(num_features, dtype=jnp.int32)[None, :]) * n_bins_total + b
    out = jnp.zeros((n_nodes * num_features * n_bins_total, 2), jnp.float32)
    ghb = jnp.broadcast_to(gh[:, None, :], (n, num_features, 2))
    out = out.at[flat.reshape(-1)].add(ghb.reshape(-1, 2))
    return out.reshape(n_nodes, num_features, n_bins_total, 2)


def hist_onehot(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    chunk: int = 8192,
) -> jnp.ndarray:
    """MXU-friendly histogram: per feature, hist = onehot(node*bins)ᵀ @ gh.

    Scans row chunks (outer) and features (inner); each inner step builds a
    [chunk, n_nodes*n_bins_total] one-hot and contracts it against the chunk's
    [chunk, 2] grad/hess — a matmul XLA tiles onto the MXU. Padding rows have
    gh == 0 so over-padding of the last chunk is harmless.
    """
    n, num_features = bins.shape
    nb = n_nodes * n_bins_total
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    b = bins.astype(jnp.int32)
    if pad:
        b = jnp.pad(b, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad))
    b = b.reshape(n_chunks, chunk, num_features)
    ghc = gh.reshape(n_chunks, chunk, 2)
    posc = pos.reshape(n_chunks, chunk)

    def chunk_step(acc, args):
        bc, ghk, pk = args  # [chunk, F], [chunk, 2], [chunk]
        base = pk * n_bins_total  # [chunk]

        def feat_step(f, acc):
            idx = base + bc[:, f]  # [chunk]
            oh = jax.nn.one_hot(idx, nb, dtype=jnp.float32)  # [chunk, nb]
            contrib = jnp.matmul(oh.T, ghk, precision=jax.lax.Precision.HIGHEST)  # [nb, 2] (MXU)
            return acc.at[f].add(contrib)

        acc = jax.lax.fori_loop(0, num_features, feat_step, acc)
        return acc, None

    acc0 = jnp.zeros((num_features, nb, 2), jnp.float32)
    acc, _ = jax.lax.scan(chunk_step, acc0, (b, ghc, posc))
    # [F, n_nodes*nbt, 2] -> [n_nodes, F, nbt, 2]
    return acc.reshape(num_features, n_nodes, n_bins_total, 2).transpose(1, 0, 2, 3)


def update_partition_order(
    order: jnp.ndarray,  # [N] rows sorted stably by current pos
    counts: jnp.ndarray,  # [n_nodes] rows per node at the current level
    go_right: jnp.ndarray,  # [N] bool, indexed by ORIGINAL row id
) -> tuple:
    """O(N) stable segment split: maintain the sorted-by-node row order across
    one level of tree growth without re-sorting (the XLA analog of gpu_hist's
    incremental row partitioner). Returns (new_order, new_counts) for the
    2*n_nodes children."""
    n = order.shape[0]
    n_nodes = counts.shape[0]
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    seg_of_slot = jnp.searchsorted(
        jnp.cumsum(counts), jnp.arange(n), side="right"
    )
    gr_s = go_right[order]
    left_s = ~gr_s
    # exclusive cumulative left/right counts, segment-relative
    cum_left = jnp.cumsum(left_s) - left_s
    cum_right = jnp.cumsum(gr_s) - gr_s
    left_before = cum_left[seg_start]  # [n_nodes] lefts before each segment
    right_before = cum_right[seg_start]
    rank_left = cum_left - left_before[seg_of_slot]
    rank_right = cum_right - right_before[seg_of_slot]
    # child segment sizes
    seg_end = jnp.cumsum(counts) - 1
    total_left = jnp.where(
        counts > 0, cum_left[jnp.maximum(seg_end, 0)] + left_s[jnp.maximum(seg_end, 0)]
        - left_before, 0
    )
    left_count = total_left
    right_count = counts - left_count
    new_counts = jnp.stack([left_count, right_count], axis=1).reshape(-1)
    new_start = jnp.concatenate(
        [jnp.zeros((1,), new_counts.dtype), jnp.cumsum(new_counts)[:-1]]
    )
    child = 2 * seg_of_slot + gr_s.astype(seg_of_slot.dtype)
    rank = jnp.where(gr_s, rank_right, rank_left)
    dest = new_start[child] + rank
    new_order = jnp.zeros_like(order).at[dest].set(order)
    return new_order, new_counts


def presorted_block_layout(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    order: jnp.ndarray,  # [N] rows sorted stably by node
    counts: jnp.ndarray,  # [n_nodes]
    n_nodes: int,
    block: int,
):
    """Scatter presorted rows into node-uniform padded blocks.

    Returns (bp [n_blocks, block, F], ghp [n_blocks, block, 2],
    node_of_block [n_blocks]); padding slots carry zero gh. Shared by the XLA
    blocked-einsum path and the Pallas kernel so the layout math has one
    home."""
    n, num_features = bins.shape
    b32 = bins.astype(jnp.int32)
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    padded_counts = ((counts + block - 1) // block) * block
    padded_cum = jnp.cumsum(padded_counts)
    padded_start = jnp.concatenate(
        [jnp.zeros((1,), padded_cum.dtype), padded_cum[:-1]]
    )
    seg_of_slot = jnp.searchsorted(jnp.cumsum(counts), jnp.arange(n), side="right")
    rank_in_node = jnp.arange(n) - seg_start[seg_of_slot]
    dest = (padded_start[seg_of_slot] + rank_in_node).astype(jnp.int32)

    cap = (-(-n // block) + n_nodes) * block
    n_blocks = cap // block
    row_of_slot = jnp.full((cap,), n, jnp.int32).at[dest].set(order.astype(jnp.int32))
    node_of_block = jnp.clip(
        jnp.searchsorted(padded_cum, jnp.arange(n_blocks) * block, side="right"),
        0,
        n_nodes,
    ).astype(jnp.int32)
    bins_ext = jnp.concatenate([b32, jnp.zeros((1, num_features), jnp.int32)])
    gh_ext = jnp.concatenate([gh, jnp.zeros((1, 2), gh.dtype)])
    bp = bins_ext[row_of_slot].reshape(n_blocks, block, num_features)
    ghp = gh_ext[row_of_slot].reshape(n_blocks, block, 2)
    return bp, ghp, node_of_block


def hist_partition_presorted(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    order: jnp.ndarray,  # [N] rows sorted stably by node
    counts: jnp.ndarray,  # [n_nodes]
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    block_chunk: int = 512,
) -> jnp.ndarray:
    """hist_partition with the sort/bincount already maintained by the caller
    (see ``update_partition_order``)."""
    num_features = bins.shape[1]
    bp, ghp, node_of_block = presorted_block_layout(
        bins, gh, order, counts, n_nodes, block
    )
    return _blocked_hist(
        bp, ghp, node_of_block, n_nodes, n_bins_total, num_features, block_chunk
    )


def _blocked_hist(bp, ghp, node_of_block, n_nodes, n_bins_total, num_features,
                  block_chunk):
    n_blocks = bp.shape[0]
    n_chunks = -(-n_blocks // block_chunk)
    pad_blocks = n_chunks * block_chunk - n_blocks
    if pad_blocks:
        bp = jnp.pad(bp, ((0, pad_blocks), (0, 0), (0, 0)))
        ghp = jnp.pad(ghp, ((0, pad_blocks), (0, 0), (0, 0)))
        node_of_block = jnp.pad(node_of_block, (0, pad_blocks), constant_values=n_nodes)
    bp = bp.reshape(n_chunks, block_chunk, -1, num_features)
    ghp = ghp.reshape(n_chunks, block_chunk, -1, 2)
    nodes_c = node_of_block.reshape(n_chunks, block_chunk)

    def chunk_step(hist, args):
        bc, gc, nodes = args

        def feat_step(f, hist):
            oh = jax.nn.one_hot(bc[:, :, f], n_bins_total, dtype=jnp.float32)
            contrib = jnp.einsum(
                "cbn,cbd->cnd", oh, gc, precision=jax.lax.Precision.HIGHEST
            )
            return hist.at[nodes, f].add(contrib)

        hist = jax.lax.fori_loop(0, num_features, feat_step, hist)
        return hist, None

    hist0 = jnp.zeros((n_nodes + 1, num_features, n_bins_total, 2), jnp.float32)
    hist, _ = jax.lax.scan(chunk_step, hist0, (bp, ghp, nodes_c))
    return hist[:n_nodes]


def hist_partition(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    block_chunk: int = 512,
) -> jnp.ndarray:
    """Node-contiguous blocked histogram — the deep-level TPU workhorse.

    The one-hot-matmul formulation costs rows x nodes x bins FLOPs (the node
    axis rides in the one-hot width), which explodes at deep levels. This
    variant first *partitions rows by node* (stable sort + padded segment
    layout, the XLA analog of gpu_hist's row partitioner), so every
    ``block``-row tile belongs to exactly one node and the per-tile matmul is
    only [bins x block] @ [block x 2]: total FLOPs ~ rows x bins x features,
    independent of the node count. The final per-block scatter touches
    O(n_blocks) elements only.
    """
    n, num_features = bins.shape
    b32 = bins.astype(jnp.int32)
    order = jnp.argsort(pos, stable=True)
    pos_s = pos[order]
    counts = jnp.bincount(pos, length=n_nodes)
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    padded_counts = ((counts + block - 1) // block) * block
    padded_cum = jnp.cumsum(padded_counts)
    padded_start = jnp.concatenate(
        [jnp.zeros((1,), padded_cum.dtype), padded_cum[:-1]]
    )
    rank_in_node = jnp.arange(n) - seg_start[pos_s]
    dest = (padded_start[pos_s] + rank_in_node).astype(jnp.int32)

    cap = (-(-n // block) + n_nodes) * block  # static upper bound on slots
    n_blocks = cap // block
    row_of_slot = jnp.full((cap,), n, jnp.int32).at[dest].set(order.astype(jnp.int32))
    node_of_block = jnp.clip(
        jnp.searchsorted(padded_cum, jnp.arange(n_blocks) * block, side="right"),
        0,
        n_nodes,  # overflow blocks (all-sentinel) park in a scratch slot
    )

    bins_ext = jnp.concatenate([b32, jnp.zeros((1, num_features), jnp.int32)])
    gh_ext = jnp.concatenate([gh, jnp.zeros((1, 2), gh.dtype)])
    bp = bins_ext[row_of_slot].reshape(n_blocks, block, num_features)
    ghp = gh_ext[row_of_slot].reshape(n_blocks, block, 2)

    n_chunks = -(-n_blocks // block_chunk)
    pad_blocks = n_chunks * block_chunk - n_blocks
    if pad_blocks:
        bp = jnp.pad(bp, ((0, pad_blocks), (0, 0), (0, 0)))
        ghp = jnp.pad(ghp, ((0, pad_blocks), (0, 0), (0, 0)))
        node_of_block = jnp.pad(node_of_block, (0, pad_blocks), constant_values=n_nodes)
    bp = bp.reshape(n_chunks, block_chunk, block, num_features)
    ghp = ghp.reshape(n_chunks, block_chunk, block, 2)
    nodes_c = node_of_block.reshape(n_chunks, block_chunk)

    def chunk_step(hist, args):
        bc, gc, nodes = args  # [C, block, F], [C, block, 2], [C]

        def feat_step(f, hist):
            oh = jax.nn.one_hot(bc[:, :, f], n_bins_total, dtype=jnp.float32)
            # [C, block, nbt]^T x [C, block, 2] -> [C, nbt, 2] per block
            contrib = jnp.einsum(
                "cbn,cbd->cnd", oh, gc, precision=jax.lax.Precision.HIGHEST
            )
            return hist.at[nodes, f].add(contrib)

        hist = jax.lax.fori_loop(0, num_features, feat_step, hist)
        return hist, None

    hist0 = jnp.zeros((n_nodes + 1, num_features, n_bins_total, 2), jnp.float32)
    hist, _ = jax.lax.scan(chunk_step, hist0, (bp, ghp, nodes_c))
    return hist[:n_nodes]


def node_sums(gh: jnp.ndarray, pos: jnp.ndarray, n_nodes: int) -> jnp.ndarray:
    """Per-node (grad, hess) totals: [n_nodes, 2] via segment-sum."""
    out = jnp.zeros((n_nodes, 2), jnp.float32)
    return out.at[pos].add(gh)


def build_histogram(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    impl: str = "scatter",
    chunk: int = 8192,
) -> jnp.ndarray:
    if impl == "onehot":
        return hist_onehot(bins, gh, pos, n_nodes, n_bins_total, chunk=chunk)
    if impl == "partition":
        return hist_partition(bins, gh, pos, n_nodes, n_bins_total)
    if impl == "mixed":
        # shallow levels: node axis is cheap in the one-hot width; deep
        # levels: row partitioning keeps FLOPs independent of node count
        if n_nodes <= 4:
            return hist_onehot(bins, gh, pos, n_nodes, n_bins_total, chunk=chunk)
        return hist_partition(bins, gh, pos, n_nodes, n_bins_total)
    if impl == "pallas":
        try:
            from xgboost_ray_tpu.ops import hist_pallas

            return hist_pallas.hist_pallas(bins, gh, pos, n_nodes, n_bins_total)
        except Exception:
            return hist_scatter(bins, gh, pos, n_nodes, n_bins_total)
    return hist_scatter(bins, gh, pos, n_nodes, n_bins_total)
