"""Gradient/hessian histogram construction.

TPU-native replacement for xgboost's C++ ``hist`` / CUDA ``gpu_hist``
histogram builders (selected by the user's ``params["tree_method"]``,
validated at ``xgboost_ray/main.py:1506-1524``). This is the hot op of GBDT
training: per boosting level we accumulate (grad, hess) sums into
``[n_nodes, n_features, n_bins+1, 2]`` buckets keyed by (row's node, feature,
feature bin). The merged-across-shards histogram is obtained by ``psum`` in
the shard_map round step (replacing the Rabit allreduce, SURVEY §5.8).

Two implementations:

* ``hist_scatter`` — one flat XLA scatter-add. Correct everywhere (CPU tests,
  TPU), shape-static, reasonable on TPU for moderate fan-out.
* ``hist_onehot`` — row-chunked one-hot × (grad,hess) matmuls that run on the
  MXU; scan over features and row chunks keeps peak VMEM bounded. Preferred
  on TPU for large rows×bins products.

Selection happens in the trainer via params ("tpu_hist_impl").
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Quantized histogram allreduce (``hist_quant`` in params).
#
# The per-round hot path psums a full [n_nodes, F, n_bins+1, 2] float32
# histogram at every tree level; on a multi-chip mesh those collective bytes
# ARE the scaling cost (VERDICT r5: only the 8-chip projection beats the
# gpu_hist target). "Quantized Training of GBDTs" (arxiv 2207.09682) shows
# gradient histograms tolerate low-bit quantization, and EQuARX
# (arxiv 2506.17615) shows quantized allreduce recovers near-linear
# collective bandwidth. Two wire formats:
#
# Row scales ("int8" / "int16"):
#
#   1. per-(node, feature) symmetric scales from a pmax-merged absmax
#      (one tiny f32 pre-reduce — every actor agrees on the scales);
#   2. deterministic round-to-nearest quantization (NO stochastic rounding,
#      so every actor computes bit-identical payloads and the merged
#      histogram is bit-identical on every shard);
#   3. reduce-scatter as an int8/int16 all_to_all, with the accumulation
#      WIDENED to int32 on the receiving actor — actor counts cannot
#      overflow the narrow payload dtype;
#   4. the reduced rows are re-quantized against their own merged absmax
#      (same per-(node, feature) granularity) and all_gathered as
#      int8/int16 + one f32 scale per row.
#
# Block scales ("int8_block" / "int16_block") — the EQuARX schedule:
#
#   1. NO absmax pre-pass. Scales are per contiguous block of the FLATTENED
#      histogram (``hist_quant_block`` elements, default 512), computed from
#      whatever each actor holds locally at the moment it sends — the
#      full-extent pmax pre-reduce (a full-latency collective per merge) is
#      deleted from the schedule entirely;
#   2. the merge is a ppermute ring reduce-scatter: at each of the n-1 hops
#      an actor quantizes its running partial sum against its own running
#      block absmax, ships int8/int16 data + bitcast f32 block scales as ONE
#      in-band payload, and the receiver dequant-accumulates in f32 — the
#      wire is narrow on every hop;
#   3. after the ring each actor owns one fully-reduced chunk, built by a
#      single computation path — so the final requantize + tiled all_gather
#      (scales again in-band) publishes bit-identical results everywhere.
#
# Row-scale wire per element ~ 1 + 1/n bytes (int8) vs 4 for f32 psum, plus
# the pmax pre-pass. Block-scale wire = 2(n-1) * (S/n + 4*ceil(S/(n*B)))
# bytes for S elements at block B: fewer bytes AND one fewer full-latency
# collective per merge. Accuracy: row modes round twice at 1/127 (int8)
# per (node, feature); block modes round once per hop against the running
# block absmax (n_hops + 1 roundings at 1/127 per block of 512 elements —
# finer granularity, more roundings; 2207.09682 bounds both regimes).
# ---------------------------------------------------------------------------

HIST_QUANT_MODES = ("none", "int16", "int8", "int16_block", "int8_block")
_QMAX = {"int16": 32767, "int8": 127}
_QDTYPE = {"int16": jnp.int16, "int8": jnp.int8}
#: block-scaled wire modes -> the narrow dtype key their payloads use
HIST_QUANT_BLOCK_MODES = {"int16_block": "int16", "int8_block": "int8"}
#: default elements per in-band scale block (``hist_quant_block`` param)
HIST_QUANT_DEFAULT_BLOCK = 512

# Payloads below this ship as plain f32 psum even when a quantized mode is
# on: small collectives are latency-bound (quantizing them saves nothing and
# costs two extra dispatches), and keeping small histograms exact preserves
# world-size-invariant tree structure on small problems — sub-threshold
# levels see identical bin sums no matter how rows are sharded. 32 KiB is
# well under one HIGGS-shaped level payload (28 x 257 x 2 x 4 B ~ 57 KiB per
# node row), so production-scale meshes quantize every level.
HIST_QUANT_MIN_BYTES = 32768


class AllreduceBytes:
    """Per-actor wire-byte counter for one traced round, under the standard
    ring-collective cost model.

    Every collective call site records the bytes an actor moves over the
    wire for that op — the quantity ICI/DCN actually carries, which is what
    the quantized modes are built to cut:

    * allreduce (psum/pmax) = reduce-scatter + all-gather:
      ``2 * (n-1)/n * bytes(operand)``
    * all_to_all: ``(n-1)/n * bytes(operand)``
    * all_gather: ``(n-1) * bytes(local chunk)`` (each actor receives every
      other actor's chunk)

    Operand shapes are jit-static, so trace-time accumulation counts
    exactly the traffic of the compiled collectives; the total is emitted
    as a device scalar next to the metrics, so the reduction of a quantized
    mode is *measured from the program that ran*, not asserted. On a
    1-device mesh every term is zero — there is no wire. ``lax.scan``
    bodies trace once but execute per step: growers wrap such regions in
    ``repeated(n_steps)``."""

    def __init__(self, n_actors: int):
        self.n = max(1, int(n_actors))
        self.total = 0  # python int: operand shapes are trace-time constants
        self._mult = 1

    @staticmethod
    def _nbytes(arr) -> int:
        return int(arr.size) * arr.dtype.itemsize

    def add_allreduce(self, arr) -> None:
        self.total += (
            int(2 * (self.n - 1) * self._nbytes(arr) / self.n) * self._mult
        )

    def add_all_to_all(self, arr) -> None:
        self.total += (
            int((self.n - 1) * self._nbytes(arr) / self.n) * self._mult
        )

    def add_all_gather(self, chunk) -> None:
        self.total += (self.n - 1) * self._nbytes(chunk) * self._mult

    def add_ppermute(self, arr, hops: int = 1) -> None:
        """One ``ppermute`` ring hop: every actor ships the full operand to
        exactly one peer, so the per-actor wire cost is the operand itself
        (``hops`` times for a multi-hop ring recorded at one call site).
        Without this the counter would have no model for the block-scale
        ring and would silently charge it as an allreduce."""
        self.total += self._nbytes(arr) * int(hops) * self._mult

    def repeated(self, n: int):
        """Context manager: collectives traced inside run ``n`` times."""
        import contextlib

        counter = self

        @contextlib.contextmanager
        def scope():
            counter._mult *= n
            try:
                yield
            finally:
                counter._mult //= n

        return scope()

    def absorb(self, other: Optional["AllreduceBytes"]) -> None:
        """Fold another counter's total into this one (e.g. the feature
        axis's own-ring-extent counter on a 2D mesh) so ``as_scalar`` stays
        the single emission point. ``None`` is a no-op."""
        if other is not None:
            self.total += int(other.total)

    def as_scalar(self) -> jnp.ndarray:
        """The total as a device int32 (clamped; ~2 GB/round is beyond any
        real per-round payload)."""
        return jnp.int32(min(self.total, 2**31 - 1))


def counting_psum(axis_name: str, counter: Optional[AllreduceBytes]):
    """A ``lax.psum`` wrapper that records its ring-model wire bytes."""

    def psum(x):
        if counter is not None:
            counter.add_allreduce(x)
        return jax.lax.psum(x, axis_name)

    return psum


def quantized_hist_allreduce(
    h: jnp.ndarray,  # [n_nodes, F, n_bins_total, 2] float32 local histogram
    axis_name: str,
    mode: str,
    n_actors: int,
    counter: Optional[AllreduceBytes] = None,
    min_bytes: int = HIST_QUANT_MIN_BYTES,
    block: int = HIST_QUANT_DEFAULT_BLOCK,
) -> jnp.ndarray:
    """Allreduce a histogram across ``axis_name`` with an optionally
    quantized wire format (see module comment). ``mode`` is one of
    ``HIST_QUANT_MODES``; ``"none"`` is the plain f32 psum, and payloads
    under ``min_bytes`` fall back to it (shape-static decision). ``block``
    is the scale granularity of the block-scaled modes (ignored by the row
    modes). The result is bit-identical on every shard in all modes.

    ``h`` may be an INT32 quantized-domain histogram (``gh_precision``
    int8/int16 gradients accumulate integer-exact): the fallback psum stays
    in int32 — an exact integer wire at the same 4 bytes/element — and the
    quantized wire stages read the f32 view of the integer sums (exact below
    2^24; the wire rounding is far coarser beyond)."""
    if mode == "none" or h.size * 4 < min_bytes:
        if counter is not None:
            counter.add_allreduce(h)
        return jax.lax.psum(h, axis_name)
    if mode in HIST_QUANT_BLOCK_MODES:
        return _block_scaled_allreduce(
            h, axis_name, HIST_QUANT_BLOCK_MODES[mode], n_actors, counter,
            int(block),
        )
    if mode not in _QMAX:
        raise ValueError(f"unknown hist_quant mode {mode!r}")
    qmax = _QMAX[mode]
    qdt = _QDTYPE[mode]
    nn, num_features, nbt, two = h.shape
    rows = nn * num_features
    cols = nbt * two
    hr = h.reshape(rows, cols)
    if hr.dtype != jnp.float32:
        hr = hr.astype(jnp.float32)

    # stage 1: shared per-(node, feature) scales from the global absmax of
    # the LOCAL histograms (pmax bounds every actor's values, so the
    # quantized payload always fits +-qmax)
    amax_local = jnp.max(jnp.abs(hr), axis=1)  # [rows] f32
    if counter is not None:
        counter.add_allreduce(amax_local)
    amax = jax.lax.pmax(amax_local, axis_name)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(hr / scale[:, None]), -qmax, qmax).astype(qdt)

    if n_actors == 1:
        # no wire (the counter's ring terms are all zero on 1 device): the
        # same two deterministic roundings as the multi-actor path, so
        # 1-actor and n-actor models see the same quantization contract
        merged = q.astype(jnp.int32).astype(jnp.float32) * scale[:, None]
        amax2 = jnp.max(jnp.abs(merged), axis=1)
        scale2 = jnp.where(amax2 > 0, amax2 / qmax, 1.0)
        q2 = jnp.clip(jnp.round(merged / scale2[:, None]), -qmax, qmax)
        return (q2 * scale2[:, None]).reshape(nn, num_features, nbt, two)

    # stage 2: reduce-scatter the narrow payload (all_to_all), accumulate
    # WIDENED to int32 — up to 2^23 actors cannot overflow an int8 payload
    pad = (-rows) % n_actors
    scale_p = jnp.pad(scale, (0, pad), constant_values=1.0) if pad else scale
    qp = jnp.pad(q, ((0, pad), (0, 0))) if pad else q
    chunk = (rows + pad) // n_actors
    if counter is not None:
        counter.add_all_to_all(qp)
    recv = jax.lax.all_to_all(
        qp.reshape(n_actors, chunk, cols), axis_name, 0, 0
    )  # [n_actors, chunk, cols] narrow ints
    acc = jnp.sum(recv.astype(jnp.int32), axis=0)  # widened accumulation

    # stage 3: requantize the merged rows this actor owns against their own
    # merged absmax (same per-(node, feature) granularity as stage 1) and
    # gather narrow ints + one f32 scale per row. The scale's raw bytes ride
    # INSIDE the same payload (bitcast to the narrow dtype, appended as
    # trailing columns) so the gather is ONE collective, not two — collective
    # dispatch count, not only bytes, is a real cost on small meshes.
    idx = jax.lax.axis_index(axis_name)
    scale_own = jax.lax.dynamic_slice_in_dim(scale_p, idx * chunk, chunk)
    merged_rows = acc.astype(jnp.float32) * scale_own[:, None]
    amax2 = jnp.max(jnp.abs(merged_rows), axis=1)
    scale2 = jnp.where(amax2 > 0, amax2 / qmax, 1.0)
    q2 = jnp.clip(
        jnp.round(merged_rows / scale2[:, None]), -qmax, qmax
    ).astype(qdt)
    scale_cols = jax.lax.bitcast_convert_type(scale2, qdt)  # [chunk, 4 // iw]
    payload = jnp.concatenate([q2, scale_cols], axis=1)
    if counter is not None:
        counter.add_all_gather(payload)
    full = jax.lax.all_gather(payload, axis_name, tiled=True)
    full_s = jax.lax.bitcast_convert_type(full[:, cols:], jnp.float32)
    merged = full[:, :cols].astype(jnp.float32) * full_s.reshape(-1, 1)
    return merged[:rows].reshape(nn, num_features, nbt, two)


def _block_scaled_allreduce(
    h: jnp.ndarray,
    axis_name: str,
    base: str,  # "int8" | "int16" — the narrow payload dtype
    n_actors: int,
    counter: Optional[AllreduceBytes],
    block: int,
) -> jnp.ndarray:
    """Block-scaled ring allreduce (``hist_quant="int8_block"/"int16_block"``,
    see module comment). No absmax pre-pass: each send quantizes against the
    LOCAL running block absmax, and the schedule is n-1 narrow ppermute hops
    (ring reduce-scatter with f32 dequant-accumulate per hop) + one narrow
    tiled all_gather with the f32 block scales bitcast in-band. Each chunk's
    final value is computed by exactly one actor along its ring path, so the
    gathered result is bit-identical on every shard."""
    qmax = _QMAX[base]
    qdt = _QDTYPE[base]
    nn, num_features, nbt, two = h.shape
    size = nn * num_features * nbt * two
    flat = h.reshape(-1)
    if flat.dtype != jnp.float32:
        # int32 gh_precision domain: exact below 2^24, coarser-than-wire
        # rounding beyond — and NEVER a full-rank f32 psum (VER004)
        flat = flat.astype(jnp.float32)
    n = max(1, int(n_actors))
    pad = (-size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunk = (size + pad) // n
    bpc = -(-chunk // block)  # scale blocks per chunk (last may be ragged)
    bpad = bpc * block - chunk
    sw = 4 // jnp.dtype(qdt).itemsize  # narrow words per f32 scale

    def quantize(v):  # [chunk] f32 -> ([chunk] narrow, [bpc] f32 scales)
        vb = (jnp.pad(v, (0, bpad)) if bpad else v).reshape(bpc, block)
        amax = jnp.max(jnp.abs(vb), axis=1)
        scale = jnp.where(amax > 0, amax / qmax, 1.0)
        q = jnp.clip(jnp.round(vb / scale[:, None]), -qmax, qmax).astype(qdt)
        return q.reshape(-1)[:chunk], scale

    def dequantize(q, scale):  # ([chunk] narrow, [bpc] f32) -> [chunk] f32
        qb = (jnp.pad(q, (0, bpad)) if bpad else q).reshape(bpc, block)
        v = qb.astype(jnp.int32).astype(jnp.float32) * scale[:, None]
        return v.reshape(-1)[:chunk]

    def pack(q, scale):  # ragged 1-D wire: data then bitcast scale words
        return jnp.concatenate(
            [q, jax.lax.bitcast_convert_type(scale, qdt).reshape(-1)]
        )

    def unpack(payload):
        scale = jax.lax.bitcast_convert_type(
            payload[chunk:].reshape(bpc, sw), jnp.float32
        )
        return payload[:chunk], scale

    if n == 1:
        # no wire: the same two deterministic block-granular roundings as
        # the multi-actor path (one at the first ring send, one at the
        # publish requantize), so 1-actor and n-actor models see the same
        # quantization contract
        q, scale = quantize(flat)
        q2, scale2 = quantize(dequantize(q, scale))
        return dequantize(q2, scale2)[:size].reshape(
            nn, num_features, nbt, two
        )

    chunks = flat.reshape(n, chunk)
    p = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # ring reduce-scatter: at step s actor p ships the running sum of chunk
    # (p - 1 - s) % n to p + 1, quantized against its running block absmax;
    # the receiver dequant-accumulates its own local copy in f32. After the
    # n - 1 hops actor p owns the fully reduced chunk p.
    cur = jnp.take(chunks, (p - 1) % n, axis=0)
    for s in range(n - 1):
        payload = pack(*quantize(cur))
        if counter is not None:
            counter.add_ppermute(payload)
        recv = jax.lax.ppermute(payload, axis_name, perm)
        rq, rscale = unpack(recv)
        cur = dequantize(rq, rscale) + jnp.take(chunks, (p - 2 - s) % n, axis=0)
    # publish: requantize the owned chunk against its merged block absmax
    # and all_gather with the scales riding in-band — one collective
    payload = pack(*quantize(cur))
    if counter is not None:
        counter.add_all_gather(payload)
    full = jax.lax.all_gather(payload, axis_name, tiled=True)
    per = full.reshape(n, chunk + bpc * sw)
    scales = jax.lax.bitcast_convert_type(
        per[:, chunk:].reshape(n, bpc, sw), jnp.float32
    )
    qs = per[:, :chunk]
    qb = jnp.pad(qs, ((0, 0), (0, bpad))) if bpad else qs
    vals = (
        qb.reshape(n, bpc, block).astype(jnp.int32).astype(jnp.float32)
        * scales[:, :, None]
    )
    merged = vals.reshape(n, bpc * block)[:, :chunk].reshape(-1)
    return merged[:size].reshape(nn, num_features, nbt, two)


def _einsum_precision(precision: str):
    """Histogram accumulation precision: "highest" (f32-exact bf16x3 passes)
    or "fast" (single bf16 pass; ~0.2% relative rounding on gh entering the
    MXU, 2-3x fewer MXU passes). Accumulation itself is always f32."""
    return (
        jax.lax.Precision.DEFAULT
        if precision == "fast"
        else jax.lax.Precision.HIGHEST
    )


def _append_missing(hist_reg: jnp.ndarray, node_tot: jnp.ndarray) -> jnp.ndarray:
    """Reconstruct the missing-value bucket by subtraction.

    ``hist_reg`` is [n_nodes, F, n_bins, 2] over the regular (non-missing)
    bins; a row's gh lands in NO regular bin exactly when its value is
    missing, so per (node, feature): missing = node_total - sum(regular).
    Keeping the built histogram at n_bins (a 128-lane multiple for the
    default max_bin=256) instead of n_bins+1 avoids a whole extra MXU tile
    per pass (257 -> 3x128 tiles, 256 -> 2)."""
    miss = node_tot[:, None, :] - hist_reg.sum(axis=2)  # [n_nodes, F, 2]
    return jnp.concatenate([hist_reg, miss[:, :, None, :]], axis=2)


def _acc_dtype(gh) -> jnp.dtype:
    """Histogram accumulation dtype for a gh buffer: int32 for quantized
    (``gh_precision``) integer gradients — sums of narrow ints are EXACT in
    int32 up to ~2^31/qmax rows per (shard, bin) — float32 otherwise."""
    return (
        jnp.int32 if jnp.issubdtype(gh.dtype, jnp.integer) else jnp.float32
    )


def _node_totals_from_blocks(
    ghp: jnp.ndarray, node_of_block: jnp.ndarray, n_nodes: int
) -> jnp.ndarray:
    """[n_blocks, block, 2] node-uniform blocks -> [n_nodes + 1, 2] totals."""
    acc = _acc_dtype(ghp)
    block_sums = ghp.sum(axis=1, dtype=acc) if acc == jnp.int32 else ghp.sum(axis=1)
    return jnp.zeros((n_nodes + 1, 2), acc).at[node_of_block].add(block_sums)


def hist_scatter(
    bins: jnp.ndarray,  # [N, F] integer bins in 0..n_bins (n_bins == missing)
    gh: jnp.ndarray,  # [N, 2] float32 (grad, hess); padding rows must be 0
    pos: jnp.ndarray,  # [N] int32 node position within level, 0..n_nodes-1
    n_nodes: int,
    n_bins_total: int,  # n_bins + 1 (missing bucket included)
) -> jnp.ndarray:
    """Returns [n_nodes, F, n_bins_total, 2] float32 (int32 exact sums when
    ``gh`` is a quantized integer buffer)."""
    n, num_features = bins.shape
    b = bins.astype(jnp.int32)
    # flat bucket id per (row, feature)
    flat = (pos[:, None] * num_features + jnp.arange(num_features, dtype=jnp.int32)[None, :]) * n_bins_total + b
    acc = _acc_dtype(gh)
    if acc == jnp.int32:
        gh = gh.astype(jnp.int32)  # widen the [N, 2] source, not the fan-out
    out = jnp.zeros((n_nodes * num_features * n_bins_total, 2), acc)
    ghb = jnp.broadcast_to(gh[:, None, :], (n, num_features, 2))
    out = out.at[flat.reshape(-1)].add(ghb.reshape(-1, 2))
    return out.reshape(n_nodes, num_features, n_bins_total, 2)


def hist_onehot(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    chunk: int = 8192,
    precision: str = "highest",
) -> jnp.ndarray:
    """MXU-friendly histogram: per feature, hist = onehot(node*bins)ᵀ @ gh.

    Scans row chunks (outer) and features (inner); each inner step builds a
    [chunk, n_nodes*n_bins] one-hot over the REGULAR bins (missing rows get an
    all-zero one-hot and are reconstructed by subtraction, see
    ``_append_missing``) and contracts it against the chunk's [chunk, 2]
    grad/hess — a matmul XLA tiles onto the MXU. Padding rows have gh == 0 so
    over-padding of the last chunk is harmless.
    """
    n, num_features = bins.shape
    nb_reg = n_bins_total - 1  # regular bins; bucket nb_reg == missing
    nb = n_nodes * nb_reg
    prec = _einsum_precision(precision)
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    b = bins  # keep the storage dtype (uint8/int16): HBM matters at 11M rows
    if pad:
        b = jnp.pad(b, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
        pos = jnp.pad(pos, (0, pad))
    b = b.reshape(n_chunks, chunk, num_features)
    ghc = gh.reshape(n_chunks, chunk, 2)
    posc = pos.reshape(n_chunks, chunk)

    # quantized gradients (gh_precision): the one-hot and gh ride the matmul
    # in the narrow integer dtype accumulating int32 — exact, and the
    # int8 x int8 -> int32 MXU path on modern hardware. The bf16 "fast" knob
    # is meaningless here (integer accumulation is already the cheap mode).
    int_gh = jnp.issubdtype(gh.dtype, jnp.integer)
    acc_dt = jnp.int32 if int_gh else jnp.float32
    # fast mode: materialize the one-hot (the HBM-bound operand) in bf16 —
    # exact for 0/1 values, halves the traffic; gh rounds to bf16 (~0.2%)
    if int_gh:
        oh_dtype = gh.dtype
    else:
        oh_dtype = jnp.bfloat16 if precision == "fast" else jnp.float32

    # tile features so each sequential step does one WIDE dot — the scan/fori
    # step count, not FLOPs or HBM, bounds this path on TPU (measured v5e)
    ftile = min(4, num_features)
    n_ftiles = -(-num_features // ftile)
    f_pad = n_ftiles * ftile - num_features

    def chunk_step(carry, args):
        acc, tot = carry
        bc, ghk, pk = args  # [chunk, F], [chunk, 2], [chunk]
        bc = bc.astype(jnp.int32)  # per-chunk transient upcast
        if f_pad:
            # pad with missing-valued columns -> all-zero one-hot rows
            bc = jnp.pad(bc, ((0, 0), (0, f_pad)), constant_values=nb_reg)
        base = pk * nb_reg  # [chunk]
        ghk_c = ghk.astype(oh_dtype)

        def ftile_step(t, acc):
            cols = jax.lax.dynamic_slice_in_dim(bc, t * ftile, ftile, axis=1)
            # missing rows -> index -1 -> all-zero one-hot row
            idx = jnp.where(cols >= nb_reg, -1, base[:, None] + cols)
            oh = jax.nn.one_hot(idx, nb, dtype=oh_dtype)  # [chunk, ftile, nb]
            oh = oh.reshape(oh.shape[0], ftile * nb)
            contrib = jax.lax.dot_general(
                oh, ghk_c, (((0,), (0,)), ((), ())),
                precision=prec, preferred_element_type=acc_dt,
            )  # [ftile*nb, 2] (MXU, f32 — or exact int32 — accumulate)
            return jax.lax.dynamic_update_slice_in_dim(
                acc,
                jax.lax.dynamic_slice_in_dim(acc, t * ftile, ftile, axis=0)
                + contrib.reshape(ftile, nb, 2),
                t * ftile,
                axis=0,
            )

        acc = jax.lax.fori_loop(0, n_ftiles, ftile_step, acc)
        # node totals ride the scan as one extra tiny matmul per chunk (a
        # [N]-row scatter here measured ~20 ms/1M rows on TPU)
        if int_gh:
            oh_node = jax.nn.one_hot(pk, n_nodes, dtype=gh.dtype)
            tot = tot + jax.lax.dot_general(
                oh_node, ghk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
        else:
            oh_node = jax.nn.one_hot(pk, n_nodes, dtype=jnp.float32)
            tot = tot + jnp.matmul(oh_node.T, ghk, precision=jax.lax.Precision.HIGHEST)
        return (acc, tot), None

    acc0 = (
        jnp.zeros((n_ftiles * ftile, nb, 2), acc_dt),
        jnp.zeros((n_nodes, 2), acc_dt),
    )
    (acc, node_tot), _ = jax.lax.scan(chunk_step, acc0, (b, ghc, posc))
    # [F, n_nodes*nb_reg, 2] -> [n_nodes, F, nb_reg, 2]
    hist_reg = acc[:num_features].reshape(
        num_features, n_nodes, nb_reg, 2
    ).transpose(1, 0, 2, 3)
    return _append_missing(hist_reg, node_tot)


def update_partition_order(
    order: jnp.ndarray,  # [N] rows sorted stably by current pos
    counts: jnp.ndarray,  # [n_nodes] rows per node at the current level
    go_right: jnp.ndarray,  # [N] bool, indexed by ORIGINAL row id
) -> tuple:
    """O(N) stable segment split: maintain the sorted-by-node row order across
    one level of tree growth without re-sorting (the XLA analog of gpu_hist's
    incremental row partitioner). Returns (new_order, new_counts) for the
    2*n_nodes children."""
    n = order.shape[0]
    n_nodes = counts.shape[0]
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    seg_of_slot = jnp.searchsorted(
        jnp.cumsum(counts), jnp.arange(n), side="right"
    )
    gr_s = go_right[order]
    left_s = ~gr_s
    # exclusive cumulative left/right counts, segment-relative
    cum_left = jnp.cumsum(left_s) - left_s
    cum_right = jnp.cumsum(gr_s) - gr_s
    left_before = cum_left[seg_start]  # [n_nodes] lefts before each segment
    right_before = cum_right[seg_start]
    rank_left = cum_left - left_before[seg_of_slot]
    rank_right = cum_right - right_before[seg_of_slot]
    # child segment sizes
    seg_end = jnp.cumsum(counts) - 1
    total_left = jnp.where(
        counts > 0, cum_left[jnp.maximum(seg_end, 0)] + left_s[jnp.maximum(seg_end, 0)]
        - left_before, 0
    )
    left_count = total_left
    right_count = counts - left_count
    new_counts = jnp.stack([left_count, right_count], axis=1).reshape(-1)
    new_start = jnp.concatenate(
        [jnp.zeros((1,), new_counts.dtype), jnp.cumsum(new_counts)[:-1]]
    )
    child = 2 * seg_of_slot + gr_s.astype(seg_of_slot.dtype)
    rank = jnp.where(gr_s, rank_right, rank_left)
    dest = new_start[child] + rank
    new_order = jnp.zeros_like(order).at[dest].set(order)
    return new_order, new_counts


def select_small_child_rows(
    order: jnp.ndarray,  # [N] rows sorted stably by child node
    counts: jnp.ndarray,  # [2 * n_par] rows per child node
    small_is_right: jnp.ndarray,  # [n_par] bool
):
    """Compact the rows of every parent's smaller child into [N // 2] slots.

    The globally-smaller children hold at most half of all rows, so the
    compacted layout has a STATIC capacity of N // 2 — this is what turns
    sibling subtraction into a real 2x on row traffic (zeroing gh of the
    bigger child still feeds its rows through the MXU; gathering the smaller
    child's rows does not). Returns (rows [N//2] with sentinel N for unused
    slots, parent index per slot [N//2], valid mask [N//2], counts_sel
    [n_par]); rows come out sorted by parent, so they are directly a
    presorted (order=arange, counts=counts_sel) layout.
    """
    n = order.shape[0]
    n_par = small_is_right.shape[0]
    n_half = max(n // 2, 1)
    c_small = 2 * jnp.arange(n_par, dtype=jnp.int32) + small_is_right.astype(jnp.int32)
    counts_sel = counts[c_small]
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    cum_sel = jnp.cumsum(counts_sel)
    start_sel = jnp.concatenate([jnp.zeros((1,), cum_sel.dtype), cum_sel[:-1]])
    i = jnp.arange(n_half)
    p = jnp.searchsorted(cum_sel, i, side="right")
    pc = jnp.clip(p, 0, n_par - 1).astype(jnp.int32)
    src = seg_start[c_small[pc]] + (i - start_sel[pc])
    valid = i < cum_sel[-1]
    rows = jnp.where(valid, order[jnp.clip(src, 0, n - 1)], n).astype(jnp.int32)
    return rows, pc, valid, counts_sel


def presorted_block_layout(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    order: jnp.ndarray,  # [N] rows sorted stably by node
    counts: jnp.ndarray,  # [n_nodes]
    n_nodes: int,
    block: int,
):
    """Scatter presorted rows into node-uniform padded blocks.

    Returns (bp [n_blocks, block, F], ghp [n_blocks, block, 2],
    node_of_block [n_blocks]); padding slots carry zero gh. Shared by the XLA
    blocked-einsum path and the Pallas kernel so the layout math has one
    home.

    ``order`` may be SHORTER than bins (a compacted selection, e.g. the
    smaller-child rows under sibling subtraction): slots beyond
    ``sum(counts)`` and entries holding the sentinel ``bins.shape[0]`` land
    on the appended zero row and contribute nothing."""
    sentinel, num_features = bins.shape
    n_slots = order.shape[0]
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    padded_counts = ((counts + block - 1) // block) * block
    padded_cum = jnp.cumsum(padded_counts)
    padded_start = jnp.concatenate(
        [jnp.zeros((1,), padded_cum.dtype), padded_cum[:-1]]
    )
    seg_of_slot = jnp.searchsorted(
        jnp.cumsum(counts), jnp.arange(n_slots), side="right"
    )
    seg_c = jnp.minimum(seg_of_slot, counts.shape[0] - 1)
    rank_in_node = jnp.arange(n_slots) - seg_start[seg_c]
    in_range = seg_of_slot < counts.shape[0]
    dest = jnp.where(in_range, padded_start[seg_c] + rank_in_node, -1).astype(jnp.int32)

    cap = (-(-n_slots // block) + n_nodes) * block
    n_blocks = cap // block
    # OOB dest (-1 slots beyond the selection) are dropped by the scatter
    row_of_slot = jnp.full((cap,), sentinel, jnp.int32).at[dest].set(
        order.astype(jnp.int32), mode="drop"
    )
    node_of_block = jnp.clip(
        jnp.searchsorted(padded_cum, jnp.arange(n_blocks) * block, side="right"),
        0,
        n_nodes,
    ).astype(jnp.int32)
    # keep the bins gather in the storage dtype (uint8/int16): the padded
    # block copy is the largest per-level buffer (11M x 28 would be 1.2 GB
    # as int32 — enough to OOM an 11M-row training step on a 16 GB chip)
    bins_ext = jnp.concatenate([bins, jnp.zeros((1, num_features), bins.dtype)])
    gh_ext = jnp.concatenate([gh, jnp.zeros((1, 2), gh.dtype)])
    bp = bins_ext[row_of_slot].reshape(n_blocks, block, num_features)
    ghp = gh_ext[row_of_slot].reshape(n_blocks, block, 2)
    return bp, ghp, node_of_block


def hist_partition_presorted(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    order: jnp.ndarray,  # [N] rows sorted stably by node
    counts: jnp.ndarray,  # [n_nodes]
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    block_chunk: int = 512,
    precision: str = "highest",
) -> jnp.ndarray:
    """hist_partition with the sort/bincount already maintained by the caller
    (see ``update_partition_order``)."""
    num_features = bins.shape[1]
    bp, ghp, node_of_block = presorted_block_layout(
        bins, gh, order, counts, n_nodes, block
    )
    return _blocked_hist(
        bp, ghp, node_of_block, n_nodes, n_bins_total, num_features,
        block_chunk, precision,
    )


def _blocked_hist(bp, ghp, node_of_block, n_nodes, n_bins_total, num_features,
                  block_chunk, precision: str = "highest"):
    nb_reg = n_bins_total - 1  # regular bins; missing reconstructed after
    prec = _einsum_precision(precision)
    node_tot = _node_totals_from_blocks(ghp, node_of_block, n_nodes)
    n_blocks = bp.shape[0]
    n_chunks = -(-n_blocks // block_chunk)
    pad_blocks = n_chunks * block_chunk - n_blocks
    if pad_blocks:
        bp = jnp.pad(bp, ((0, pad_blocks), (0, 0), (0, 0)))
        ghp = jnp.pad(ghp, ((0, pad_blocks), (0, 0), (0, 0)))
        node_of_block = jnp.pad(node_of_block, (0, pad_blocks), constant_values=n_nodes)
    bp = bp.reshape(n_chunks, block_chunk, -1, num_features)
    ghp = ghp.reshape(n_chunks, block_chunk, -1, 2)
    nodes_c = node_of_block.reshape(n_chunks, block_chunk)

    # quantized gradients: narrow-int one-hot x gh, exact int32 accumulation
    # (see hist_onehot); the bf16 fast mode does not apply
    acc_dt = _acc_dtype(ghp)
    if acc_dt == jnp.int32:
        oh_dtype = ghp.dtype
    else:
        oh_dtype = jnp.bfloat16 if precision == "fast" else jnp.float32
    # tile features per sequential step (step count, not FLOPs, bounds this
    # path on TPU — same treatment as hist_onehot)
    ftile = min(4, num_features)
    n_ftiles = -(-num_features // ftile)
    f_pad = n_ftiles * ftile - num_features

    def chunk_step(hist, args):
        bc, gc, nodes = args
        bc = bc.astype(jnp.int32)  # per-chunk transient upcast
        if f_pad:
            # missing-valued pad columns produce all-zero one-hot rows
            bc = jnp.pad(bc, ((0, 0), (0, 0), (0, f_pad)), constant_values=nb_reg)
        gc_c = gc.astype(oh_dtype)

        def ftile_step(t, hist):
            cols = jax.lax.dynamic_slice_in_dim(bc, t * ftile, ftile, axis=2)
            # bins == nb_reg (missing) exceed the one-hot width -> zero rows
            oh = jax.nn.one_hot(cols, nb_reg, dtype=oh_dtype)  # [C, b, T, nb]
            contrib = jnp.einsum("cbtn,cbd->ctnd", oh, gc_c, precision=prec,
                                 preferred_element_type=acc_dt)
            # scatter the [C, T, nb, 2] tile contributions into the node rows
            sl = jax.lax.dynamic_slice_in_dim(hist, t * ftile, ftile, axis=1)
            sl = sl.at[nodes, :, :, :].add(contrib)
            return jax.lax.dynamic_update_slice_in_dim(hist, sl, t * ftile, axis=1)

        hist = jax.lax.fori_loop(0, n_ftiles, ftile_step, hist)
        return hist, None

    hist0 = jnp.zeros((n_nodes + 1, n_ftiles * ftile, nb_reg, 2), acc_dt)
    hist, _ = jax.lax.scan(chunk_step, hist0, (bp, ghp, nodes_c))
    hist = hist[:, :num_features]
    return _append_missing(hist[:n_nodes], node_tot[:n_nodes])


def hist_partition(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    block: int = 256,
    block_chunk: int = 512,
    precision: str = "highest",
) -> jnp.ndarray:
    """Node-contiguous blocked histogram — the deep-level TPU workhorse.

    The one-hot-matmul formulation costs rows x nodes x bins FLOPs (the node
    axis rides in the one-hot width), which explodes at deep levels. This
    variant first *partitions rows by node* (stable sort + padded segment
    layout, the XLA analog of gpu_hist's row partitioner), so every
    ``block``-row tile belongs to exactly one node and the per-tile matmul is
    only [bins x block] @ [block x 2]: total FLOPs ~ rows x bins x features,
    independent of the node count. The final per-block scatter touches
    O(n_blocks) elements only.
    """
    num_features = bins.shape[1]
    order = jnp.argsort(pos, stable=True)
    counts = jnp.bincount(pos, length=n_nodes)
    bp, ghp, node_of_block = presorted_block_layout(
        bins, gh, order, counts, n_nodes, block
    )
    return _blocked_hist(
        bp, ghp, node_of_block, n_nodes, n_bins_total, num_features,
        block_chunk, precision,
    )


def node_sums(gh: jnp.ndarray, pos: jnp.ndarray, n_nodes: int) -> jnp.ndarray:
    """Per-node (grad, hess) totals: [n_nodes, 2] via segment-sum (exact
    int32 sums for quantized integer gh)."""
    acc = _acc_dtype(gh)
    out = jnp.zeros((n_nodes, 2), acc)
    return out.at[pos].add(gh if gh.dtype == acc else gh.astype(acc))


def zero_phantom_missing(h: jnp.ndarray, feat_has_missing) -> jnp.ndarray:
    """h: [nn, F, nbt, 2]; zero the (subtraction-reconstructed) missing
    bucket where the feature provably has NO missing values — under
    hist_precision="fast" the bf16 rounding residue of the regular bins
    lands in that bucket, and phantom missing mass must not steer the
    learned default direction. Shared by both growers (depthwise build_tree
    and the lossguide scan)."""
    if feat_has_missing is None:
        return h
    keep = feat_has_missing[None, :, None].astype(h.dtype)
    return h.at[:, :, -1, :].multiply(keep)


def build_histogram(
    bins: jnp.ndarray,
    gh: jnp.ndarray,
    pos: jnp.ndarray,
    n_nodes: int,
    n_bins_total: int,
    impl: str = "scatter",
    chunk: int = 8192,
    precision: str = "highest",
) -> jnp.ndarray:
    """Back-compat shim over the histogram-provider registry: resolves
    ``impl`` through ``ops.provider`` (the ONE string -> strategy point)
    and builds with no maintained row layout. The growers dispatch through
    a resolved :class:`~xgboost_ray_tpu.ops.provider.HistogramProvider`
    directly; this entry point serves standalone callers (profiling,
    micro-benchmarks, tests)."""
    from xgboost_ray_tpu.ops.provider import resolve_hist_provider

    provider = resolve_hist_provider(impl, precision=precision, chunk=chunk)
    return provider.build(bins, gh, pos, n_nodes, n_bins_total)
