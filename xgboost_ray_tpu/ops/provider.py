"""Pluggable histogram providers + the feature-parallel shard context.

Histogram construction used to be selected by a ``hist_impl`` STRING that
was re-interpreted at three separate layers (``engine.resolve_hist_impl``,
the branch ladder in ``ops/grow.py``'s per-level ``_build_raw``, and
``ops/histogram.py``'s ``build_histogram``). This module replaces that
spread with one protocol object: a :class:`HistogramProvider` owns the
whole decision of HOW a ``[n_nodes, F, n_bins+1, 2]`` gradient histogram is
accumulated from (possibly compacted, possibly presorted) rows, and the
growers are provider-blind. Providers are registered by name, so an
alternative implementation (a future kernel, a debugging reference, an A/B
candidate in bench.py) plugs in by registration instead of by editing the
dispatch ladders:

    register_histogram_provider("mine", MyProvider)
    params = {"hist_impl": "mine", ...}

Every provider is a frozen dataclass (hashable — it rides inside the
jit-static :class:`~xgboost_ray_tpu.ops.grow.GrowConfig`-adjacent closures)
constructed with the two knobs all builds share: ``precision`` (the MXU
accumulation contract, see ``ops/histogram.py``) and ``chunk`` (row-chunk
length for the scanning builds).

The second half of this module is :class:`FeatureShard`: the trace-time
context of the 2D row x feature mesh (``feature_parallel`` > 1). It names
the feature mesh axis and carries the three collective helpers the sharded
growers need — the shard-0 broadcast of histogram-derived node totals, the
owner-broadcast of a winning feature's bin column (one ``[N]`` psum per
level, so partition update stays O(rows) not O(rows x F)), and global
feature-index arithmetic. All cross-shard traffic it emits rides the
feature axis; the histogram allreduce itself stays on the actors axis.
"""

import dataclasses
from typing import Optional, Tuple, Type

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.histogram import (
    hist_onehot,
    hist_partition,
    hist_partition_presorted,
    hist_scatter,
)


def _gather_rows(bins, gh, rows_sel):
    """Materialize a compacted row selection for gather-based builds.

    ``rows_sel`` indexes the FULL bins/gh with the sentinel ``n`` for unused
    slots; sentinel slots clamp to the last row with zeroed gh so they
    contribute nothing. ``None`` passes the full arrays through.
    """
    if rows_sel is None:
        return bins, gh
    n = bins.shape[0]
    rows_c = jnp.minimum(rows_sel, n - 1)
    ok = (rows_sel < n)[:, None].astype(gh.dtype)
    return bins[rows_c], gh[rows_c] * ok


@dataclasses.dataclass(frozen=True)
class HistogramProvider:
    """One histogram build strategy behind a uniform interface.

    ``build`` returns the ``[n_nodes, F_local, n_bins_total, 2]`` float32
    histogram for one tree level (or lossguide step). The grower supplies
    whatever row layout it maintains; a provider consumes what it needs:

    * ``pos`` — per-row (or per-selected-slot) node index, always present;
    * ``order``/``counts`` — rows stably sorted by node + per-node counts,
      maintained by the grower iff :attr:`wants_order` is True;
    * ``rows_sel`` — a compacted row-id view (sibling subtraction's
      smaller-child selection or a sampling selection), sentinel ``n`` for
      unused slots. Presorted builds consume it directly as the row order;
      gather builds materialize it first.
    """

    precision: str = "highest"
    chunk: int = 8192

    #: registry key (subclasses override)
    name = "base"
    #: True when the grower should maintain the presorted order/counts
    #: layout across levels (the O(N) stable segment split)
    wants_order = False

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class ScatterHistogram(HistogramProvider):
    """One flat XLA scatter-add — correct everywhere, the CPU default."""

    name = "scatter"

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        bins_g, gh_g = _gather_rows(bins, gh, rows_sel)
        return hist_scatter(bins_g, gh_g, pos, n_nodes, n_bins_total)


@dataclasses.dataclass(frozen=True)
class OnehotHistogram(HistogramProvider):
    """Row-chunked one-hot x (grad, hess) matmuls on the MXU."""

    name = "onehot"

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        bins_g, gh_g = _gather_rows(bins, gh, rows_sel)
        return hist_onehot(bins_g, gh_g, pos, n_nodes, n_bins_total,
                           chunk=self.chunk, precision=self.precision)


@dataclasses.dataclass(frozen=True)
class PartitionHistogram(HistogramProvider):
    """Node-contiguous presorted blocks: FLOPs independent of node fan-out."""

    name = "partition"
    wants_order = True

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        order_in = rows_sel if rows_sel is not None else order
        if order_in is None:
            # no maintained layout (standalone callers): sort here
            return hist_partition(bins, gh, pos, n_nodes, n_bins_total,
                                  precision=self.precision)
        return hist_partition_presorted(
            bins, gh, order_in, counts, n_nodes, n_bins_total,
            precision=self.precision,
        )


@dataclasses.dataclass(frozen=True)
class MixedHistogram(HistogramProvider):
    """One-hot at tiny node fan-out, presorted blocks beyond (measured v5e
    crossover; see ops/grow.py module docstring)."""

    name = "mixed"
    wants_order = True

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        order_in = rows_sel if rows_sel is not None else order
        if order_in is not None:
            if n_nodes <= 2:
                bins_g, gh_g = _gather_rows(bins, gh, rows_sel)
                return hist_onehot(bins_g, gh_g, pos, n_nodes, n_bins_total,
                                   chunk=self.chunk,
                                   precision=self.precision)
            return hist_partition_presorted(
                bins, gh, order_in, counts, n_nodes, n_bins_total,
                precision=self.precision,
            )
        if n_nodes <= 4:
            return hist_onehot(bins, gh, pos, n_nodes, n_bins_total,
                               chunk=self.chunk, precision=self.precision)
        return hist_partition(bins, gh, pos, n_nodes, n_bins_total,
                              precision=self.precision)


@dataclasses.dataclass(frozen=True)
class VmappedKProvider(HistogramProvider):
    """Histogram build seam for the vmapped-K (multi-candidate HPO) round.

    Under ``jax.vmap`` over the lane axis the delegate build's scatter/
    matmul primitives batch mechanically — each lane accumulates its own
    ``[n_nodes, F, nbt, 2]`` histogram from its own (sampled) gh — so the
    default implementation simply delegates to a base provider and lets
    vmap's batching rules do the stacking. The point of routing through the
    registry anyway is the seam: a TPU kernel that folds the K axis into
    one scatter (lane-major node index ``k * n_nodes + pos``) registers a
    subclass here and every grower picks it up through ``cfg.hist_provider``
    with zero grower changes, exactly like any other ``hist_impl``.

    ``base`` must name a gather-based provider (``wants_order`` False):
    the presorted-partition layouts maintain ONE row order per tree, but
    vmapped lanes sample and route rows independently, so a shared order
    table would be wrong for every lane but one.
    """

    base: str = "scatter"

    name = "vmapped_k"
    wants_order = False

    def delegate(self) -> HistogramProvider:
        prov = resolve_hist_provider(self.base, self.precision, self.chunk)
        if prov.wants_order:
            raise NotImplementedError(
                f"hist_impl {self.base!r} maintains a presorted row order "
                "and cannot back the vmapped-K build (per-lane row "
                "routing diverges); use a gather-based provider"
            )
        return prov

    def build(self, bins, gh, pos, n_nodes, n_bins_total, *, order=None,
              counts=None, rows_sel=None):
        return self.delegate().build(
            bins, gh, pos, n_nodes, n_bins_total,
            order=order, counts=counts, rows_sel=rows_sel,
        )


def vmapped_k_impl(base: str) -> str:
    """Return (registering on first use) the ``hist_impl`` name of the
    vmapped-K provider delegating to ``base`` — e.g. ``vmapped_k[scatter]``.
    The engine's vmapped path resolves its configured impl through this so
    the lane-batched build is a first-class registry citizen."""
    if base == "auto":
        base = default_hist_impl()
    name = f"vmapped_k[{base}]"
    if name not in _PROVIDERS:
        cls = dataclasses.make_dataclass(
            f"VmappedK_{base}",
            [("base", str, dataclasses.field(default=base))],
            bases=(VmappedKProvider,),
            frozen=True,
        )
        cls.name = name
        register_histogram_provider(name, cls)
    return name


_PROVIDERS = {
    cls.name: cls
    for cls in (ScatterHistogram, OnehotHistogram, PartitionHistogram,
                MixedHistogram, VmappedKProvider)
}


def register_histogram_provider(
    name: str, cls: Type[HistogramProvider], overwrite: bool = False
) -> None:
    """Register a provider class under ``name`` (then usable as a
    ``hist_impl`` value). ``cls`` must construct from ``(precision, chunk)``
    keywords. Re-registering a builtin requires ``overwrite=True``."""
    if not overwrite and name in _PROVIDERS:
        raise ValueError(f"histogram provider {name!r} already registered")
    if name == "auto":
        raise ValueError("'auto' is the backend-default selector, not a "
                         "registrable provider name")
    _PROVIDERS[name] = cls


def available_hist_impls() -> Tuple[str, ...]:
    """Valid ``hist_impl`` values: 'auto' plus every registered provider."""
    return ("auto",) + tuple(sorted(_PROVIDERS))


def default_hist_impl() -> str:
    """Backend policy behind ``hist_impl='auto'``: scatter on CPU (parity
    tests), mixed on accelerators (one-hot MXU matmuls while the node
    fan-out is small, node-contiguous partitioning beyond)."""
    return "scatter" if jax.default_backend() == "cpu" else "mixed"


def resolve_hist_provider(
    impl: str, precision: str = "highest", chunk: int = 8192
) -> HistogramProvider:
    """The one string -> provider resolution point."""
    if impl == "auto":
        impl = default_hist_impl()
    cls = _PROVIDERS.get(impl)
    if cls is None:
        # defense-in-depth behind parse_params: a typo'd or removed impl
        # (e.g. the deleted 'pallas') must not silently become scatter
        raise ValueError(
            f"unknown histogram provider {impl!r}; registered: "
            f"{sorted(_PROVIDERS)}"
        )
    return cls(precision=precision, chunk=chunk)


class FeatureShard:
    """Trace-time context of the feature-parallel mesh axis.

    Constructed by the engine per traced round body when
    ``feature_parallel`` > 1 and threaded through the growers; ``None``
    means the 1D row mesh and every consumer takes its legacy path (the
    C=1-is-bitwise contract). All methods are called under ``shard_map``
    over the 2D mesh, where ``bins`` is this chip's ``[N/R, F_pad/C]``
    tile and feature indices in split records are GLOBAL (padded) indices.
    """

    def __init__(self, axis: str, num_shards: int, f_padded: int,
                 f_real: int, counter=None):
        self.axis = axis
        self.num_shards = int(num_shards)
        #: padded global feature count (a multiple of ``num_shards``)
        self.f_padded = int(f_padded)
        #: real (unpadded) feature count
        self.f_real = int(f_real)
        #: AllreduceBytes counter with the FEATURE-axis ring extent (the
        #: actors-axis traffic is counted by the growers' own counter)
        self.counter = counter

    def offset(self, f_local: int):
        """This shard's first global feature index (traced)."""
        return jax.lax.axis_index(self.axis) * f_local

    def slice_cols(self, arr, f_local: int, axis: int = 0):
        """Slice a global per-feature array down to this shard's columns."""
        return jax.lax.dynamic_slice_in_dim(
            arr, self.offset(f_local), f_local, axis=axis
        )

    def bcast_from_shard0(self, x):
        """Replicate shard 0's value across the feature axis.

        Used for histogram-READOUT node totals (``hist[:, 0]`` bucket
        sums): every shard reads a different feature column, whose f32
        rounding differs, and node totals feeding leaf weights must be
        identical on every chip — so the column the 1D program reads
        (global feature 0, owned by shard 0) wins.
        """
        if self.counter is not None:
            self.counter.add_allreduce(x)
        is_shard0 = jax.lax.axis_index(self.axis) == 0
        return jax.lax.psum(
            jnp.where(is_shard0, x, jnp.zeros_like(x)), self.axis
        )

    def bin_column(self, bins, f_global):
        """Every row's bin value at a GLOBAL feature index — the winning
        feature's bin column, broadcast from its owner shard.

        ``f_global`` is [N] int32 (per-row, typically ``feature[pos]``).
        Exactly one shard owns each feature, so the masked psum is an
        owner-broadcast: one [N] int32 collective per call — O(rows), the
        partition-update cost contract of the 2D mesh.
        """
        f_local = bins.shape[1]
        off = self.offset(f_local)
        local_f = jnp.clip(f_global - off, 0, f_local - 1)
        bv = jnp.take_along_axis(
            bins.astype(jnp.int32), local_f[:, None], axis=1
        )[:, 0]
        own = (f_global >= off) & (f_global < off + f_local)
        contrib = jnp.where(own, bv, 0)
        if self.counter is not None:
            self.counter.add_allreduce(contrib)
        return jax.lax.psum(contrib, self.axis)
