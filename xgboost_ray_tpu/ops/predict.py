"""Vectorized tree-ensemble prediction on raw feature values.

TPU-native replacement for xgboost's C++ prediction kernel
(``model.predict(local_data)`` in the reference actor,
``xgboost_ray/main.py:795-810``).

The padded-heap tree layout (see ``grow.py``) makes prediction a fixed-length
gather walk: ``max_depth`` steps of (feature gather, compare, child index),
identical for every row — no data-dependent control flow, so the whole
ensemble walk jits into one fused XLA program. Trees are vmapped; per-class
routing for multiclass sums tree outputs round-robin into K margins.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.grow import Tree, cat_mask_const as _cat_mask_const


def _step_right(tree, idx, xv, f, cat_mask):
    """Routing rule shared by every raw-x walk: numeric = threshold compare,
    categorical = code equality (candidate category goes left), missing =
    learned default."""
    present_right = xv >= tree.threshold[idx]
    if cat_mask is not None:
        code = jnp.round(xv).astype(jnp.int32)
        present_right = jnp.where(
            cat_mask[f], code != tree.split_bin[idx], present_right
        )
    return jnp.where(jnp.isnan(xv), ~tree.default_left[idx], present_right)


def _walk_one_tree(
    tree: Tree, x: jnp.ndarray, max_depth: int, cat_mask=None
) -> jnp.ndarray:
    """x: [N, F] raw (may contain NaN). Returns leaf values [N]."""
    n, num_features = x.shape
    idx = jnp.zeros((n,), jnp.int32)
    for _ in range(max_depth):
        f = jnp.clip(tree.feature[idx], 0, num_features - 1)
        xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
        go_right = _step_right(tree, idx, xv, f, cat_mask)
        nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
        idx = jnp.where(tree.is_leaf[idx], idx, nxt)
    return tree.value[idx]


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_margin(
    forest: Tree,  # stacked trees: each field [T, heap]
    x: jnp.ndarray,  # [N, F] float32 raw features
    base_margin: jnp.ndarray,  # [N, K] starting margin
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,  # [T] per-tree scale (DART)
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Sum leaf values of all trees into per-class margins. Returns [N, K]."""
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, x.shape[1])
    leaf = jax.vmap(lambda tr: _walk_one_tree(tr, x, max_depth, cat_mask))(forest)  # [T, N]
    if tree_weights is not None:
        leaf = leaf * tree_weights[:, None]
    if ntree_limit:
        keep = jnp.arange(t) < ntree_limit
        leaf = jnp.where(keep[:, None], leaf, 0.0)
    if num_outputs == 1:
        margin = base_margin[:, 0] + leaf.sum(axis=0) / num_parallel_tree
        return margin[:, None]
    # tree t belongs to class (t // num_parallel_tree) % K (round-major layout)
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=leaf.dtype)  # [T, K]
    return base_margin + (leaf.T @ onehot) / num_parallel_tree


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_contribs(
    forest: Tree,  # stacked trees: each field [T, heap]
    x: jnp.ndarray,  # [N, F] float32 raw features
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Per-feature prediction contributions (xgboost ``pred_contribs`` with
    ``approx_contribs=True`` — Saabas path attribution; reference surface:
    ``xgb.Booster.predict`` passed through at ``xgboost_ray/main.py:795-810``).

    Walking x's path, each split's expected-value change
    ``base_weight[child] - base_weight[node]`` is credited to the split
    feature; the bias column gets ``base_weight[root]``. The credits telescope,
    so each row of the result sums exactly to that row's margin (minus the
    base-score offset, which the caller adds to the bias column).

    Returns [T-summed] contributions ``[N, K, F+1]`` (bias last).

    Trees are accumulated with ``lax.scan`` (not vmap) so peak memory is the
    O(N*K*F) accumulator, never a [T, N, F] intermediate.
    """
    n, num_features = x.shape
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, num_features)

    scale = jnp.ones((t,), jnp.float32)
    if tree_weights is not None:
        scale = scale * tree_weights
    if ntree_limit:
        scale = jnp.where(jnp.arange(t) < ntree_limit, scale, 0.0)
    scale = scale / num_parallel_tree
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=jnp.float32) * scale[:, None]  # [T, K]

    def tree_step(acc, args):
        tree, oh = args  # Tree of [heap] fields, [K]
        feat_acc, bias_acc = acc
        idx = jnp.zeros((n,), jnp.int32)
        contrib = jnp.zeros((n, num_features), jnp.float32)
        for _ in range(max_depth):
            stepped = ~tree.is_leaf[idx] & (tree.feature[idx] >= 0)
            f = jnp.clip(tree.feature[idx], 0, num_features - 1)
            xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            go_right = _step_right(tree, idx, xv, f, cat_mask)
            nxt = jnp.where(stepped, 2 * idx + 1 + go_right.astype(jnp.int32), idx)
            delta = jnp.where(
                stepped, tree.base_weight[nxt] - tree.base_weight[idx], 0.0
            )
            contrib = contrib.at[jnp.arange(n), f].add(delta)
            idx = nxt
        feat_acc = feat_acc + jnp.einsum("nf,k->nkf", contrib, oh)
        bias_acc = bias_acc + tree.base_weight[0] * oh
        return (feat_acc, bias_acc), None

    acc0 = (
        jnp.zeros((n, num_outputs, num_features), jnp.float32),
        jnp.zeros((num_outputs,), jnp.float32),
    )
    (feat_part, bias_part), _ = jax.lax.scan(tree_step, acc0, (forest, onehot))
    bias = jnp.broadcast_to(bias_part[None, :, None], (n, num_outputs, 1))
    return jnp.concatenate([feat_part, bias], axis=2)


def predict_leaf_index(
    forest: Tree, x: jnp.ndarray, max_depth: int, cat_features: tuple = ()
) -> jnp.ndarray:
    """Per-tree leaf heap index for each row (xgboost pred_leaf analog). [N, T]."""
    n, num_features = x.shape
    cat_mask = _cat_mask_const(cat_features, num_features)

    def walk(tree):
        idx = jnp.zeros((n,), jnp.int32)
        for _ in range(max_depth):
            f = jnp.clip(tree.feature[idx], 0, num_features - 1)
            xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            go_right = _step_right(tree, idx, xv, f, cat_mask)
            nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
            idx = jnp.where(tree.is_leaf[idx], idx, nxt)
        return idx

    return jax.vmap(walk)(forest).T
