"""Vectorized tree-ensemble prediction on raw feature values.

TPU-native replacement for xgboost's C++ prediction kernel
(``model.predict(local_data)`` in the reference actor,
``xgboost_ray/main.py:795-810``).

The padded-heap tree layout (see ``grow.py``) makes prediction a fixed-length
gather walk: ``max_depth`` steps of (feature gather, compare, child index),
identical for every row — no data-dependent control flow, so the whole
ensemble walk jits into one fused XLA program. Trees are vmapped; per-class
routing for multiclass sums tree outputs round-robin into K margins.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from xgboost_ray_tpu.ops.grow import Tree, cat_mask_const as _cat_mask_const


def _step_right(tree, idx, xv, f, cat_mask):
    """Routing rule shared by every raw-x walk: numeric = threshold compare,
    categorical = code equality (candidate category goes left), missing =
    learned default."""
    present_right = xv >= tree.threshold[idx]
    if cat_mask is not None:
        code = jnp.round(xv).astype(jnp.int32)
        present_right = jnp.where(
            cat_mask[f], code != tree.split_bin[idx], present_right
        )
    return jnp.where(jnp.isnan(xv), ~tree.default_left[idx], present_right)


def _walk_one_tree(
    tree: Tree, x: jnp.ndarray, max_depth: int, cat_mask=None
) -> jnp.ndarray:
    """x: [N, F] raw (may contain NaN). Returns leaf values [N]."""
    n, num_features = x.shape
    idx = jnp.zeros((n,), jnp.int32)
    for _ in range(max_depth):
        f = jnp.clip(tree.feature[idx], 0, num_features - 1)
        xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
        go_right = _step_right(tree, idx, xv, f, cat_mask)
        nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
        idx = jnp.where(tree.is_leaf[idx], idx, nxt)
    return tree.value[idx]


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_margin(
    forest: Tree,  # stacked trees: each field [T, heap]
    x: jnp.ndarray,  # [N, F] float32 raw features
    base_margin: jnp.ndarray,  # [N, K] starting margin
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,  # [T] per-tree scale (DART)
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Sum leaf values of all trees into per-class margins. Returns [N, K]."""
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, x.shape[1])
    leaf = jax.vmap(lambda tr: _walk_one_tree(tr, x, max_depth, cat_mask))(forest)  # [T, N]
    if tree_weights is not None:
        leaf = leaf * tree_weights[:, None]
    if ntree_limit:
        keep = jnp.arange(t) < ntree_limit
        leaf = jnp.where(keep[:, None], leaf, 0.0)
    if num_outputs == 1:
        margin = base_margin[:, 0] + leaf.sum(axis=0) / num_parallel_tree
        return margin[:, None]
    # tree t belongs to class (t // num_parallel_tree) % K (round-major layout)
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=leaf.dtype)  # [T, K]
    return base_margin + (leaf.T @ onehot) / num_parallel_tree


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_contribs(
    forest: Tree,  # stacked trees: each field [T, heap]
    x: jnp.ndarray,  # [N, F] float32 raw features
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Per-feature prediction contributions (xgboost ``pred_contribs`` with
    ``approx_contribs=True`` — Saabas path attribution; reference surface:
    ``xgb.Booster.predict`` passed through at ``xgboost_ray/main.py:795-810``).

    Walking x's path, each split's expected-value change
    ``base_weight[child] - base_weight[node]`` is credited to the split
    feature; the bias column gets ``base_weight[root]``. The credits telescope,
    so each row of the result sums exactly to that row's margin (minus the
    base-score offset, which the caller adds to the bias column).

    Returns [T-summed] contributions ``[N, K, F+1]`` (bias last).

    Trees are accumulated with ``lax.scan`` (not vmap) so peak memory is the
    O(N*K*F) accumulator, never a [T, N, F] intermediate.
    """
    n, num_features = x.shape
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, num_features)

    scale = jnp.ones((t,), jnp.float32)
    if tree_weights is not None:
        scale = scale * tree_weights
    if ntree_limit:
        scale = jnp.where(jnp.arange(t) < ntree_limit, scale, 0.0)
    scale = scale / num_parallel_tree
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=jnp.float32) * scale[:, None]  # [T, K]

    def tree_step(acc, args):
        tree, oh = args  # Tree of [heap] fields, [K]
        feat_acc, bias_acc = acc
        idx = jnp.zeros((n,), jnp.int32)
        contrib = jnp.zeros((n, num_features), jnp.float32)
        for _ in range(max_depth):
            stepped = ~tree.is_leaf[idx] & (tree.feature[idx] >= 0)
            f = jnp.clip(tree.feature[idx], 0, num_features - 1)
            xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            go_right = _step_right(tree, idx, xv, f, cat_mask)
            nxt = jnp.where(stepped, 2 * idx + 1 + go_right.astype(jnp.int32), idx)
            delta = jnp.where(
                stepped, tree.base_weight[nxt] - tree.base_weight[idx], 0.0
            )
            contrib = contrib.at[jnp.arange(n), f].add(delta)
            idx = nxt
        feat_acc = feat_acc + jnp.einsum("nf,k->nkf", contrib, oh)
        bias_acc = bias_acc + tree.base_weight[0] * oh
        return (feat_acc, bias_acc), None

    acc0 = (
        jnp.zeros((n, num_outputs, num_features), jnp.float32),
        jnp.zeros((num_outputs,), jnp.float32),
    )
    (feat_part, bias_part), _ = jax.lax.scan(tree_step, acc0, (forest, onehot))
    bias = jnp.broadcast_to(bias_part[None, :, None], (n, num_outputs, 1))
    return jnp.concatenate([feat_part, bias], axis=2)


def _shap_weight_table(max_depth: int):
    """Ctab[m, k] = k! (m-1-k)! / m!  — the Shapley permutation weight for a
    coalition of size k among m players (0 outside k < m)."""
    import numpy as np

    fact = [1.0]
    for i in range(1, max_depth + 2):
        fact.append(fact[-1] * i)
    ctab = np.zeros((max_depth + 1, max(max_depth, 1)), np.float32)
    for m in range(1, max_depth + 1):
        for k in range(m):
            ctab[m, k] = fact[k] * fact[m - 1 - k] / fact[m]
    return jnp.asarray(ctab)


def _shap_path_data(tree: Tree, x: jnp.ndarray, slot: jnp.ndarray,
                    max_depth: int, cat_mask):
    """Root-to-leaf path data for one bottom slot of the padded heap.

    Every leaf is represented by exactly one *canonical* slot (the one whose
    remaining steps below the leaf all go left), so summing slot contributions
    enumerates each leaf once. Returns per-step lists over s in [0, D):
    features ``fs`` (scalar), zero-fractions ``zs`` (scalar, cover ratio),
    one-fractions ``os`` ([N], does x follow this branch), ``valids`` (scalar
    bool, real split on a canonical path) — duplicates already merged into
    their first occurrence (TreeSHAP's repeated-feature rule) — plus the leaf
    value ``v_leaf`` and player count ``m``.
    """
    n, num_features = x.shape
    d = max_depth
    nodes = [jnp.int32(0)]
    bits = []
    for s in range(d):
        b = ((slot >> (d - 1 - s)) & 1).astype(jnp.int32)
        bits.append(b)
        nodes.append(2 * nodes[-1] + 1 + b)
    leaf_found = jnp.zeros((), bool)
    leaf_d = jnp.int32(d)
    for depth, node in enumerate(nodes):
        hit = tree.is_leaf[node] & ~leaf_found
        leaf_d = jnp.where(hit, jnp.int32(depth), leaf_d)
        leaf_found = leaf_found | tree.is_leaf[node]
    canon = leaf_found
    for s in range(d):
        canon = canon & ((s < leaf_d) | (bits[s] == 0))
    v_leaf = jnp.stack([tree.value[i] for i in nodes])[leaf_d]

    zs, os_, fs, valids = [], [], [], []
    for s in range(d):
        i_n, i_c = nodes[s], nodes[s + 1]
        valid = canon & (s < leaf_d)
        f = jnp.clip(tree.feature[i_n], 0, num_features - 1)
        z = jnp.where(
            tree.cover[i_n] > 0.0,
            tree.cover[i_c] / jnp.maximum(tree.cover[i_n], 1e-12),
            0.0,
        )
        xv = jnp.take(x, f, axis=1)
        go_right = _step_right(tree, i_n, xv, f, cat_mask)
        o = (go_right.astype(jnp.int32) == bits[s]).astype(jnp.float32)
        zs.append(z)
        os_.append(o)
        fs.append(f)
        valids.append(valid)

    # merge repeated features into their first occurrence (z,o multiply)
    for s in range(1, d):
        merged = jnp.zeros((), bool)
        for j in range(s):
            can = valids[j] & valids[s] & (fs[j] == fs[s]) & ~merged
            zs[j] = jnp.where(can, zs[j] * zs[s], zs[j])
            os_[j] = jnp.where(can, os_[j] * os_[s], os_[j])
            merged = merged | can
        valids[s] = valids[s] & ~merged

    m = sum(v.astype(jnp.int32) for v in valids)
    return fs, zs, os_, valids, v_leaf, m, canon


def _poly_extend(q, z, o, valid):
    """Multiply coefficient array ``q`` [N, D+1] by (z + o*t) where valid."""
    shifted = jnp.concatenate([jnp.zeros_like(q[:, :1]), q[:, :-1]], axis=1)
    return jnp.where(valid, z * q + o[:, None] * shifted, q)


def _poly_unwind(q, z, o, max_depth: int):
    """Divide q [N, D+1] by (z + o*t); o is the 0/1 indicator [N].

    o == 1: downward recurrence r[k-1] = q[k] - z r[k];
    o == 0: r[k] = q[k] / z (guarded — z == 0 means the dead branch already
    zeroed the polynomial, so 0 is the correct quotient).
    """
    d = max_depth
    r1 = [None] * d
    acc = q[:, d]
    for k in range(d - 1, -1, -1):
        r1[k] = acc
        acc = q[:, k] - z * acc
    r1 = jnp.stack(r1, axis=1)  # [N, D]
    r0 = jnp.where(z > 0.0, q[:, :d] / jnp.maximum(z, 1e-12), 0.0)
    return jnp.where(o[:, None] > 0.5, r1, r0)


def _shap_one_tree(tree: Tree, x: jnp.ndarray, max_depth: int, cat_mask):
    """Exact TreeSHAP (Lundberg et al.) for one padded-heap tree.

    Returns (phi [N, F], expected_value scalar): phi rows satisfy the
    efficiency axiom  sum_f phi[n, f] = margin(x_n) - expected_value.
    """
    n, num_features = x.shape
    d = max_depth
    ctab = _shap_weight_table(d)

    def slot_contrib(slot):
        fs, zs, os_, valids, v_leaf, m, canon = _shap_path_data(
            tree, x, slot, d, cat_mask
        )
        q = jnp.zeros((n, d + 1), jnp.float32).at[:, 0].set(1.0)
        for s in range(d):
            q = _poly_extend(q, zs[s], os_[s], valids[s])
        w = ctab[m]  # [D] permutation weights for this slot's player count
        phi = jnp.zeros((n, num_features), jnp.float32)
        for s in range(d):
            r = _poly_unwind(q, zs[s], os_[s], d)  # [N, D]
            contrib = v_leaf * (os_[s] - zs[s]) * (r @ w)
            contrib = jnp.where(valids[s], contrib, 0.0)
            phi = phi.at[:, fs[s]].add(contrib)
        e_slot = v_leaf
        for s in range(d):
            e_slot = e_slot * jnp.where(valids[s], zs[s], 1.0)
        e_slot = jnp.where(canon, e_slot, 0.0)
        return phi, e_slot

    if d == 0:
        return jnp.zeros((n, num_features), jnp.float32), tree.value[0]

    def slot_step(acc, slot):
        phi_acc, e_acc = acc
        phi, e = slot_contrib(slot)
        return (phi_acc + phi, e_acc + e), None

    (phi_tot, e_tot), _ = jax.lax.scan(
        slot_step,
        (jnp.zeros((n, num_features), jnp.float32), jnp.float32(0.0)),
        jnp.arange(2 ** d, dtype=jnp.int32),
    )
    return phi_tot, e_tot


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_contribs_exact(
    forest: Tree,  # stacked trees: each field [T, heap]
    x: jnp.ndarray,  # [N, F] float32 raw features
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,
    cat_features: tuple = (),
) -> jnp.ndarray:
    """Exact TreeSHAP contributions (xgboost ``pred_contribs`` default).

    Reference surface: ``xgb.Booster.predict(pred_contribs=True)`` passed
    through at ``xgboost_ray/main.py:795-810``. Per tree, each leaf's
    conditional-expectation weight polynomial is built over the path's unique
    features (EXTEND), then each player's Shapley weight is recovered by
    synthetic division (UNWIND); the bias column carries the cover-weighted
    tree expectation, so rows sum exactly to the margin.

    Returns [N, K, F+1] (bias last), trees accumulated with ``lax.scan``.
    """
    n, num_features = x.shape
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, num_features)

    scale = jnp.ones((t,), jnp.float32)
    if tree_weights is not None:
        scale = scale * tree_weights
    if ntree_limit:
        scale = jnp.where(jnp.arange(t) < ntree_limit, scale, 0.0)
    scale = scale / num_parallel_tree
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=jnp.float32) * scale[:, None]  # [T, K]

    def tree_step(acc, args):
        tree, oh = args
        feat_acc, bias_acc = acc
        phi, e_tree = _shap_one_tree(tree, x, max_depth, cat_mask)
        feat_acc = feat_acc + jnp.einsum("nf,k->nkf", phi, oh)
        bias_acc = bias_acc + e_tree * oh
        return (feat_acc, bias_acc), None

    acc0 = (
        jnp.zeros((n, num_outputs, num_features), jnp.float32),
        jnp.zeros((num_outputs,), jnp.float32),
    )
    (feat_part, bias_part), _ = jax.lax.scan(tree_step, acc0, (forest, onehot))
    bias = jnp.broadcast_to(bias_part[None, :, None], (n, num_outputs, 1))
    return jnp.concatenate([feat_part, bias], axis=2)


def _shap_interactions_one_tree(tree: Tree, x: jnp.ndarray, max_depth: int,
                                cat_mask):
    """Exact SHAP interaction values for one tree.

    Returns (phi_mat [N, F, F], phi_bias [N, F], phi_plain [N, F], e_tree):

    * off-diagonal (Lundberg's definition, what xgboost's
      PredictInteractionContributions computes): Phi[i,j] = (phi_j with i
      conditioned present - phi_j with i conditioned absent) / 2, obtained by
      unwinding i from the path polynomial;
    * phi_bias[i] = (E[tree | i present] - E[tree | i absent]) / 2 — the
      feature-bias interaction column xgboost emits;
    * diagonal: Phi[i,i] = phi_i - sum_{j != i} Phi[i,j] - phi_bias[i], so
      each feature row (including its bias entry) sums to phi_i.
    """
    n, num_features = x.shape
    d = max_depth
    ctab = _shap_weight_table(d)

    def slot_contrib(slot):
        fs, zs, os_, valids, v_leaf, m, canon = _shap_path_data(
            tree, x, slot, d, cat_mask
        )
        q = jnp.zeros((n, d + 1), jnp.float32).at[:, 0].set(1.0)
        for s in range(d):
            q = _poly_extend(q, zs[s], os_[s], valids[s])

        w_m = ctab[m]          # weights for m players (plain phi)
        w_m1 = ctab[jnp.maximum(m - 1, 0)]  # weights with player i removed
        phi_mat = jnp.zeros((n, num_features, num_features), jnp.float32)
        phi_bias = jnp.zeros((n, num_features), jnp.float32)
        phi_plain = jnp.zeros((n, num_features), jnp.float32)

        e_slot = v_leaf
        for s in range(d):
            e_slot = e_slot * jnp.where(valids[s], zs[s], 1.0)
        e_slot = jnp.where(canon, e_slot, 0.0)

        for s in range(d):
            r_s = _poly_unwind(q, zs[s], os_[s], d)
            contrib = v_leaf * (os_[s] - zs[s]) * (r_s @ w_m)
            contrib = jnp.where(valids[s], contrib, 0.0)
            phi_plain = phi_plain.at[:, fs[s]].add(contrib)

        for i in range(d):
            # bias interaction: conditional tree expectations differ by the
            # z_i -> o_i swap in the cover product
            prod_rest = jnp.ones((n,), jnp.float32) * v_leaf
            for j in range(d):
                if j != i:
                    prod_rest = prod_rest * jnp.where(valids[j], zs[j], 1.0)
            b_i = 0.5 * (os_[i] - zs[i]) * prod_rest
            b_i = jnp.where(valids[i] & canon, b_i, 0.0)
            phi_bias = phi_bias.at[:, fs[i]].add(b_i)

            # polynomial with player i unwound
            q_i = _poly_unwind(q, zs[i], os_[i], d)
            q_i = jnp.concatenate([q_i, jnp.zeros((n, 1), jnp.float32)], axis=1)
            for j in range(d):
                if j == i:
                    continue
                pair_valid = valids[i] & valids[j]
                r = _poly_unwind(q_i, zs[j], os_[j], d)
                base = (os_[j] - zs[j]) * (r @ w_m1)
                # condition on i present (weight o_i) vs absent (weight z_i)
                delta = 0.5 * v_leaf * base * (os_[i] - zs[i])
                delta = jnp.where(pair_valid, delta, 0.0)
                phi_mat = phi_mat.at[:, fs[i], fs[j]].add(delta)
        return phi_mat, phi_bias, phi_plain, e_slot

    if d == 0:
        z = jnp.zeros((n, num_features, num_features), jnp.float32)
        zf = jnp.zeros((n, num_features), jnp.float32)
        return z, zf, zf, tree.value[0]

    def slot_step(acc, slot):
        mat_a, bias_a, plain_a, e_a = acc
        mat, bias, plain, e = slot_contrib(slot)
        return (mat_a + mat, bias_a + bias, plain_a + plain, e_a + e), None

    acc0 = (
        jnp.zeros((n, num_features, num_features), jnp.float32),
        jnp.zeros((n, num_features), jnp.float32),
        jnp.zeros((n, num_features), jnp.float32),
        jnp.float32(0.0),
    )
    (phi_mat, phi_bias, phi_plain, e_tree), _ = jax.lax.scan(
        slot_step, acc0, jnp.arange(2 ** d, dtype=jnp.int32)
    )
    # diagonal absorbs the remainder so each feature row (with its bias
    # entry) sums to phi_plain
    row_off = phi_mat.sum(axis=2) - jnp.einsum("nii->ni", phi_mat)
    diag = phi_plain - row_off - phi_bias
    eye = jnp.eye(num_features, dtype=jnp.float32)
    phi_mat = phi_mat * (1.0 - eye) + diag[:, :, None] * eye
    return phi_mat, phi_bias, phi_plain, e_tree


@functools.partial(jax.jit, static_argnames=("max_depth", "num_outputs", "num_parallel_tree", "ntree_limit", "cat_features"))
def predict_interactions(
    forest: Tree,
    x: jnp.ndarray,
    max_depth: int,
    num_outputs: int,
    num_parallel_tree: int = 1,
    ntree_limit: int = 0,
    tree_weights: Optional[jnp.ndarray] = None,
    cat_features: tuple = (),
) -> jnp.ndarray:
    """SHAP interaction values (xgboost ``pred_interactions``): [N, K, F+1, F+1].

    Matches xgboost's output contract: Phi[i, bias] = Phi[bias, i] is the
    feature-bias interaction, each feature row sums to that feature's plain
    contribution, the bias-bias cell absorbs the remainder of the tree
    expectation, and the grand total equals the margin.
    """
    n, num_features = x.shape
    t = forest.feature.shape[0]
    cat_mask = _cat_mask_const(cat_features, num_features)

    scale = jnp.ones((t,), jnp.float32)
    if tree_weights is not None:
        scale = scale * tree_weights
    if ntree_limit:
        scale = jnp.where(jnp.arange(t) < ntree_limit, scale, 0.0)
    scale = scale / num_parallel_tree
    cls = (jnp.arange(t) // num_parallel_tree) % num_outputs
    onehot = jax.nn.one_hot(cls, num_outputs, dtype=jnp.float32) * scale[:, None]

    def tree_step(acc, args):
        tree, oh = args
        mat_acc, fbias_acc, e_acc = acc
        phi_mat, phi_bias, _, e_tree = _shap_interactions_one_tree(
            tree, x, max_depth, cat_mask
        )
        mat_acc = mat_acc + jnp.einsum("nfg,k->nkfg", phi_mat, oh)
        fbias_acc = fbias_acc + jnp.einsum("nf,k->nkf", phi_bias, oh)
        e_acc = e_acc + e_tree * oh
        return (mat_acc, fbias_acc, e_acc), None

    acc0 = (
        jnp.zeros((n, num_outputs, num_features, num_features), jnp.float32),
        jnp.zeros((n, num_outputs, num_features), jnp.float32),
        jnp.zeros((num_outputs,), jnp.float32),
    )
    (mat_part, fbias_part, e_part), _ = jax.lax.scan(
        tree_step, acc0, (forest, onehot)
    )
    out = jnp.zeros((n, num_outputs, num_features + 1, num_features + 1), jnp.float32)
    out = out.at[:, :, :num_features, :num_features].set(mat_part)
    out = out.at[:, :, :num_features, num_features].set(fbias_part)
    out = out.at[:, :, num_features, :num_features].set(fbias_part)
    # bias-bias absorbs the remainder of the expectation so the bias row also
    # sums to the plain bias contribution (and the grand total to the margin)
    out = out.at[:, :, num_features, num_features].set(
        jnp.broadcast_to(e_part[None, :], (n, num_outputs))
        - fbias_part.sum(axis=2)
    )
    return out


@functools.partial(jax.jit, static_argnames=("max_depth", "cat_features"))
def predict_leaf_index(
    forest: Tree, x: jnp.ndarray, max_depth: int, cat_features: tuple = ()
) -> jnp.ndarray:
    """Per-tree leaf heap index for each row (xgboost pred_leaf analog). [N, T]."""
    n, num_features = x.shape
    cat_mask = _cat_mask_const(cat_features, num_features)

    def walk(tree):
        idx = jnp.zeros((n,), jnp.int32)
        for _ in range(max_depth):
            f = jnp.clip(tree.feature[idx], 0, num_features - 1)
            xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            go_right = _step_right(tree, idx, xv, f, cat_mask)
            nxt = 2 * idx + 1 + go_right.astype(jnp.int32)
            idx = jnp.where(tree.is_leaf[idx], idx, nxt)
        return idx

    return jax.vmap(walk)(forest).T
